"""Continuous micro-batcher: bounded queue, deadline-aware admission,
bucket padding, backpressure.

Orca-style continuous batching (Yu et al., OSDI '22 — PAPERS.md): the
batch boundary is the scheduling boundary.  The worker takes whatever
is queued the moment the previous micro-batch retires (up to the
engine's largest bucket), so a request arriving mid-computation joins
the *next* dispatch instead of waiting out a fixed batching window —
the compute time itself is the batching window, and occupancy rises
with load instead of being configured.  For single-shot forwards
(mnist, resnet, /predict on the LM families) the request IS the
iteration, so ``ContinuousBatcher`` schedules requests; generative
traffic runs ``TokenContinuousBatcher`` below — Orca's actual
per-TOKEN iteration scheduling over a ``DecodeEngine``'s paged KV
cache, where requests join and leave the running batch at token
boundaries.

Admission is where backpressure lives: a full queue rejects
immediately with a retry-after hint (the HTTP front maps it to 429)
rather than buffering unboundedly — shedding at admission keeps p95
bounded for the requests that ARE admitted, and the queue-depth gauge
plus the latency histogram are exactly the signals the autoscaler's
serving lane scales replicas on.  Requests carry deadlines; one whose
deadline passed while queued is expired, not computed (its caller has
already given up — computing it would only tax its neighbors).

The checkpoint hot-swap moment lives HERE, between batches
(``engine.refresh()``): a micro-batch in flight bound its weights at
dispatch, so no request ever observes mixed-generation outputs.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

import numpy as np

from edl_tpu.serving.engine import DispatchWedgedError, NotReadyError


class QueueFullError(RuntimeError):
    """Admission rejected: the bounded queue is full.  ``retry_after``
    is the backoff hint (seconds) the HTTP front surfaces as a
    Retry-After header."""

    def __init__(self, msg: str, retry_after: float = 0.05):
        super().__init__(msg)
        self.retry_after = retry_after


class DeadlineExceededError(RuntimeError):
    """The request's deadline passed before its batch dispatched."""


class DrainingError(RuntimeError):
    """Admission closed: the replica is draining (graceful shutdown /
    scale-down victim).  DISTINCT from ``QueueFullError`` on purpose —
    the HTTP front maps this to 503 + Retry-After (go to another
    replica; this one is leaving) where queue-full is 429 (back off
    and retry HERE).  ``retry_after`` is the client hint in seconds."""

    def __init__(self, msg: str, retry_after: float = 0.5):
        super().__init__(msg)
        self.retry_after = retry_after


class Ticket:
    """One admitted request's future: resolved by the batcher worker
    with (outputs, meta) or an error."""

    __slots__ = (
        "inputs", "rows", "deadline", "enqueued", "_done",
        "_result", "_error",
    )

    def __init__(self, inputs: Dict[str, np.ndarray], rows: int, deadline: float):
        self.inputs = inputs
        self.rows = rows
        self.deadline = deadline
        self.enqueued = time.monotonic()
        self._done = threading.Event()
        self._result: Optional[tuple] = None
        self._error: Optional[BaseException] = None

    def _resolve(self, outputs, meta) -> None:
        self._result = (outputs, meta)
        self._done.set()

    def _reject(self, err: BaseException) -> None:
        self._error = err
        self._done.set()

    def result(self, timeout: Optional[float] = None) -> tuple:
        """Block for (outputs, meta); raises the worker's error."""
        if not self._done.wait(timeout):
            raise TimeoutError("request still in flight")
        if self._error is not None:
            raise self._error
        return self._result


class ContinuousBatcher:
    """Background worker turning admitted requests into padded-bucket
    forward passes on an ``InferenceEngine``."""

    def __init__(
        self,
        engine,
        queue_limit: int = 256,
        default_deadline_s: float = 2.0,
        chaos=None,
    ):
        self.engine = engine
        self.queue_limit = int(queue_limit)
        self.default_deadline_s = float(default_deadline_s)
        self.chaos = chaos if chaos is not None else engine.chaos
        self._cv = threading.Condition()
        self._queue: deque = deque()
        self._stop = False
        #: admission closed (drain): submit raises DrainingError while
        #: queued + dispatching work runs to completion
        self._draining = False
        #: TICKETS the worker currently holds (popped off the queue
        #: but not yet resolved) — same unit as len(_queue), so
        #: ``in_flight`` counts requests consistently
        self._busy = 0
        self._thread: Optional[threading.Thread] = None
        self.stats = {"batches": 0, "swaps": 0}

        from edl_tpu import telemetry

        reg = telemetry.get_registry()
        self._m_requests = reg.counter("edl_serve_requests_total")
        self._m_batches = reg.counter("edl_serve_batches_total")
        self._m_examples = reg.counter("edl_serve_examples_total")
        self._g_depth = reg.gauge("edl_serve_queue_depth")
        self._m_latency = reg.histogram("edl_serve_latency_seconds")
        self._m_occupancy = reg.histogram("edl_serve_batch_occupancy")

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ContinuousBatcher":
        with self._cv:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop = False
            self._thread = threading.Thread(
                target=self._work, daemon=True, name="edl-serve-batcher"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
        # Nothing queued survives a stop: resolve, don't strand callers.
        with self._cv:
            pending = list(self._queue)
            self._queue.clear()
            self._g_depth.set(0)
        for t in pending:
            self._m_requests.inc(status="error")
            t._reject(RuntimeError("batcher stopped"))

    @property
    def depth(self) -> int:
        with self._cv:
            return len(self._queue)

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def in_flight(self) -> int:
        """Requests admitted but not yet resolved: queued + the batch
        the worker currently holds.  The drain loop polls this to 0."""
        with self._cv:
            return len(self._queue) + self._busy

    def close_admission(self) -> None:
        """Enter drain: every later ``submit`` raises DrainingError
        (503 + Retry-After at the HTTP front, distinct from 429
        queue-full); already-admitted requests keep computing."""
        with self._cv:
            self._draining = True

    # -- admission ----------------------------------------------------------
    def submit(
        self,
        inputs: Dict[str, Any],
        deadline_s: Optional[float] = None,
    ) -> Ticket:
        """Admit one request (1..max_batch rows).  Raises
        ``QueueFullError`` on backpressure, ``DrainingError`` once
        admission closed for a drain, and ``ValueError`` on a schema
        mismatch — all BEFORE the request costs any compute."""
        if self._draining:
            self._m_requests.inc(status="draining")
            raise DrainingError(
                "replica draining: admission closed; retry another "
                "replica"
            )
        arrays, rows = self.engine.coerce_inputs(inputs)
        if rows < 1:
            raise ValueError("empty request (0 rows)")
        if rows > self.engine.max_batch:
            raise ValueError(
                f"request of {rows} rows exceeds max_batch "
                f"{self.engine.max_batch}; split it client-side"
            )
        budget = (
            deadline_s if deadline_s is not None else self.default_deadline_s
        )
        ticket = Ticket(arrays, rows, time.monotonic() + budget)
        with self._cv:
            if self._draining:
                # Re-check under the lock: a drain closing admission
                # concurrently with this submit must not see in-flight
                # grow after it read the count.
                self._m_requests.inc(status="draining")
                raise DrainingError(
                    "replica draining: admission closed; retry another "
                    "replica"
                )
            forced = self.chaos is not None and bool(
                self.chaos.due("serve.queue.full")
            )
            if forced or len(self._queue) >= self.queue_limit:
                # chaos[serve.queue.full] forces this branch so the
                # 429/Retry-After path is testable without a real storm.
                self._m_requests.inc(status="rejected")
                raise QueueFullError(
                    f"admission queue full ({self.queue_limit}); retry",
                    retry_after=max(0.01, budget / 4),
                )
            self._queue.append(ticket)
            self._g_depth.set(len(self._queue))
            self._cv.notify()
        return ticket

    # -- the worker ---------------------------------------------------------
    def _take_batch(self) -> List[Ticket]:
        """Pop whatever is queued up to the largest bucket (continuous
        batching: no artificial wait — the previous batch's compute WAS
        the window), expiring dead requests on the way."""
        taken: List[Ticket] = []
        now = time.monotonic()
        cap = self.engine.max_batch
        rows = 0
        with self._cv:
            while self._queue:
                t = self._queue[0]
                if t.deadline <= now:
                    self._queue.popleft()
                    self._m_requests.inc(status="expired")
                    t._reject(
                        DeadlineExceededError(
                            "deadline passed while queued"
                        )
                    )
                    continue
                if rows + t.rows > cap:
                    break
                self._queue.popleft()
                taken.append(t)
                rows += t.rows
            self._busy = len(taken)
            self._g_depth.set(len(self._queue))
        return taken

    def _work(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stop:
                    self._cv.wait(timeout=0.5)
                if self._stop:
                    return
            # Hot-swap moment: between batches, never mid-batch.  A
            # rejected candidate (torn checkpoint) leaves the current
            # weights serving.  Guarded: even an unexpected swap-path
            # failure (device OOM placing a grown checkpoint, a
            # mismatched tree from a misconfigured trainer) must cost
            # the SWAP, never the worker — a dead worker strands every
            # queued and future request until its timeout.
            try:
                if self.engine.refresh():
                    self.stats["swaps"] += 1
            except Exception:
                import traceback

                traceback.print_exc()
            batch = self._take_batch()
            if not batch:
                continue
            if self.chaos is not None:
                for ev in self.chaos.due("serve.request.slow"):
                    # chaos[serve.request.slow]: a slow dispatch (GC
                    # pause, contended device) inflates the latency
                    # histogram — the p95 signal the serving lane
                    # scales on, under test control.
                    time.sleep(float(ev.arg or 0.05))
            rows = sum(t.rows for t in batch)
            merged = {
                k: np.concatenate([t.inputs[k] for t in batch], axis=0)
                for k in batch[0].inputs
            }
            try:
                outputs, meta = self.engine.predict(merged)
            except BaseException as e:
                for t in batch:
                    self._m_requests.inc(status="error")
                    t._reject(e)
                with self._cv:
                    self._busy = 0
                continue
            self._m_batches.inc()
            self._m_examples.inc(rows)
            self._m_occupancy.observe(rows / meta["bucket"])
            self.stats["batches"] += 1
            now = time.monotonic()
            lo = 0
            for t in batch:
                sl = jax_tree_slice(outputs, lo, lo + t.rows)
                lo += t.rows
                self._m_requests.inc(status="ok")
                self._m_latency.observe(now - t.enqueued)
                t._resolve(sl, dict(meta))
            with self._cv:
                self._busy = 0


def jax_tree_slice(outputs: Dict[str, np.ndarray], lo: int, hi: int):
    """Row-slice every output array (outputs are host numpy by the time
    the batcher splits them back per request)."""
    return {k: v[lo:hi] for k, v in outputs.items()}


# -- per-token continuous batching (the true-Orca path) ----------------------

#: GenerateTicket lifecycle states
_QUEUED, _PREFILLING, _DECODING, _DONE = (
    "queued", "prefilling", "decoding", "done",
)


class GenerateTicket:
    """One admitted generate request: the prompt, its budget, and the
    future its caller blocks on.  ``on_event`` (optional) streams
    incremental events as the worker emits them:

    - ``{"token": id, "i": n}``     — one generated token
    - ``{"restart": True, ...}``    — a hot swap voided prior tokens
      (the sequence re-prefills against the new weights; previously
      streamed tokens are not part of the final output)
    - ``{"done": True, "tokens": [...], ...meta}`` / ``{"error": ...}``
    """

    __slots__ = (
        "prompt", "max_new", "deadline", "eos_id", "enqueued", "on_event",
        "state", "blocks", "table", "length", "last_token", "tokens",
        "restarts", "last_time", "prefilled", "chunks", "first_time",
        "migrated", "reused_blocks", "_done", "_result", "_error",
    )

    def __init__(
        self,
        prompt: np.ndarray,
        max_new: int,
        deadline: float,
        eos_id: Optional[int],
        on_event=None,
    ):
        self.prompt = prompt
        self.max_new = max_new
        self.deadline = deadline
        self.eos_id = eos_id
        self.enqueued = time.monotonic()
        self.on_event = on_event
        self.state = _QUEUED
        #: owned physical block ids (freed the iteration we finish)
        self.blocks: List[int] = []
        self.table: Optional[np.ndarray] = None
        #: written cache positions (prompt + generated so far)
        self.length = 0
        self.last_token = 0
        self.tokens: List[int] = []
        self.restarts = 0
        self.last_time = 0.0
        #: prompt positions already written by prefill chunks (chunked
        #: admission splits the prompt; a hot swap resets this to 0)
        self.prefilled = 0
        #: prefill dispatches this request paid (monolithic = 1 per
        #: prefill; chunked = one per chunk, cumulative over restarts)
        self.chunks = 0
        #: wall time of the FIRST ever emitted token — TTFT spans
        #: enqueue -> first token across ALL chunks (ISSUE 14
        #: satellite), and a restart never moves it
        self.first_time: Optional[float] = None
        #: this sequence was handed to a survivor replica (live KV
        #: migration or cold requeue) — it no longer counts toward the
        #: local drain; its caller's future resolves via the relay
        self.migrated = False
        #: KV blocks claimed from the prefix cache at admission (this
        #: many blocks of prompt were never prefilled here; a restart
        #: resets it alongside ``prefilled``)
        self.reused_blocks = 0
        self._done = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def _event(self, ev: dict) -> None:
        if self.on_event is not None:
            try:
                self.on_event(ev)
            except Exception:
                self.on_event = None  # a dead stream must not kill the worker

    def _finish(self, meta: Dict[str, Any]) -> None:
        self.state = _DONE
        self._result = (list(self.tokens), meta)
        self._event({"done": True, "tokens": list(self.tokens), **meta})
        self._done.set()

    def _reject(self, err: BaseException) -> None:
        self.state = _DONE
        self._error = err
        self._event({"error": str(err)})
        self._done.set()

    def result(self, timeout: Optional[float] = None) -> tuple:
        """Block for (tokens, meta); raises the worker's error."""
        if not self._done.wait(timeout):
            raise TimeoutError("generation still in flight")
        if self._error is not None:
            raise self._error
        return self._result


class TokenContinuousBatcher:
    """Per-TOKEN iteration scheduling over a ``DecodeEngine`` (Orca's
    actual continuity unit, closing PAPERS.md's request-level caveat).

    Each worker iteration:

    1. **swap check** — at the token boundary only.  A newer verified
       checkpoint re-prefills every in-flight sequence against the new
       weights (their partial output is VOID, streamed as a restart
       event): one sequence never mixes weight generations, and the
       generation a finished sequence reports produced every one of
       its tokens.
    2. **join** — queued requests are admitted while decode slots and
       KV blocks last.  With **chunked prefill** (ISSUE 14, the
       default when the model declares a ``chunk_fn``) an admitted
       request enters a FIFO of partially-prefilled sequences and its
       prompt is fed through chunk executables under a per-iteration
       **token budget** — at most ``prefill_token_budget`` prompt
       tokens per iteration ride beside the decode step, so a long
       admission NEVER blocks the token cadence (Sarathi-Serve's
       stall-free posture, PAPERS.md); the sequence joins decode only
       when its last chunk lands (the TTFT moment, measured from
       enqueue across ALL chunks).  With ``chunked_prefill=False``
       each join pays one monolithic bucketed prefill (the PR 13
       posture — kept as the bench interference A/B).
    3. **decode** — ONE token of compute for every active sequence
       (bucketed by count; block tables absorb ragged lengths).
       Finished sequences (EOS / token budget / context cap / past
       deadline) resolve and release their KV blocks the SAME
       iteration — half-prefilled sequences release theirs at expiry
       too.

    Admission semantics carry over from the single-shot batcher
    unchanged: bounded queue -> ``QueueFullError`` (HTTP 429 +
    Retry-After), queued-dead requests expire instead of computing; a
    prompt longer than the context cap raises the engine's typed
    ``PromptTooLongError`` at submit, never mid-chunk.
    """

    def __init__(
        self,
        engine,
        queue_limit: int = 256,
        default_deadline_s: float = 30.0,
        default_max_new: int = 16,
        refresh: bool = True,
        chaos=None,
        chunked_prefill: Optional[bool] = None,
        prefill_token_budget: int = 0,
        prefix_cache: Optional[bool] = None,
    ):
        self.engine = engine
        self.queue_limit = int(queue_limit)
        self.default_deadline_s = float(default_deadline_s)
        self.default_max_new = int(default_max_new)
        #: False = another batcher sharing this engine owns refresh();
        #: this one still observes generation changes and re-prefills
        self.refresh = refresh
        self.chaos = chaos if chaos is not None else engine.chaos
        spec = getattr(engine, "spec", None)
        if chunked_prefill is None:
            chunked_prefill = getattr(spec, "chunk_fn", None) is not None
        elif chunked_prefill and getattr(spec, "chunk_fn", None) is None:
            raise ValueError(
                f"model {engine.model.name!r} declares no chunk_fn; "
                "chunked prefill unavailable"
            )
        self.chunked_prefill = bool(chunked_prefill)
        #: prompt tokens one iteration may spend on prefill chunks
        #: beside its decode step (0 -> the engine's max chunk size);
        #: clamped so every iteration can dispatch at least one block
        self.prefill_token_budget = int(
            prefill_token_budget
            or getattr(engine, "max_chunk_tokens", 0)
            or 64
        )
        #: content-addressed prefix reuse (serving/prefix.py): chunked
        #: mode only — the skip-to-cold offset IS a chunk offset.  On
        #: by default; ``prefix_cache=False`` is the A/B baseline.
        if prefix_cache is None:
            prefix_cache = self.chunked_prefill
        self.prefix = None
        if prefix_cache:
            if not self.chunked_prefill:
                raise ValueError(
                    "prefix_cache requires chunked prefill (the cached "
                    "run's skip offset is a chunk offset)"
                )
            from edl_tpu.serving.prefix import PrefixCache

            self.prefix = PrefixCache(
                engine.pool, engine.block_tokens, chaos=self.chaos
            )
        self._cv = threading.Condition()
        self._queue: deque = deque()
        #: FIFO of admitted, partially-prefilled sequences (chunked
        #: mode): the head is fed chunk-by-chunk under the budget; a
        #: sequence joins ``_active`` when its last chunk lands
        self._prefilling: deque = deque()
        #: running token counters behind ``queued_prefill_tokens``:
        #: queue prompt tokens (mutated under _cv beside every queue
        #: mutation) and the FIFO's remaining tokens (worker-thread
        #: owned, decremented per chunk)
        self._queued_tokens = 0
        self._prefilling_tokens = 0
        self._active: List[GenerateTicket] = []
        #: sequences migrated IN (KV already imported into granted
        #: blocks) awaiting adoption at the next token boundary:
        #: (ticket, weights_step, weights_digest, cache_epoch) entries
        self._adopted: deque = deque()
        self._stop = False
        #: admission closed (drain): submit_generate raises
        #: DrainingError; queued/prefilling/active sequences finish
        self._draining = False
        #: token-boundary freeze handshake (live migration export):
        #: the exporter raises _freeze_req, the worker parks and acks,
        #: _resume releases it
        self._freeze_req = threading.Event()
        self._frozen_ack = threading.Event()
        self._resume = threading.Event()
        #: serializes frozen() callers (a drain export racing a
        #: migration import would otherwise share one ack handshake)
        self._freeze_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._bound_gen = -1
        self._bound_step = -1
        self._bound_digest = -1
        self._bound_epoch = 0  # engine.cache_epoch last observed
        self.stats = {"iterations": 0, "prefills": 0, "swaps": 0,
                      "restarts": 0, "chunks": 0}

        from edl_tpu import telemetry

        reg = telemetry.get_registry()
        self.recorder = telemetry.get_recorder()
        self._m_requests = reg.counter("edl_serve_requests_total")
        self._m_tokens = reg.counter("edl_serve_tokens_total")
        self._m_prefills = reg.counter("edl_serve_prefills_total")
        self._m_iterations = reg.counter(
            "edl_serve_decode_iterations_total"
        )
        self._m_restarts = reg.counter("edl_serve_restarts_total")
        self._g_depth = reg.gauge("edl_serve_decode_queue_depth")
        self._g_active = reg.gauge("edl_serve_active_sequences")
        self._g_kv = reg.gauge("edl_serve_kv_occupancy")
        # tp-aware block accounting: block COUNTS are tp-invariant
        # (tables/free list are host-side), but the bytes one device
        # carries for them shrink 1/tp with the pool's head sharding —
        # per-device bytes are what an HBM budget actually gates.
        self._g_kv_bytes = reg.gauge("edl_serve_kv_used_bytes_per_device")
        self._m_ttft = reg.histogram("edl_serve_ttft_seconds")
        self._m_intertoken = reg.histogram("edl_serve_intertoken_seconds")
        self._m_occupancy = reg.histogram("edl_serve_batch_occupancy")
        self._m_chunks = reg.counter("edl_serve_prefill_chunks_total")
        self._m_prefill_tokens = reg.counter(
            "edl_serve_prefill_tokens_total"
        )
        self._g_prefill_queued = reg.gauge(
            "edl_serve_prefill_queued_tokens"
        )
        self._m_stall = reg.histogram("edl_serve_prefill_stall_seconds")

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "TokenContinuousBatcher":
        with self._cv:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop = False
            self._thread = threading.Thread(
                target=self._work, daemon=True, name="edl-serve-decode"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)

    @property
    def depth(self) -> int:
        with self._cv:
            return len(self._queue)

    @property
    def active_count(self) -> int:
        return len(self._active)

    @property
    def prefilling_count(self) -> int:
        return len(self._prefilling)

    @property
    def queued_prefill_tokens(self) -> int:
        """Prompt tokens still awaiting prefill (the chunk FIFO's
        remaining work + every queued prompt) — the /healthz and
        autoscaler pressure signal for chunked admission.  Running
        counters, not a scan: /healthz threads read two ints (no deque
        iteration racing the worker's mutations), and the worker's
        per-token gauge update costs O(1), not O(queue depth)."""
        return self._queued_tokens + self._prefilling_tokens

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def adopted_count(self) -> int:
        with self._cv:
            return len(self._adopted)

    @property
    def in_flight(self) -> int:
        """Sequences admitted but not yet resolved: queued + mid-
        prefill + the active decode batch + migrated-in sequences
        awaiting adoption.  The drain loop polls this to 0 — a drained
        replica's KV pool is empty by construction (every finish path
        frees its blocks the same iteration)."""
        with self._cv:
            return (
                len(self._queue)
                + len(self._prefilling)
                + len(self._active)
                + len(self._adopted)
            )

    def close_admission(self) -> None:
        """Enter drain: later ``submit_generate`` calls raise
        DrainingError (HTTP 503 + Retry-After); every admitted
        sequence — queued, mid-prefill, decoding — runs to its normal
        finish and frees its KV blocks."""
        with self._cv:
            self._draining = True

    # -- live KV sequence migration -----------------------------------------
    @contextmanager
    def frozen(self):
        """Park the worker at a token boundary and hold it there while
        the migration exporter reads pool device buffers and batch
        state — no donated dispatch can invalidate either until the
        block exits.  The worker resumes even if the body raises; if
        the worker isn't running the state is already still."""
        with self._freeze_lock:
            alive = self._thread is not None and self._thread.is_alive()
            if not alive:
                yield
                return
            self._resume.clear()
            self._frozen_ack.clear()
            self._freeze_req.set()
            with self._cv:
                self._cv.notify_all()
            self._frozen_ack.wait(timeout=30.0)
            try:
                yield
            finally:
                self._freeze_req.clear()
                self._resume.set()

    def detach(self, t: GenerateTicket) -> None:
        """Remove a decoding sequence from the active batch and free
        its blocks (its K/V is already snapshotted host-side).  Caller
        must hold the worker frozen."""
        if t in self._active:
            self._active.remove(t)
        self._free_blocks(t)
        t.migrated = True

    def take_cold(self) -> List[GenerateTicket]:
        """Detach every queued and half-prefilled sequence for COLD
        handoff to a survivor: they streamed nothing, so a requeue on
        the dest re-prefills the prompt with no restart event and no
        claim on the local drain budget.  Caller must hold the worker
        frozen."""
        out: List[GenerateTicket] = []
        with self._cv:
            while self._queue:
                t = self._queue.popleft()
                self._queued_tokens -= int(t.prompt.shape[0])
                out.append(t)
            self._g_depth.set(0)
        while self._prefilling:
            t = self._prefilling.popleft()
            self._prefilling_tokens -= int(t.prompt.shape[0]) - t.prefilled
            self._free_blocks(t)
            t.prefilled = 0
            t.reused_blocks = 0
            t.state = _QUEUED
            out.append(t)
        for t in out:
            t.migrated = True
        return out

    def readmit(self, t: GenerateTicket) -> None:
        """Fallback ladder's LAST rung: every survivor path failed, so
        the sequence comes back to the local queue and the drain waits
        it out (the PR 15 posture).  Streamed tokens are void — it
        re-prefills under the local weights."""
        t.migrated = False
        if t.tokens:
            t.restarts += 1
            self.stats["restarts"] += 1
            self._m_restarts.inc()
            t._event({"restart": True, "reason": "migration failed"})
        t.state = _QUEUED
        t.tokens = []
        t.length = 0
        t.last_token = 0
        t.prefilled = 0
        t.reused_blocks = 0
        with self._cv:
            self._queue.appendleft(t)
            self._queued_tokens += int(t.prompt.shape[0])
            self._g_depth.set(len(self._queue))
            self._cv.notify()

    def adopt(
        self,
        t: GenerateTicket,
        weights_step: int,
        weights_digest: int,
        cache_epoch: int,
    ) -> None:
        """Hand a migrated-in sequence (K/V already imported into its
        granted pool blocks) to the worker for adoption at the next
        token boundary.  ``weights_step``/``weights_digest`` name the
        checkpoint the K/V was produced under; the worker re-checks
        them at adoption and routes any skew to a cold re-prefill.
        Runs on the migration receiver's thread."""
        with self._cv:
            if self._stop:
                raise RuntimeError("batcher stopped")
            self._adopted.append(
                (t, int(weights_step), int(weights_digest), int(cache_epoch))
            )
            self._cv.notify()

    def _adopt_pending(self, w) -> int:
        """Place migrated-in sequences into the active decode batch.
        The generation-key check happens HERE, at the token boundary:
        if a hot swap or pool rebuild landed between the import grant
        and adoption, the imported cache is unusable — the sequence
        re-prefills cold (a restart event, never a mixed-generation
        token)."""
        adopted = 0
        while True:
            with self._cv:
                if not self._adopted:
                    return adopted
                t, step, digest, epoch = self._adopted.popleft()
            stale = (
                step != w.step
                or digest != w.digest
                or epoch != getattr(self.engine, "cache_epoch", 0)
            )
            if stale or len(self._active) >= self.engine.max_seqs:
                self._free_blocks(t)
                t.state = _QUEUED
                t.tokens = []
                t.length = 0
                t.last_token = 0
                t.prefilled = 0
                t.reused_blocks = 0
                t.restarts += 1
                t._event(
                    {
                        "restart": True,
                        "weights_generation": w.generation,
                        "weights_step": w.step,
                    }
                )
                self.stats["restarts"] += 1
                self._m_restarts.inc()
                with self._cv:
                    self._queue.appendleft(t)
                    self._queued_tokens += int(t.prompt.shape[0])
                    self._g_depth.set(len(self._queue))
                continue
            t.state = _DECODING
            t.last_time = time.monotonic()
            self._active.append(t)
            adopted += 1
            if self._seq_finished(t):
                self._finish(t)

    # -- admission ----------------------------------------------------------
    def submit_generate(
        self,
        inputs: Dict[str, Any],
        max_new_tokens: Optional[int] = None,
        deadline_s: Optional[float] = None,
        eos_id: Optional[int] = None,
        on_event=None,
    ) -> GenerateTicket:
        """Admit one autoregressive request (a single prompt row).
        Raises ``QueueFullError`` on backpressure, ``DrainingError``
        once admission closed for a drain, and ``ValueError`` on a
        schema violation — all before any compute."""
        if self._draining:
            self._m_requests.inc(status="draining")
            raise DrainingError(
                "replica draining: admission closed; retry another "
                "replica"
            )
        prompt = self.engine.coerce_prompt(inputs)
        max_new = int(max_new_tokens or self.default_max_new)
        if max_new < 1:
            raise ValueError(f"max_new_tokens {max_new} < 1")
        budget = (
            deadline_s if deadline_s is not None else self.default_deadline_s
        )
        ticket = GenerateTicket(
            prompt,
            max_new,
            time.monotonic() + budget,
            None if eos_id is None else int(eos_id),
            on_event=on_event,
        )
        with self._cv:
            if self._draining:
                # Re-check under the lock (see ContinuousBatcher.submit)
                self._m_requests.inc(status="draining")
                raise DrainingError(
                    "replica draining: admission closed; retry another "
                    "replica"
                )
            forced = self.chaos is not None and bool(
                self.chaos.due("serve.queue.full")
            )
            if forced or len(self._queue) >= self.queue_limit:
                self._m_requests.inc(status="rejected")
                raise QueueFullError(
                    f"admission queue full ({self.queue_limit}); retry",
                    retry_after=max(0.01, budget / 4),
                )
            self._queue.append(ticket)
            self._queued_tokens += int(prompt.shape[0])
            self._g_depth.set(len(self._queue))
            self._cv.notify()
        return ticket

    # -- worker internals ---------------------------------------------------
    def _free_blocks(self, t: GenerateTicket) -> None:
        if t.blocks:
            self.engine.pool.free(t.blocks)
            t.blocks = []
        t.table = None

    def _finish(self, t: GenerateTicket, status: str = "ok") -> None:
        """Resolve + release KV blocks (the SAME iteration the final
        token was emitted — slot reuse is what keeps occupancy high)."""
        self._free_blocks(t)
        if t in self._active:
            self._active.remove(t)
        self._m_requests.inc(status=status)
        w_gen = self._bound_gen
        t._finish(
            {
                "weights_step": self._bound_step,
                "weights_generation": w_gen,
                "restarts": t.restarts,
                "prompt_tokens": int(t.prompt.shape[0]),
                "prefill_chunks": t.chunks,
                "reused_blocks": t.reused_blocks,
                "ttft_s": (
                    round(t.first_time - t.enqueued, 6)
                    if t.first_time is not None
                    else None
                ),
            }
        )

    def _expire(self, t: GenerateTicket) -> None:
        self._free_blocks(t)
        if t in self._active:
            self._active.remove(t)
        self._m_requests.inc(status="expired")
        t._reject(DeadlineExceededError("deadline passed mid-generation"))

    def _restart_active(self, new_gen: int, new_step: int) -> None:
        """A hot swap landed: every in-flight sequence re-prefills
        against the new weights.  Its emitted-so-far tokens are VOID
        (streamed as a restart event) — the alternative, continuing
        the old prefix under new weights, would mix generations within
        one sequence, which is exactly what the generation-keyed
        contract forbids."""
        restarted = list(self._active)
        self._active = []
        # Half-prefilled sequences restart their chunking from ZERO
        # too: their cache holds old-generation K/V.  They streamed no
        # tokens, so no restart event and no restart count — requeue
        # with progress reset is the whole story.
        rewound = list(self._prefilling)
        self._prefilling.clear()
        self._prefilling_tokens = 0
        with self._cv:
            for t in reversed(rewound):
                self._free_blocks(t)
                t.state = _QUEUED
                t.prefilled = 0
                t.reused_blocks = 0
                self._queue.appendleft(t)
                self._queued_tokens += int(t.prompt.shape[0])
            for t in reversed(restarted):
                self._free_blocks(t)
                t.state = _QUEUED
                t.tokens = []
                t.length = 0
                t.last_token = 0
                t.prefilled = 0
                t.reused_blocks = 0
                t.restarts += 1
                t._event(
                    {
                        "restart": True,
                        "weights_generation": new_gen,
                        "weights_step": new_step,
                    }
                )
                self._queue.appendleft(t)  # keep arrival order
                self._queued_tokens += int(t.prompt.shape[0])
            self._g_depth.set(len(self._queue))
        if restarted:
            self.stats["restarts"] += len(restarted)
            self._m_restarts.inc(len(restarted))
            self.recorder.record(
                "serve.restart",
                {
                    "sequences": len(restarted),
                    "to_generation": new_gen,
                    "to_step": new_step,
                },
                step=max(0, new_step),
            )

    def _admit(self, weights) -> int:
        """Token-boundary JOIN: pop queued requests while decode slots
        and KV blocks last; each pays its own bucketed prefill.
        Returns how many sequences joined."""
        bt = self.engine.block_tokens
        joined = 0
        while len(self._active) < self.engine.max_seqs:
            with self._cv:
                if not self._queue:
                    return joined
                t = self._queue[0]
                now = time.monotonic()
                if t.deadline <= now:
                    self._queue.popleft()
                    self._queued_tokens -= int(t.prompt.shape[0])
                    self._g_depth.set(len(self._queue))
                    self._m_requests.inc(status="expired")
                    t._reject(
                        DeadlineExceededError("deadline passed while queued")
                    )
                    continue
                plen = int(t.prompt.shape[0])
                need = self.engine.prompt_bucket_for(plen) // bt
                blocks = self.engine.pool.alloc(need)
                if blocks is None:
                    return joined  # KV pressure: no more joins now
                self._queue.popleft()
                self._queued_tokens -= plen
                self._g_depth.set(len(self._queue))
            t.blocks = blocks
            t.table = np.zeros(self.engine.blocks_per_seq, np.int32)
            t.table[: len(blocks)] = blocks
            try:
                first = self.engine.prefill(weights, t.prompt, t.table)
            except DispatchWedgedError:
                # Wedged dispatch (watchdog): RECOVERABLE — the engine
                # already rebuilt the pools + bumped cache_epoch.  The
                # request survives: requeue it at the front (arrival
                # order kept) and stop joining; the worker loop's
                # epoch check re-prefills everything next iteration.
                self._free_blocks(t)
                with self._cv:
                    t.state = _QUEUED
                    self._queue.appendleft(t)
                    self._queued_tokens += int(t.prompt.shape[0])
                    self._g_depth.set(len(self._queue))
                return joined
            except BaseException as e:
                self._free_blocks(t)
                self._m_requests.inc(status="error")
                t._reject(e)
                continue
            t.chunks += 1
            self._join_decode(t, first, plen, weights)
            joined += 1
        return joined

    def _join_decode(
        self, t: GenerateTicket, first: int, plen: int, weights
    ) -> None:
        """The TTFT moment: a fully-prefilled sequence emits its first
        token and joins the running decode batch.  Shared by monolithic
        join and the final chunk of a chunked prefill — TTFT is
        observed from ``enqueued`` either way (never from the last
        chunk's dispatch)."""
        self.stats["prefills"] += 1
        self._m_prefills.inc()
        now = time.monotonic()
        if t.first_time is None:
            # TTFT observes ONCE per request, enqueue -> first EVER
            # token (the catalog contract) — a hot-swap restart
            # re-joins here but must not inject a second, inflated
            # sample.
            self._m_ttft.observe(now - t.enqueued)
            t.first_time = now
        t.state = _DECODING
        t.length = plen
        t.last_token = first
        t.last_time = now
        t.tokens.append(first)
        # The FIRST token of a (re)started sequence names the weights
        # that produced it: a stream relay (the router's /generate
        # re-drive) decides resume-vs-restart off this stamp — the
        # generation-purity rule made visible at the stream surface.
        t._event({
            "token": first,
            "i": 0,
            "weights_step": weights.step,
            "weights_generation": weights.generation,
        })
        self._m_tokens.inc()
        self._active.append(t)
        if self._seq_finished(t):
            self._finish(t)

    def _admit_chunked(self) -> int:
        """Chunked-mode JOIN: pop queued requests into the prefill
        FIFO while decode slots last (a prefilling sequence holds a
        slot — it will join decode).  KV blocks are taken per CHUNK,
        not up front, so admission itself is instant."""
        joined = 0
        while (
            len(self._active) + len(self._prefilling)
            < self.engine.max_seqs
        ):
            with self._cv:
                if not self._queue:
                    return joined
                t = self._queue[0]
                now = time.monotonic()
                if t.deadline <= now:
                    self._queue.popleft()
                    self._queued_tokens -= int(t.prompt.shape[0])
                    self._g_depth.set(len(self._queue))
                    self._m_requests.inc(status="expired")
                    t._reject(
                        DeadlineExceededError("deadline passed while queued")
                    )
                    continue
                self._queue.popleft()
                self._queued_tokens -= int(t.prompt.shape[0])
                self._g_depth.set(len(self._queue))
            t.state = _PREFILLING
            if t.table is None:
                t.table = np.zeros(self.engine.blocks_per_seq, np.int32)
            if (
                self.prefix is not None
                and t.prefilled == 0
                and not t.blocks
            ):
                run, skip = self.prefix.claim(t.prompt)
                if skip:
                    # Shared-prefix hit: seed the run/table with the
                    # claimed (refcounted, read-only) blocks and skip
                    # the FIFO straight to the first cold block.  The
                    # claimer never writes these blocks — all its
                    # writes land at positions >= skip, in blocks the
                    # prefill loop allocates privately.
                    t.blocks = list(run)
                    t.table[: len(run)] = run
                    t.prefilled = skip
                    t.reused_blocks = len(run)
            self._prefilling_tokens += int(t.prompt.shape[0]) - t.prefilled
            self._prefilling.append(t)
            joined += 1
        return joined

    def _prefill_iteration(self, weights) -> int:
        """Feed the prefill FIFO's head at most ``prefill_token_budget``
        prompt tokens of chunk dispatches (FIFO: a sequence's chunks
        stay in admission order; the head finishes before the next
        starts).  Non-final chunks are block-aligned so every chunk's
        offset stays block-aligned; the final chunk pads to its bucket
        and emits the first token (the sequence joins decode).
        Returns how many chunks dispatched."""
        eng = self.engine
        bt = eng.block_tokens
        budget = max(self.prefill_token_budget, bt)
        epoch0 = getattr(eng, "cache_epoch", 0)
        dispatched = 0
        while budget > 0 and self._prefilling:
            t = self._prefilling[0]
            now = time.monotonic()
            if t.deadline <= now:
                # Expiry frees a half-prefilled sequence's blocks too.
                self._prefilling.popleft()
                self._prefilling_tokens -= (
                    int(t.prompt.shape[0]) - t.prefilled
                )
                self._free_blocks(t)
                self._m_requests.inc(status="expired")
                t._reject(
                    DeadlineExceededError("deadline passed mid-prefill")
                )
                continue
            plen = int(t.prompt.shape[0])
            rem = plen - t.prefilled
            # Cap the chunk so its PADDED bucket still fits the context
            # window: near the window's end, chunk_bucket_for(rem)
            # could otherwise overshoot max_context and overflow the
            # block table (offset is block-aligned and < max_context,
            # so at least one block of room always exists).
            room = eng.max_context - t.prefilled
            cap = bt
            for c in eng.chunk_buckets:
                if c <= room:
                    cap = c
            clen = min(rem, cap, budget)
            if clen < rem:
                clen = (clen // bt) * bt
                if clen == 0:
                    break  # budget slice under one block: next iteration
            bucket = eng.chunk_bucket_for(clen)
            need = (t.prefilled + bucket) // bt - len(t.blocks)
            if need > 0:
                blocks = eng.pool.alloc(need)
                if blocks is None:
                    break  # KV pressure: the FIFO head waits its turn
                for b in blocks:
                    t.table[len(t.blocks)] = b
                    t.blocks.append(b)
            try:
                first = eng.prefill_chunk(
                    weights,
                    t.prompt[t.prefilled : t.prefilled + clen],
                    t.prefilled,
                    t.table,
                )
            except DispatchWedgedError:
                # Wedged chunk dispatch: recoverable.  Leave the
                # sequence at the FIFO head — the epoch rewind next
                # iteration frees its blocks, resets its progress, and
                # requeues it (no reject: the request survives).
                break
            except BaseException as e:
                self._prefilling.popleft()
                self._prefilling_tokens -= plen - t.prefilled
                self._free_blocks(t)
                self._m_requests.inc(status="error")
                t._reject(e)
                if getattr(eng, "cache_epoch", 0) != epoch0:
                    # The failed dispatch rebuilt the (donated) pools:
                    # every other live sequence's cached K/V is gone.
                    # Stop dispatching — the worker loop's epoch check
                    # rewinds the FIFO and the active batch next
                    # iteration.
                    break
                continue
            t.prefilled += clen
            t.chunks += 1
            self._prefilling_tokens -= clen
            budget -= clen
            dispatched += 1
            self.stats["chunks"] += 1
            self._m_chunks.inc()
            self._m_prefill_tokens.inc(clen)
            if t.prefilled >= plen:
                self._prefilling.popleft()
                if self.prefix is not None:
                    # Publish the fully-filled prompt blocks into the
                    # prefix index (the trailing partial block stays
                    # private).  This sequence's own refcount keeps
                    # them alive while it decodes; at refcount 0 they
                    # park on the pool's cached LRU for reuse.
                    self.prefix.publish(t.prompt, t.blocks)
                self._join_decode(t, first, plen, weights)
        return dispatched

    def _seq_finished(self, t: GenerateTicket) -> bool:
        if t.eos_id is not None and t.tokens and t.tokens[-1] == t.eos_id:
            return True
        if len(t.tokens) >= t.max_new:
            return True
        # context cap: position t.length (the next write) must exist,
        # i.e. continue while t.length <= max_context - 1
        return t.length >= self.engine.max_context

    def _decode_iteration(self, weights) -> int:
        """ONE token for every active sequence.  Returns how many
        sequences actually decoded."""
        now = time.monotonic()
        for t in list(self._active):
            if t.deadline <= now:
                self._expire(t)
        if not self._active:
            return 0
        bt = self.engine.block_tokens
        ready: List[GenerateTicket] = []
        for t in self._active:
            bi = t.length // bt
            if bi >= len(t.blocks):
                blk = self.engine.pool.alloc(1)
                if blk is None:
                    continue  # KV pressure: this seq skips one iteration
                t.blocks.append(blk[0])
                t.table[bi] = blk[0]
            ready.append(t)
        if not ready:
            return 0
        if self.chaos is not None:
            for ev in self.chaos.due("serve.request.slow"):
                # chaos[serve.request.slow]: a slow decode iteration
                # inflates TTFT/inter-token — the signals the serving
                # lane scales on, under test control.
                time.sleep(float(ev.arg or 0.05))
        bucket = self.engine.decode_bucket_for(len(ready))
        tokens = np.zeros(bucket, np.int32)
        lengths = np.zeros(bucket, np.int32)
        tables = np.zeros(
            (bucket, self.engine.blocks_per_seq), np.int32
        )  # padding rows: trash block, length 0
        for i, t in enumerate(ready):
            tokens[i] = t.last_token
            lengths[i] = t.length
            tables[i] = t.table
        try:
            ids = self.engine.decode_step(weights, tokens, lengths, tables)
        except DispatchWedgedError:
            # Wedged decode dispatch: recoverable — the sequences stay
            # ACTIVE (nothing is rejected); the worker loop's next
            # epoch check sees the rebuilt pool and re-prefills every
            # one of them against the fresh cache.  A genuine compute
            # error (below) still rejects.
            return 0
        except BaseException as e:
            for t in ready:
                if t in self._active:
                    self._active.remove(t)
                self._free_blocks(t)
                self._m_requests.inc(status="error")
                t._reject(e)
            return 0
        self.stats["iterations"] += 1
        self._m_iterations.inc()
        self._m_tokens.inc(len(ready))
        self._m_occupancy.observe(len(ready) / bucket)
        now = time.monotonic()
        for i, t in enumerate(ready):
            tok = int(ids[i])
            t.length += 1
            t.last_token = tok
            t.tokens.append(tok)
            self._m_intertoken.observe(now - t.last_time)
            t.last_time = now
            ev = {"token": tok, "i": len(t.tokens) - 1}
            if ev["i"] == 0:
                # chunked admissions emit their first token here, not
                # in _admit — same purity stamp (see _admit)
                ev["weights_step"] = weights.step
                ev["weights_generation"] = weights.generation
            t._event(ev)
            if self._seq_finished(t):
                self._finish(t)
        return len(ready)

    def _work(self) -> None:
        while True:
            with self._cv:
                while (
                    not self._queue
                    and not self._active
                    and not self._prefilling
                    and not self._adopted
                    and not self._stop
                    and not self._freeze_req.is_set()
                ):
                    self._cv.wait(timeout=0.5)
                if self._stop:
                    queued = list(self._queue)
                    self._queue.clear()
                    self._queued_tokens = 0
                    self._g_depth.set(0)
                    adopted = [e[0] for e in self._adopted]
                    self._adopted.clear()
                    break
            if self._freeze_req.is_set():
                # Token-boundary FREEZE (live migration): the exporter
                # owns the pool buffers and batch state until resume —
                # parking here is what makes the device->host KV
                # gather safe against the next donated dispatch.
                self._frozen_ack.set()
                self._resume.wait(timeout=60.0)
                self._frozen_ack.clear()
                continue
            # 1. swap check — at the token boundary only.  Guarded:
            # a swap-path failure costs the swap, never the worker.
            try:
                if self.refresh and self.engine.refresh():
                    self.stats["swaps"] += 1
            except Exception:
                import traceback

                traceback.print_exc()
            w = self.engine.current_weights()
            if w is None:
                # No verified checkpoint yet: requests cannot serve.
                with self._cv:
                    queued = list(self._queue)
                    self._queue.clear()
                    self._queued_tokens = 0
                    self._g_depth.set(0)
                for t in queued:
                    self._m_requests.inc(status="error")
                    t._reject(NotReadyError("no verified checkpoint loaded"))
                continue
            epoch = getattr(self.engine, "cache_epoch", 0)
            if w.generation != self._bound_gen or epoch != self._bound_epoch:
                if self.prefix is not None:
                    # Rekey BEFORE the restart frees any blocks: the
                    # index drops atomically, published marks clear,
                    # and no admission under the new weights can ever
                    # claim a block filled by the old ones.
                    self.prefix.rekey((w.generation, epoch))
                if self._bound_gen >= 0:
                    # A swap (new generation) or a rebuilt pool (new
                    # cache epoch after a failed donated dispatch):
                    # either way the live caches are unusable — every
                    # in-flight sequence re-prefills.
                    self._restart_active(w.generation, w.step)
                self._bound_gen = w.generation
                self._bound_step = w.step
                self._bound_digest = w.digest
                self._bound_epoch = epoch
            if self.prefix is not None and self.chaos is not None:
                # chaos[serve.prefix.evicted]: force LRU evictions of
                # cached prefix blocks as if allocation pressure hit.
                self.prefix.chaos_tick()
            # 1b. adopt migrated-in sequences (generation-key checked
            # against the weights just bound — skew re-prefills cold).
            adopted_work = self._adopt_pending(w) if self._adopted else 0
            # 2. token-boundary join + budgeted prefill work;
            # 3. one decode iteration for the active batch.  The time
            # admission work holds up an already-running batch is the
            # STALL the chunked scheduler exists to bound — measured
            # here, per iteration, only when both sides were live.
            had_active = bool(self._active)
            t_pre = time.monotonic()
            if self.chunked_prefill:
                progress = self._admit_chunked()
                prefill_work = self._prefill_iteration(w)
            else:
                progress = prefill_work = self._admit(w)
            pre_dt = time.monotonic() - t_pre
            if had_active and prefill_work:
                self._m_stall.observe(pre_dt)
            progress += prefill_work if self.chunked_prefill else 0
            if getattr(self.engine, "cache_epoch", 0) != epoch:
                # A failed (donated) dispatch during admission rebuilt
                # the pools: the active batch's cached K/V is zeroed,
                # so decoding it now would emit garbage — and a
                # sequence finishing on that garbage token would
                # resolve WRONG before the next iteration's epoch
                # check could rewind it.  Skip straight to the rewind.
                continue
            progress += adopted_work
            progress += self._decode_iteration(w)
            self._g_active.set(len(self._active))
            self._g_kv.set(self.engine.pool.occupancy())
            self._g_kv_bytes.set(
                self.engine.pool.used_blocks
                * (
                    self.engine.kv_pool_bytes_per_device()
                    // self.engine.pool.num_blocks
                )
            )
            self._g_prefill_queued.set(self.queued_prefill_tokens)
            if not progress and (
                self._active or self._queue or self._prefilling
            ):
                # Every live sequence is stalled (KV-block exhaustion)
                # and nobody could join: nothing can change until a
                # deadline expires or blocks free, so don't busy-spin.
                time.sleep(0.01)
        # stopped: nothing queued, adopted, prefilling or active
        # survives.
        for t in queued + adopted + list(self._prefilling) + list(self._active):
            self._free_blocks(t)
            self._m_requests.inc(status="error")
            t._reject(RuntimeError("batcher stopped"))
        self._prefilling.clear()
        self._prefilling_tokens = 0
        self._active = []
        self._g_active.set(0)
