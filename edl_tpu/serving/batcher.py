"""Continuous micro-batcher: bounded queue, deadline-aware admission,
bucket padding, backpressure.

Orca-style continuous batching (Yu et al., OSDI '22 — PAPERS.md): the
batch boundary is the scheduling boundary.  The worker takes whatever
is queued the moment the previous micro-batch retires (up to the
engine's largest bucket), so a request arriving mid-computation joins
the *next* dispatch instead of waiting out a fixed batching window —
the compute time itself is the batching window, and occupancy rises
with load instead of being configured.  (Our unit of continuity is the
request/forward pass, not Orca's per-token iteration: the model zoo's
forwards are single-shot, so "iteration-level" and "request-level"
coincide.)

Admission is where backpressure lives: a full queue rejects
immediately with a retry-after hint (the HTTP front maps it to 429)
rather than buffering unboundedly — shedding at admission keeps p95
bounded for the requests that ARE admitted, and the queue-depth gauge
plus the latency histogram are exactly the signals the autoscaler's
serving lane scales replicas on.  Requests carry deadlines; one whose
deadline passed while queued is expired, not computed (its caller has
already given up — computing it would only tax its neighbors).

The checkpoint hot-swap moment lives HERE, between batches
(``engine.refresh()``): a micro-batch in flight bound its weights at
dispatch, so no request ever observes mixed-generation outputs.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np


class QueueFullError(RuntimeError):
    """Admission rejected: the bounded queue is full.  ``retry_after``
    is the backoff hint (seconds) the HTTP front surfaces as a
    Retry-After header."""

    def __init__(self, msg: str, retry_after: float = 0.05):
        super().__init__(msg)
        self.retry_after = retry_after


class DeadlineExceededError(RuntimeError):
    """The request's deadline passed before its batch dispatched."""


class Ticket:
    """One admitted request's future: resolved by the batcher worker
    with (outputs, meta) or an error."""

    __slots__ = (
        "inputs", "rows", "deadline", "enqueued", "_done",
        "_result", "_error",
    )

    def __init__(self, inputs: Dict[str, np.ndarray], rows: int, deadline: float):
        self.inputs = inputs
        self.rows = rows
        self.deadline = deadline
        self.enqueued = time.monotonic()
        self._done = threading.Event()
        self._result: Optional[tuple] = None
        self._error: Optional[BaseException] = None

    def _resolve(self, outputs, meta) -> None:
        self._result = (outputs, meta)
        self._done.set()

    def _reject(self, err: BaseException) -> None:
        self._error = err
        self._done.set()

    def result(self, timeout: Optional[float] = None) -> tuple:
        """Block for (outputs, meta); raises the worker's error."""
        if not self._done.wait(timeout):
            raise TimeoutError("request still in flight")
        if self._error is not None:
            raise self._error
        return self._result


class ContinuousBatcher:
    """Background worker turning admitted requests into padded-bucket
    forward passes on an ``InferenceEngine``."""

    def __init__(
        self,
        engine,
        queue_limit: int = 256,
        default_deadline_s: float = 2.0,
        chaos=None,
    ):
        self.engine = engine
        self.queue_limit = int(queue_limit)
        self.default_deadline_s = float(default_deadline_s)
        self.chaos = chaos if chaos is not None else engine.chaos
        self._cv = threading.Condition()
        self._queue: deque = deque()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.stats = {"batches": 0, "swaps": 0}

        from edl_tpu import telemetry

        reg = telemetry.get_registry()
        self._m_requests = reg.counter("edl_serve_requests_total")
        self._m_batches = reg.counter("edl_serve_batches_total")
        self._m_examples = reg.counter("edl_serve_examples_total")
        self._g_depth = reg.gauge("edl_serve_queue_depth")
        self._m_latency = reg.histogram("edl_serve_latency_seconds")
        self._m_occupancy = reg.histogram("edl_serve_batch_occupancy")

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "ContinuousBatcher":
        with self._cv:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop = False
            self._thread = threading.Thread(
                target=self._work, daemon=True, name="edl-serve-batcher"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
        # Nothing queued survives a stop: resolve, don't strand callers.
        with self._cv:
            pending = list(self._queue)
            self._queue.clear()
            self._g_depth.set(0)
        for t in pending:
            self._m_requests.inc(status="error")
            t._reject(RuntimeError("batcher stopped"))

    @property
    def depth(self) -> int:
        with self._cv:
            return len(self._queue)

    # -- admission ----------------------------------------------------------
    def submit(
        self,
        inputs: Dict[str, Any],
        deadline_s: Optional[float] = None,
    ) -> Ticket:
        """Admit one request (1..max_batch rows).  Raises
        ``QueueFullError`` on backpressure and ``ValueError`` on a
        schema mismatch — both BEFORE the request costs any compute."""
        arrays, rows = self.engine.coerce_inputs(inputs)
        if rows < 1:
            raise ValueError("empty request (0 rows)")
        if rows > self.engine.max_batch:
            raise ValueError(
                f"request of {rows} rows exceeds max_batch "
                f"{self.engine.max_batch}; split it client-side"
            )
        budget = (
            deadline_s if deadline_s is not None else self.default_deadline_s
        )
        ticket = Ticket(arrays, rows, time.monotonic() + budget)
        with self._cv:
            forced = self.chaos is not None and bool(
                self.chaos.due("serve.queue.full")
            )
            if forced or len(self._queue) >= self.queue_limit:
                # chaos[serve.queue.full] forces this branch so the
                # 429/Retry-After path is testable without a real storm.
                self._m_requests.inc(status="rejected")
                raise QueueFullError(
                    f"admission queue full ({self.queue_limit}); retry",
                    retry_after=max(0.01, budget / 4),
                )
            self._queue.append(ticket)
            self._g_depth.set(len(self._queue))
            self._cv.notify()
        return ticket

    # -- the worker ---------------------------------------------------------
    def _take_batch(self) -> List[Ticket]:
        """Pop whatever is queued up to the largest bucket (continuous
        batching: no artificial wait — the previous batch's compute WAS
        the window), expiring dead requests on the way."""
        taken: List[Ticket] = []
        now = time.monotonic()
        cap = self.engine.max_batch
        rows = 0
        with self._cv:
            while self._queue:
                t = self._queue[0]
                if t.deadline <= now:
                    self._queue.popleft()
                    self._m_requests.inc(status="expired")
                    t._reject(
                        DeadlineExceededError(
                            "deadline passed while queued"
                        )
                    )
                    continue
                if rows + t.rows > cap:
                    break
                self._queue.popleft()
                taken.append(t)
                rows += t.rows
            self._g_depth.set(len(self._queue))
        return taken

    def _work(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stop:
                    self._cv.wait(timeout=0.5)
                if self._stop:
                    return
            # Hot-swap moment: between batches, never mid-batch.  A
            # rejected candidate (torn checkpoint) leaves the current
            # weights serving.  Guarded: even an unexpected swap-path
            # failure (device OOM placing a grown checkpoint, a
            # mismatched tree from a misconfigured trainer) must cost
            # the SWAP, never the worker — a dead worker strands every
            # queued and future request until its timeout.
            try:
                if self.engine.refresh():
                    self.stats["swaps"] += 1
            except Exception:
                import traceback

                traceback.print_exc()
            batch = self._take_batch()
            if not batch:
                continue
            if self.chaos is not None:
                for ev in self.chaos.due("serve.request.slow"):
                    # chaos[serve.request.slow]: a slow dispatch (GC
                    # pause, contended device) inflates the latency
                    # histogram — the p95 signal the serving lane
                    # scales on, under test control.
                    time.sleep(float(ev.arg or 0.05))
            rows = sum(t.rows for t in batch)
            merged = {
                k: np.concatenate([t.inputs[k] for t in batch], axis=0)
                for k in batch[0].inputs
            }
            try:
                outputs, meta = self.engine.predict(merged)
            except BaseException as e:
                for t in batch:
                    self._m_requests.inc(status="error")
                    t._reject(e)
                continue
            self._m_batches.inc()
            self._m_examples.inc(rows)
            self._m_occupancy.observe(rows / meta["bucket"])
            self.stats["batches"] += 1
            now = time.monotonic()
            lo = 0
            for t in batch:
                sl = jax_tree_slice(outputs, lo, lo + t.rows)
                lo += t.rows
                self._m_requests.inc(status="ok")
                self._m_latency.observe(now - t.enqueued)
                t._resolve(sl, dict(meta))


def jax_tree_slice(outputs: Dict[str, np.ndarray], lo: int, hi: int):
    """Row-slice every output array (outputs are host numpy by the time
    the batcher splits them back per request)."""
    return {k: v[lo:hi] for k, v in outputs.items()}
