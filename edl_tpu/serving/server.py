"""HTTP serving front + replica driver (the ``coord_service`` idiom).

One stdlib ``ThreadingHTTPServer`` per replica:

- ``POST /predict``  — ``{"inputs": {...}, "deadline_ms": 500}`` ->
  ``{"outputs": {...}, "weights_step": N, ...}``; 429 + ``Retry-After``
  on admission backpressure, 503 before weights load, 504 past
  deadline.
- ``POST /generate`` — autoregressive decode on the token batcher:
  ``{"inputs": {"tokens": [...]}, "max_new_tokens": 16,
  "deadline_ms": 30000, "eos_id": 1, "stream": true}``.  Non-stream
  replies one JSON object (``tokens`` + weight generation/step);
  ``stream: true`` replies chunked ``application/x-ndjson`` — one line
  per token as it decodes, a ``{"restart": true}`` line when a hot
  swap voids prior tokens (the sequence re-prefills on the new
  weights), and a final ``{"done": true, "tokens": [...]}`` line that
  is the authoritative output and carries the chunked-admission
  receipts (``prefill_chunks``, ``ttft_s`` spanning enqueue to first
  token across all chunks).  A long prompt prefills in block-aligned
  chunks BESIDE the running batch's decode steps (ISSUE 14), so no
  token line of another stream stalls behind this admission.  Same
  429/504/503 mapping as /predict; a prompt over the context cap is a
  typed 400 at admission, never a mid-generation error.
- ``GET /healthz``   — readiness: weights step, warmed buckets, depth.
- ``GET /metrics``   — Prometheus exposition of the process registry
  (the serving counters/histograms live there, so one scrape config
  covers trainers and servers alike).

``ServingReplica`` closes the control loop: it warms the engine's
bucketed forwards BEFORE registering with the job coordinator (a
replica in the serving world is a replica that answers its first
request on a held executable — the /prewarm contract's serving
analog), then heartbeats and ships telemetry snapshots on the training
stack's exact cadence machinery, so the coordinator's merged
``/telemetry`` carries the latency/queue-depth series the autoscaler's
serving lane scales on.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from edl_tpu.serving.batcher import (
    ContinuousBatcher,
    DeadlineExceededError,
    DrainingError,
    QueueFullError,
)
from edl_tpu.serving.engine import InferenceEngine, NotReadyError


class ServingServer:
    """Serve one ContinuousBatcher (and, for decode-capable models, a
    TokenContinuousBatcher on ``/generate``) over HTTP."""

    def __init__(
        self,
        batcher: ContinuousBatcher,
        host: str = "0.0.0.0",
        port: int = 0,
        gen_batcher=None,
    ):
        self.batcher = batcher
        self.gen_batcher = gen_batcher
        #: the ServingReplica driving this server (set by
        #: ServingReplica.start) — POST /drain routes through it so the
        #: full contract runs (admission close -> in-flight finish ->
        #: deregister); without one the handler drains the batchers
        #: directly (batcher-only test/CLI deployments)
        self.replica = None
        #: the MigrationReceiver advertised on GET /migrate (set by
        #: ServingReplica.start for decode-capable replicas)
        self.migration = None
        engine = batcher.engine
        self_server = self
        from edl_tpu import telemetry

        registry = telemetry.get_registry()

        class Handler(BaseHTTPRequestHandler):
            # /generate streaming uses Transfer-Encoding: chunked,
            # which RFC 7230 only defines for HTTP/1.1 — the default
            # HTTP/1.0 response line would make strict clients and
            # intermediaries buffer (or mis-parse) the stream.  Every
            # response here carries Content-Length or chunked framing,
            # so 1.1 keep-alive is safe.
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _reply(self, obj, code=200, headers=()):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    gen0 = self.server_gen_batcher
                    health = {
                        "ok": engine.ready,
                        "model": engine.model.name,
                        "weights_step": engine.weights_step,
                        "weights_generation": engine.weights_generation,
                        "warm_buckets": list(engine.warm_buckets),
                        "queue_depth": self.server_batcher.depth,
                        # backpressure surface (ISSUE 20): how close
                        # admission is to the 429 wall — the router's
                        # least-loaded scoring reads this, clients
                        # should not
                        "queue_limit": self.server_batcher.queue_limit,
                        "saturation": round(
                            self.server_batcher.depth
                            / max(1, self.server_batcher.queue_limit),
                            4,
                        ),
                        # drain posture: admission state + what is
                        # still in flight (the scale-down victim-ack
                        # signal a poller can watch)
                        "draining": self.server_batcher.draining
                        or (gen0 is not None and gen0.draining),
                        "in_flight": self.server_batcher.in_flight
                        + (gen0.in_flight if gen0 is not None else 0),
                        # serving mesh shape + per-device weight
                        # footprint (ISSUE 18): a poller (or the
                        # autoscaler's serving lane) can tell a
                        # replicated engine from a tp-sharded one and
                        # size HBM budgets off per-device bytes.
                        "mesh": {"dp": engine.dp, "tp": engine.tp},
                        "weight_shard_bytes_per_device": (
                            engine.weight_shard_bytes_per_device()
                        ),
                    }
                    gen = self.server_gen_batcher
                    if gen is not None:
                        health["decode"] = {
                            "max_seqs": engine.max_seqs,
                            "max_context": engine.max_context,
                            "block_tokens": engine.block_tokens,
                            "active_sequences": gen.active_count,
                            "decode_queue_depth": gen.depth,
                            "queue_limit": gen.queue_limit,
                            "saturation": round(
                                gen.depth / max(1, gen.queue_limit), 4
                            ),
                            "kv_occupancy": round(
                                engine.pool.occupancy(), 4
                            ),
                            "kv_pool_bytes_per_device": (
                                engine.kv_pool_bytes_per_device()
                            ),
                            # chunked-prefill posture (ISSUE 14): how
                            # admission shares iterations with decode
                            "chunked_prefill": gen.chunked_prefill,
                            "prefill_token_budget": (
                                gen.prefill_token_budget
                            ),
                            "prefilling_sequences": gen.prefilling_count,
                            "queued_prefill_tokens": (
                                gen.queued_prefill_tokens
                            ),
                        }
                    self._reply(health, 200 if engine.ready else 503)
                elif self.path == "/migrate":
                    # Migration endpoint advertisement: a draining
                    # source GETs this before opening the chunked-TCP
                    # push (the port lives outside HTTP — KV bytes
                    # never squeeze through JSON).
                    mig = self_server.migration
                    gen0 = self.server_gen_batcher
                    if mig is None:
                        self._reply(
                            {"error": "no migration receiver"}, 404
                        )
                        return
                    self._reply(
                        {
                            "migrate_port": mig.port,
                            "accepting": bool(
                                mig.accepting
                                and engine.ready
                                and not (
                                    gen0 is not None and gen0.draining
                                )
                            ),
                        }
                    )
                elif self.path == "/metrics":
                    body = registry.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._reply({"error": "not found"}, 404)

            @property
            def server_batcher(self):
                return batcher

            @property
            def server_gen_batcher(self):
                return self_server.gen_batcher

            def _read_json(self):
                n = int(self.headers.get("Content-Length", 0))
                return json.loads(self.rfile.read(n) or b"{}")

            def do_POST(self):
                if self.path == "/generate":
                    self._do_generate()
                    return
                if self.path == "/drain":
                    self._do_drain()
                    return
                if self.path != "/predict":
                    self._reply({"error": "not found"}, 404)
                    return
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._reply({"error": "bad json"}, 400)
                    return
                deadline_ms = req.get("deadline_ms")
                deadline_s = (
                    float(deadline_ms) / 1000.0
                    if deadline_ms is not None
                    else None
                )
                t0 = time.monotonic()
                try:
                    ticket = batcher.submit(
                        req.get("inputs") or {}, deadline_s=deadline_s
                    )
                    outputs, meta = ticket.result(
                        timeout=(deadline_s or batcher.default_deadline_s)
                        + 1.0
                    )
                except QueueFullError as e:
                    self._reply(
                        {"error": str(e), "retry_after_s": e.retry_after},
                        429,
                        headers=(
                            ("Retry-After", f"{e.retry_after:.3f}"),
                        ),
                    )
                    return
                except DrainingError as e:
                    # 503 + Retry-After, DISTINCT from 429 queue-full:
                    # this replica is leaving — clients route the retry
                    # to another replica instead of backing off here.
                    self._reply(
                        {
                            "error": str(e),
                            "draining": True,
                            "retry_after_s": e.retry_after,
                        },
                        503,
                        headers=(
                            ("Retry-After", f"{e.retry_after:.3f}"),
                        ),
                    )
                    return
                except (DeadlineExceededError, TimeoutError) as e:
                    self._reply({"error": str(e)}, 504)
                    return
                except NotReadyError as e:
                    self._reply({"error": str(e)}, 503)
                    return
                except ValueError as e:
                    self._reply({"error": str(e)}, 400)
                    return
                except Exception as e:
                    self._reply({"error": str(e)}, 500)
                    return
                self._reply(
                    {
                        "outputs": {
                            k: v.tolist() for k, v in outputs.items()
                        },
                        "weights_step": meta["weights_step"],
                        "weights_generation": meta["weights_generation"],
                        "latency_ms": round(
                            (time.monotonic() - t0) * 1000.0, 3
                        ),
                    }
                )

            def _do_generate(self):
                gen = self.server_gen_batcher
                if gen is None:
                    self._reply(
                        {
                            "error": f"model {engine.model.name!r} has no "
                            "decode path (single-shot /predict only)"
                        },
                        404,
                    )
                    return
                try:
                    req = self._read_json()
                except ValueError:
                    self._reply({"error": "bad json"}, 400)
                    return
                deadline_ms = req.get("deadline_ms")
                deadline_s = (
                    float(deadline_ms) / 1000.0
                    if deadline_ms is not None
                    else None
                )
                stream = bool(req.get("stream"))
                t0 = time.monotonic()
                events = None
                if stream:
                    import queue as _q

                    events = _q.Queue()
                try:
                    ticket = gen.submit_generate(
                        req.get("inputs") or {},
                        max_new_tokens=req.get("max_new_tokens"),
                        deadline_s=deadline_s,
                        eos_id=req.get("eos_id"),
                        on_event=events.put if stream else None,
                    )
                except QueueFullError as e:
                    self._reply(
                        {"error": str(e), "retry_after_s": e.retry_after},
                        429,
                        headers=(
                            ("Retry-After", f"{e.retry_after:.3f}"),
                        ),
                    )
                    return
                except DrainingError as e:
                    self._reply(
                        {
                            "error": str(e),
                            "draining": True,
                            "retry_after_s": e.retry_after,
                        },
                        503,
                        headers=(
                            ("Retry-After", f"{e.retry_after:.3f}"),
                        ),
                    )
                    return
                except ValueError as e:
                    self._reply({"error": str(e)}, 400)
                    return
                budget = (deadline_s or gen.default_deadline_s) + 1.0
                if stream:
                    # Chunked JSON lines: one object per event as the
                    # worker emits it; the final done/error line is the
                    # authoritative result.
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "application/x-ndjson"
                    )
                    self.send_header("Transfer-Encoding", "chunked")
                    self.end_headers()

                    def chunk(obj):
                        data = (json.dumps(obj) + "\n").encode()
                        self.wfile.write(
                            f"{len(data):x}\r\n".encode()
                            + data
                            + b"\r\n"
                        )
                        self.wfile.flush()

                    end = time.monotonic() + budget
                    try:
                        while True:
                            try:
                                ev = events.get(
                                    timeout=max(
                                        0.05, end - time.monotonic()
                                    )
                                )
                            except Exception:
                                chunk(
                                    {"error": "generation timed out"}
                                )
                                break
                            chunk(ev)
                            if "done" in ev or "error" in ev:
                                break
                        self.wfile.write(b"0\r\n\r\n")
                    except (BrokenPipeError, ConnectionError):
                        pass  # client went away; worker resolves anyway
                    return
                try:
                    tokens, meta = ticket.result(timeout=budget)
                except (DeadlineExceededError, TimeoutError) as e:
                    self._reply({"error": str(e)}, 504)
                    return
                except NotReadyError as e:
                    self._reply({"error": str(e)}, 503)
                    return
                except Exception as e:
                    self._reply({"error": str(e)}, 500)
                    return
                self._reply(
                    {
                        "tokens": tokens,
                        "weights_step": meta["weights_step"],
                        "weights_generation": meta["weights_generation"],
                        "restarts": meta["restarts"],
                        # chunked-admission receipts (ISSUE 14): how
                        # many prefill dispatches the prompt took, and
                        # the enqueue->first-token TTFT the server
                        # accounts for it (spans ALL chunks)
                        "prefill_chunks": meta.get("prefill_chunks", 0),
                        # prefix-cache receipt (ISSUE 17): how many
                        # KV blocks this admission reused instead of
                        # re-prefilling — 0 on a cold prompt
                        "reused_blocks": meta.get("reused_blocks", 0),
                        "ttft_ms": (
                            round(meta["ttft_s"] * 1000.0, 3)
                            if meta.get("ttft_s") is not None
                            else None
                        ),
                        "latency_ms": round(
                            (time.monotonic() - t0) * 1000.0, 3
                        ),
                    }
                )

            def _do_drain(self):
                """POST /drain — graceful shutdown contract (ISSUE 15):
                close admission (later /predict//generate = 503 +
                Retry-After), let every in-flight request and decode
                sequence finish under the bounded budget, free KV
                blocks, deregister from the serving coordinator.  With
                ``wait`` (the default) the reply IS the drain ack —
                the scale-down actuator's drain-victim-ack-then-patch
                handshake blocks on exactly this call."""
                try:
                    req = self._read_json()
                except ValueError:
                    self._reply({"error": "bad json"}, 400)
                    return
                budget_ms = req.get("budget_ms")
                budget_s = (
                    float(budget_ms) / 1000.0
                    if budget_ms is not None
                    else None
                )
                wait = bool(req.get("wait", True))
                migrate_to = req.get("migrate_to") or None
                trace = req.get("trace") or None
                rep = self_server.replica
                if rep is not None:
                    if wait:
                        self._reply(
                            rep.drain(
                                budget_s=budget_s,
                                migrate_to=migrate_to,
                                trace=trace,
                            )
                        )
                    else:
                        threading.Thread(
                            target=rep.drain,
                            kwargs={
                                "budget_s": budget_s,
                                "migrate_to": migrate_to,
                                "trace": trace,
                            },
                            daemon=True,
                            name="edl-serve-drain",
                        ).start()
                        self._reply(
                            {"draining": True, "drained": False}
                        )
                    return
                # Batcher-only fallback (no replica attached): close
                # admission and wait the queues out under the budget.
                batcher.close_admission()
                gen0 = self.server_gen_batcher
                if gen0 is not None:
                    gen0.close_admission()
                deadline = time.monotonic() + (budget_s or 30.0)
                if wait:
                    while time.monotonic() < deadline:
                        left = batcher.in_flight + (
                            gen0.in_flight if gen0 is not None else 0
                        )
                        if left == 0:
                            break
                        time.sleep(0.005)
                left = batcher.in_flight + (
                    gen0.in_flight if gen0 is not None else 0
                )
                self._reply(
                    {
                        "draining": True,
                        "drained": left == 0,
                        "in_flight": left,
                    }
                )

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "ServingServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="edl-serve"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class ServingReplica:
    """One serving replica's control-plane driver: warm -> register ->
    serve -> heartbeat/report until stopped.

    ``coordinator`` is the SERVING world's coordinator (Local or HTTP —
    the same membership/generation/telemetry machinery the training
    world runs; a serving fleet is just another replica set the
    autoscaler scales between [min, max]).  Warm-before-register is the
    scale-up contract: by the time this replica appears in the plan
    (and a load balancer could route to it), every bucketed forward is
    a held executable — its first request performs zero XLA compiles.
    """

    def __init__(
        self,
        engine: InferenceEngine,
        batcher: Optional[ContinuousBatcher] = None,
        server: Optional[ServingServer] = None,
        coordinator=None,
        replica_id: str = "",
        address: str = "",
        heartbeat_interval: float = 2.0,
        telemetry_interval: float = 5.0,
        gen_batcher=None,
        drain_budget_s: float = 30.0,
        chaos=None,
    ):
        """``drain_budget_s``: how long a graceful drain lets in-flight
        work finish before giving up (``EDL_SERVE_DRAIN_MS`` via
        serve_run; the kube manifests size the pod's
        terminationGracePeriodSeconds above it).  ``chaos``: a per-POD
        fault schedule for the replica-level points
        (``serve.replica.die`` / ``serve.coord.unreachable``) — kept
        separate from the engine's schedule on purpose: those points
        name a whole replica, so a schedule shared across replicas in
        one process would misroute them."""
        self.engine = engine
        self.batcher = batcher or ContinuousBatcher(engine)
        # Decode-capable engines get the token-iteration batcher too
        # (the /generate path).  BOTH batchers drive refresh() — it is
        # serialized and step-gated engine-side, and the single-shot
        # worker only refreshes while ITS queue has traffic, so a
        # generate-only fleet would otherwise never observe training's
        # newer spills (verified live: /generate stuck on the old step
        # while ckpt-24 sat in the durable dir).
        if gen_batcher is None and getattr(engine, "spec", None) is not None:
            from edl_tpu.serving.batcher import TokenContinuousBatcher

            gen_batcher = TokenContinuousBatcher(engine)
        self.gen_batcher = gen_batcher
        self.server = server
        self.coordinator = coordinator
        self.replica_id = replica_id or f"serve-{uuid.uuid4().hex[:8]}"
        self.address = address
        self.heartbeat_interval = heartbeat_interval
        self.telemetry_interval = telemetry_interval
        self._stop_evt: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        self._seq = 0
        self._events_sent_seq = 0
        self._boot = uuid.uuid4().hex[:12]
        self.drain_budget_s = float(drain_budget_s)
        self.chaos = chaos
        #: serve.coord.unreachable blackout: until this monotonic time
        #: every heartbeat/report is skipped (the coordinator has
        #: "vanished"); serving continues on last-verified weights and
        #: the lease-KeyError rejoin path reconverges on return
        self._blackout_until = 0.0
        self._deregistered = False
        self._dead = False
        #: drain state machine: None (serving) -> "running" ->
        #: "drained" (terminal) | "incomplete" (budget missed:
        #: admission stays closed, membership KEPT, retryable)
        self._drain_lock = threading.Lock()
        self._drain_state: Optional[str] = None
        #: causal-trace id the drain journals under (the actuator's
        #: decision trace, when the POST /drain body carried one)
        self._drain_trace: Optional[str] = None
        self._drain_evt: Optional[threading.Event] = None
        self._drain_result: Optional[dict] = None
        #: per-sequence drain progress (ISSUE 16 satellite): the first
        #: attempt snapshots the generation tickets in flight; retried
        #: drains re-wait ONLY the still-unresolved, still-local ones
        self._drain_pending: Optional[list] = None
        self._drain_total = 0
        self._drain_migrated = 0
        #: the live-migration receiver (decode-capable replicas only;
        #: started in start(), advertised on GET /migrate)
        self.migration = None
        from edl_tpu import telemetry

        self.telemetry = telemetry.get_registry()
        self.recorder = telemetry.get_recorder()
        self._m_reports = self.telemetry.counter(
            "edl_telemetry_reports_total"
        )
        self._g_draining = self.telemetry.gauge("edl_serve_draining")
        self._m_drains = self.telemetry.counter("edl_serve_drains_total")
        self._h_drain = self.telemetry.histogram(
            "edl_serve_drain_seconds"
        )

    def start(self) -> "ServingReplica":
        loaded = self.engine.load()
        # Warm BEFORE register: see the class doc (the prewarm/scale-up
        # contract).  Warming needs no weights — it lowers from
        # abstract shapes — so even a not-yet-ready replica boots hot
        # (DecodeEngine.warm also holds every prefill/decode bucket).
        self.engine.warm()
        self.batcher.start()
        if self.gen_batcher is not None:
            self.gen_batcher.start()
            if self.server is not None and self.server.gen_batcher is None:
                self.server.gen_batcher = self.gen_batcher
        if self.gen_batcher is not None:
            # Live KV migration receiver: survivors import drained
            # replicas' sequences here (chunked TCP, not HTTP).
            from edl_tpu.serving.migrate import MigrationReceiver

            self.migration = MigrationReceiver(
                self.engine,
                self.gen_batcher,
                replica_id=self.replica_id,
                chaos=getattr(self.engine, "chaos", None),
            ).start()
        if self.server is not None:
            self.server.replica = self  # POST /drain routes here
            self.server.migration = self.migration
            self.server.start()
        if self.coordinator is not None:
            self.coordinator.register(self.replica_id, address=self.address)
            self._start_background()
        self._g_draining.set(0, replica=self.replica_id)
        self.recorder.record(
            "serve.replica",
            {
                "replica": self.replica_id,
                "model": self.engine.model.name,
                "loaded": bool(loaded),
                "warm_buckets": list(self.engine.warm_buckets),
            },
            step=max(0, self.engine.weights_step),
        )
        return self

    def stop(self) -> None:
        if self._stop_evt is not None:
            self._stop_evt.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=10)
        if self.coordinator is not None and not self._deregistered:
            try:
                self.coordinator.deregister(self.replica_id)
                self._deregistered = True
            except Exception:
                pass
        self.batcher.stop()
        if self.gen_batcher is not None:
            self.gen_batcher.stop()
        if self.migration is not None:
            self.migration.stop()
        if self.server is not None:
            self.server.stop()

    # -- graceful drain (ISSUE 15) ------------------------------------------
    def _in_flight(self) -> int:
        n = self.batcher.in_flight
        if self.gen_batcher is not None:
            n += self.gen_batcher.in_flight
        return n

    def _pending_generation(self) -> list:
        """Snapshot the generation tickets currently on this replica's
        books (queued, mid-prefill, decoding, awaiting adoption) — the
        per-sequence unit the drain's progress accounting carries
        across retries."""
        b = self.gen_batcher
        if b is None:
            return []
        with b._cv:
            return (
                list(b._queue)
                + list(b._prefilling)
                + list(b._active)
                + [e[0] for e in b._adopted]
            )

    def drain(
        self,
        budget_s: Optional[float] = None,
        migrate_to: Optional[str] = None,
        trace: Optional[str] = None,
    ) -> dict:
        """The graceful-shutdown contract, in order: (1) close
        admission — later requests get 503 + Retry-After (distinct
        from 429: this replica is LEAVING, clients go elsewhere);
        (2) with ``migrate_to`` (a survivor's HTTP address or a
        ``tcp://host:port`` receiver endpoint) hand every in-flight
        decode sequence to the survivor FIRST — filled KV blocks move
        and decode resumes mid-generation, half-prefilled and queued
        prompts requeue cold — so drain latency is O(KV bytes), not
        the longest generation; anything that couldn't move (and every
        single-shot request) finishes under the bounded ``budget_s``
        (their normal finish paths free the KV blocks the same
        iteration); (3) stop heartbeating and deregister from the
        serving coordinator — only after in-flight settled, and
        heartbeats FIRST or the lease-KeyError rejoin path would
        re-register the leaving replica; (4) return the ack.  The
        caller owns the actual exit (``stop()``/process teardown) — a
        drained replica still answers /healthz and /metrics until
        then.

        Idempotent and join-safe: one drain runs at a time; concurrent
        calls (POST /drain racing SIGTERM racing the autoscaler's
        victim drain) block on it and share its result.  A drain that
        MISSES its budget is ``incomplete``, not terminal: admission
        stays closed, but the replica keeps heartbeating and stays
        REGISTERED — it must remain visible in the plan as an
        undrained victim so the scale-down actuator keeps blocking the
        Deployment patch and a retried drain (next tick, or a joiner's
        own call) can wait the remaining work out and ack for real.
        Only a SUCCESSFUL drain deregisters."""
        budget = self.drain_budget_s if budget_s is None else float(budget_s)
        give_up = time.monotonic() + budget + 10.0
        while True:
            with self._drain_lock:
                if self._drain_state == "drained":
                    return dict(self._drain_result)
                if self._drain_state in (None, "incomplete"):
                    first = self._drain_state is None
                    self._drain_state = "running"
                    self._drain_evt = threading.Event()
                    evt = self._drain_evt
                    break  # this caller owns the (re)attempt
                evt = self._drain_evt  # "running": join it
            evt.wait(timeout=max(0.05, give_up - time.monotonic()))
            if time.monotonic() >= give_up:
                return dict(
                    self._drain_result
                    or {"draining": True, "drained": False}
                )
            # re-check: the finished attempt either drained (return
            # its result) or came up incomplete (retry as the owner)
        t0 = time.monotonic()
        self._g_draining.set(1, replica=self.replica_id)
        if trace:
            # the actuator's decision trace (ServingLane run_once →
            # router steer → this drain): one causal chain in the
            # merged journal (ISSUE 20 satellite)
            self._drain_trace = trace
        if first:
            # counters/journal count DRAINS, not retry attempts
            self._m_drains.inc()
            self.recorder.record(
                "serve.drain",
                {"replica": self.replica_id, "phase": "start"},
                trace=self._drain_trace,
            )
        self.batcher.close_admission()
        if self.gen_batcher is not None:
            self.gen_batcher.close_admission()
        chaos = (
            self.chaos
            if self.chaos is not None
            else getattr(self.engine, "chaos", None)
        )
        # Per-sequence progress (ISSUE 16 satellite): snapshot once,
        # then every retry re-waits ONLY the still-unresolved, still-
        # local sequences — finished or migrated work never re-enters
        # the wait, so retried drains converge monotonically.
        if self._drain_pending is None:
            self._drain_pending = self._pending_generation()
            self._drain_total = len(self._drain_pending)
        else:
            self._drain_pending = [
                t
                for t in self._drain_pending
                if not t._done.is_set() and not t.migrated
            ]
        migrate_summary = None
        if migrate_to and self.gen_batcher is not None:
            from edl_tpu.serving.migrate import MigrationError, migrate_out

            try:
                migrate_summary = migrate_out(
                    self.engine,
                    self.gen_batcher,
                    migrate_to,
                    replica_id=self.replica_id,
                    chaos=chaos,
                )
                self._drain_migrated += (
                    migrate_summary["migrated"]
                    + migrate_summary["fallback"]
                    + migrate_summary["cold"]
                )
            except MigrationError as e:
                # Survivor dark or refusing before anything moved:
                # everything is still local — fall back to the PR 15
                # bounded wait below.
                migrate_summary = {"error": type(e).__name__}
                self.recorder.record(
                    "serve.migrate",
                    {
                        "phase": "abort",
                        "replica": self.replica_id,
                        "reason": type(e).__name__,
                    },
                )
        deadline = t0 + budget
        while time.monotonic() < deadline:
            if chaos is not None:
                for ev in chaos.due("serve.drain.slow"):
                    # chaos[serve.drain.slow]: a slow drain (stuck
                    # client, long generation) eats into the budget —
                    # the bounded-budget path under test control.
                    time.sleep(float(ev.arg or 0.05))
            if self._in_flight() == 0:
                break
            time.sleep(0.005)
        leftover = self._in_flight()
        drained = leftover == 0
        self._drain_pending = [
            t
            for t in self._drain_pending
            if not t._done.is_set() and not t.migrated
        ]
        if drained:
            # Heartbeats stop BEFORE deregistering (see docstring).
            if self._stop_evt is not None:
                self._stop_evt.set()
            if self._thread is not None and self._thread.is_alive():
                self._thread.join(timeout=5)
            if self.coordinator is not None and not self._deregistered:
                try:
                    self.coordinator.deregister(self.replica_id)
                    self._deregistered = True
                except Exception:
                    pass
        dt = time.monotonic() - t0
        self._h_drain.observe(dt)
        self._g_draining.set(2 if drained else 1, replica=self.replica_id)
        if drained:
            self.recorder.record(
                "serve.drain",
                {
                    "replica": self.replica_id,
                    "phase": "done",
                    "drained": True,
                    "migrated": self._drain_migrated,
                },
                timing={"seconds": round(dt, 6), "in_flight": leftover},
                trace=self._drain_trace,
            )
        result = {
            "draining": True,
            "drained": drained,
            "in_flight": leftover,
            "seconds": round(dt, 6),
            "progress": {
                "total": self._drain_total,
                "migrated": self._drain_migrated,
                "remaining": len(self._drain_pending),
            },
        }
        if migrate_summary is not None:
            result["migrate"] = migrate_summary
        with self._drain_lock:
            self._drain_result = result
            self._drain_state = "drained" if drained else "incomplete"
            evt.set()
        return dict(result)

    def die(self) -> None:
        """The UNgraceful exit (chaos ``serve.replica.die`` — the
        SIGKILL shape a drain exists to avoid): batchers stop abruptly
        (queued and mid-flight requests fail — their clients must
        retry against surviving replicas), heartbeats stop WITHOUT
        deregistering, so the coordinator only learns through lease
        expiry.  What a dead pod actually looks like."""
        self._dead = True
        if self._stop_evt is not None:
            self._stop_evt.set()
        self.batcher.stop()
        if self.gen_batcher is not None:
            self.gen_batcher.stop()
        if self.migration is not None:
            self.migration.stop()
        if self.server is not None:
            self.server.stop()

    def blackout(self, seconds: float) -> None:
        """chaos[serve.coord.unreachable]: the serving coordinator
        vanishes for ``seconds`` — beats and reports are skipped, the
        replica keeps serving its last-verified weights, and on return
        the normal heartbeat (or its KeyError -> re-register rejoin)
        reconverges membership."""
        self._blackout_until = time.monotonic() + float(seconds)

    # -- heartbeat + telemetry cadence (the training stack's shape) ---------
    def _start_background(self) -> None:
        self._stop_evt = threading.Event()

        def loop():
            last_report = 0.0
            while not self._stop_evt.wait(
                max(self.heartbeat_interval, 0.05)
            ):
                if self.chaos is not None:
                    # Replica-level chaos (per-POD schedule): a kill
                    # takes the whole replica down ungracefully; a
                    # coordinator blackout mutes the control plane
                    # while serving continues.
                    if self.chaos.due("serve.replica.die"):
                        self.die()
                        return
                    for ev in self.chaos.due("serve.coord.unreachable"):
                        self.blackout(float(ev.arg or 1.0))
                if self._blackout_until > time.monotonic():
                    continue  # coordinator unreachable: keep serving
                self._beat_once()
                now = time.monotonic()
                if (
                    self.telemetry_interval > 0
                    and now - last_report >= self.telemetry_interval
                ):
                    last_report = now
                    self._report_telemetry()

        self._thread = threading.Thread(
            target=loop, daemon=True, name="edl-serve-heartbeat"
        )
        self._thread.start()

    def _beat_once(self) -> None:
        try:
            self.coordinator.heartbeat(self.replica_id)
        except KeyError:
            # Evicted while alive (long GC/compile outlived the lease):
            # rejoin, same as a trainer (elastic._beat_once).
            try:
                self.coordinator.register(
                    self.replica_id, address=self.address
                )
            except Exception:
                pass
        except Exception:
            pass  # coordinator unreachable; retry next beat

    def _report_telemetry(self) -> None:
        rep = getattr(self.coordinator, "report_telemetry", None)
        if rep is None:
            return
        events = self.recorder.events_since(self._events_sent_seq)[:64]
        self._seq += 1
        try:
            rep(
                self.replica_id,
                snapshot=self.telemetry.snapshot(),
                seq=self._seq,
                events=[e.to_dict() for e in events],
                boot=self._boot,
            )
        except Exception:
            return  # best effort, like the trainer's cadence
        if events:
            self._events_sent_seq = events[-1].seq
        self._m_reports.inc()

    def tick(self) -> None:
        """Synchronous heartbeat+report (tests / single-threaded
        drivers that don't want the background thread)."""
        self._beat_once()
        self._report_telemetry()


def serve_run(
    entrypoint: str = "",
    coordinator_addr: str = "",
    checkpoint_dir: str = "",
    port: int = 0,
    max_batch: int = 0,
    queue_limit: int = 0,
    deadline_ms: int = 0,
    pod_address: str = "",
    replica_id: str = "",
) -> ServingReplica:
    """Build a serving replica from args + the ``EDL_SERVE_*`` pod env
    contract (the launcher analog for the serving workload).  Returns
    the started replica; the caller owns its lifetime."""
    from edl_tpu.checkpoint import HostDRAMStore
    from edl_tpu.launcher import configure_compile_cache, env_config
    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.coord_service import HTTPCoordinator

    cfg = env_config()
    configure_compile_cache(cfg["compile_cache_dir"])
    model = get_model(
        entrypoint or cfg["entrypoint"] or "mnist",
        workspace=cfg["workspace"],
    )
    spill = checkpoint_dir or cfg["checkpoint_dir"]
    store = HostDRAMStore(spill_dir=spill or None)
    if model.decode is not None:
        # Generative family: the decode stack (KV pool + /generate)
        # rides the same replica; /predict keeps working through the
        # single-shot buckets.
        from edl_tpu.serving.engine import DecodeEngine

        engine = DecodeEngine(
            model,
            store,
            max_batch=max_batch or cfg["serve_max_batch"],
        )
    else:
        engine = InferenceEngine(
            model,
            store,
            max_batch=max_batch or cfg["serve_max_batch"],
        )
    batcher = ContinuousBatcher(
        engine,
        queue_limit=queue_limit or cfg["serve_queue_limit"],
        default_deadline_s=(deadline_ms or cfg["serve_deadline_ms"])
        / 1000.0,
    )
    server = ServingServer(batcher, port=port or cfg["serve_port"])
    coordinator = None
    if coordinator_addr or cfg["coordinator_addr"]:
        coordinator = HTTPCoordinator(
            coordinator_addr or cfg["coordinator_addr"]
        )
    import os

    replica = ServingReplica(
        engine,
        batcher,
        server,
        coordinator=coordinator,
        replica_id=replica_id or cfg["pod_name"],
        address=pod_address or cfg["pod_address"],
        telemetry_interval=cfg["telemetry_interval"],
        drain_budget_s=float(os.environ.get("EDL_SERVE_DRAIN_MS", "30000"))
        / 1000.0,
    )
    return replica.start()


def main(argv=None):  # pragma: no cover - pod entrypoint
    import argparse

    p = argparse.ArgumentParser(description="EDL-TPU serving replica")
    p.add_argument("--entrypoint", default="", help="registered model name")
    p.add_argument("--coordinator", default="", help="serving coordinator")
    p.add_argument("--checkpoint-dir", default="")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--max-batch", type=int, default=0)
    p.add_argument("--queue-limit", type=int, default=0)
    p.add_argument("--deadline-ms", type=int, default=0)
    p.add_argument("--platform", default="")
    args = p.parse_args(argv)
    if args.platform:
        from edl_tpu.launcher import force_platform

        force_platform(args.platform)
    replica = serve_run(
        entrypoint=args.entrypoint,
        coordinator_addr=args.coordinator,
        checkpoint_dir=args.checkpoint_dir,
        port=args.port,
        max_batch=args.max_batch,
        queue_limit=args.queue_limit,
        deadline_ms=args.deadline_ms,
    )
    print(
        f"edl-tpu serving replica {replica.replica_id} "
        f"({replica.engine.model.name}) on port "
        f"{replica.server.port if replica.server else '-'}"
    )
    # SIGTERM = the kube pod-deletion signal: drain (close admission,
    # finish in-flight, free KV, deregister) then exit — the serving
    # half of the "a scale-down can never SIGKILL a replica
    # mid-generation" contract.  The Deployment's
    # terminationGracePeriodSeconds is sized above the drain budget so
    # the kubelet's SIGKILL never beats the drain.
    import signal
    import sys

    done = threading.Event()

    def _terminate(signum, frame):
        replica.drain()
        replica.stop()
        done.set()

    signal.signal(signal.SIGTERM, _terminate)
    done.wait()  # serve until drained out by SIGTERM
    sys.exit(0)


if __name__ == "__main__":  # pragma: no cover
    main()
