"""HTTP serving front + replica driver (the ``coord_service`` idiom).

One stdlib ``ThreadingHTTPServer`` per replica:

- ``POST /predict``  — ``{"inputs": {...}, "deadline_ms": 500}`` ->
  ``{"outputs": {...}, "weights_step": N, ...}``; 429 + ``Retry-After``
  on admission backpressure, 503 before weights load, 504 past
  deadline.
- ``GET /healthz``   — readiness: weights step, warmed buckets, depth.
- ``GET /metrics``   — Prometheus exposition of the process registry
  (the serving counters/histograms live there, so one scrape config
  covers trainers and servers alike).

``ServingReplica`` closes the control loop: it warms the engine's
bucketed forwards BEFORE registering with the job coordinator (a
replica in the serving world is a replica that answers its first
request on a held executable — the /prewarm contract's serving
analog), then heartbeats and ships telemetry snapshots on the training
stack's exact cadence machinery, so the coordinator's merged
``/telemetry`` carries the latency/queue-depth series the autoscaler's
serving lane scales on.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from edl_tpu.serving.batcher import (
    ContinuousBatcher,
    DeadlineExceededError,
    QueueFullError,
)
from edl_tpu.serving.engine import InferenceEngine, NotReadyError


class ServingServer:
    """Serve one ContinuousBatcher over HTTP."""

    def __init__(
        self,
        batcher: ContinuousBatcher,
        host: str = "0.0.0.0",
        port: int = 0,
    ):
        self.batcher = batcher
        engine = batcher.engine
        from edl_tpu import telemetry

        registry = telemetry.get_registry()

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            def _reply(self, obj, code=200, headers=()):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._reply(
                        {
                            "ok": engine.ready,
                            "model": engine.model.name,
                            "weights_step": engine.weights_step,
                            "weights_generation": engine.weights_generation,
                            "warm_buckets": list(engine.warm_buckets),
                            "queue_depth": self.server_batcher.depth,
                        },
                        200 if engine.ready else 503,
                    )
                elif self.path == "/metrics":
                    body = registry.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self._reply({"error": "not found"}, 404)

            @property
            def server_batcher(self):
                return batcher

            def do_POST(self):
                if self.path != "/predict":
                    self._reply({"error": "not found"}, 404)
                    return
                n = int(self.headers.get("Content-Length", 0))
                try:
                    req = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    self._reply({"error": "bad json"}, 400)
                    return
                deadline_ms = req.get("deadline_ms")
                deadline_s = (
                    float(deadline_ms) / 1000.0
                    if deadline_ms is not None
                    else None
                )
                t0 = time.monotonic()
                try:
                    ticket = batcher.submit(
                        req.get("inputs") or {}, deadline_s=deadline_s
                    )
                    outputs, meta = ticket.result(
                        timeout=(deadline_s or batcher.default_deadline_s)
                        + 1.0
                    )
                except QueueFullError as e:
                    self._reply(
                        {"error": str(e), "retry_after_s": e.retry_after},
                        429,
                        headers=(
                            ("Retry-After", f"{e.retry_after:.3f}"),
                        ),
                    )
                    return
                except (DeadlineExceededError, TimeoutError) as e:
                    self._reply({"error": str(e)}, 504)
                    return
                except NotReadyError as e:
                    self._reply({"error": str(e)}, 503)
                    return
                except ValueError as e:
                    self._reply({"error": str(e)}, 400)
                    return
                except Exception as e:
                    self._reply({"error": str(e)}, 500)
                    return
                self._reply(
                    {
                        "outputs": {
                            k: v.tolist() for k, v in outputs.items()
                        },
                        "weights_step": meta["weights_step"],
                        "weights_generation": meta["weights_generation"],
                        "latency_ms": round(
                            (time.monotonic() - t0) * 1000.0, 3
                        ),
                    }
                )

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "ServingServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True, name="edl-serve"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class ServingReplica:
    """One serving replica's control-plane driver: warm -> register ->
    serve -> heartbeat/report until stopped.

    ``coordinator`` is the SERVING world's coordinator (Local or HTTP —
    the same membership/generation/telemetry machinery the training
    world runs; a serving fleet is just another replica set the
    autoscaler scales between [min, max]).  Warm-before-register is the
    scale-up contract: by the time this replica appears in the plan
    (and a load balancer could route to it), every bucketed forward is
    a held executable — its first request performs zero XLA compiles.
    """

    def __init__(
        self,
        engine: InferenceEngine,
        batcher: Optional[ContinuousBatcher] = None,
        server: Optional[ServingServer] = None,
        coordinator=None,
        replica_id: str = "",
        address: str = "",
        heartbeat_interval: float = 2.0,
        telemetry_interval: float = 5.0,
    ):
        self.engine = engine
        self.batcher = batcher or ContinuousBatcher(engine)
        self.server = server
        self.coordinator = coordinator
        self.replica_id = replica_id or f"serve-{uuid.uuid4().hex[:8]}"
        self.address = address
        self.heartbeat_interval = heartbeat_interval
        self.telemetry_interval = telemetry_interval
        self._stop_evt: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        self._seq = 0
        self._events_sent_seq = 0
        self._boot = uuid.uuid4().hex[:12]
        from edl_tpu import telemetry

        self.telemetry = telemetry.get_registry()
        self.recorder = telemetry.get_recorder()
        self._m_reports = self.telemetry.counter(
            "edl_telemetry_reports_total"
        )

    def start(self) -> "ServingReplica":
        loaded = self.engine.load()
        # Warm BEFORE register: see the class doc (the prewarm/scale-up
        # contract).  Warming needs no weights — it lowers from
        # abstract shapes — so even a not-yet-ready replica boots hot.
        self.engine.warm()
        self.batcher.start()
        if self.server is not None:
            self.server.start()
        if self.coordinator is not None:
            self.coordinator.register(self.replica_id, address=self.address)
            self._start_background()
        self.recorder.record(
            "serve.replica",
            {
                "replica": self.replica_id,
                "model": self.engine.model.name,
                "loaded": bool(loaded),
                "warm_buckets": list(self.engine.warm_buckets),
            },
            step=max(0, self.engine.weights_step),
        )
        return self

    def stop(self) -> None:
        if self._stop_evt is not None:
            self._stop_evt.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=10)
        if self.coordinator is not None:
            try:
                self.coordinator.deregister(self.replica_id)
            except Exception:
                pass
        self.batcher.stop()
        if self.server is not None:
            self.server.stop()

    # -- heartbeat + telemetry cadence (the training stack's shape) ---------
    def _start_background(self) -> None:
        self._stop_evt = threading.Event()

        def loop():
            last_report = 0.0
            while not self._stop_evt.wait(
                max(self.heartbeat_interval, 0.05)
            ):
                self._beat_once()
                now = time.monotonic()
                if (
                    self.telemetry_interval > 0
                    and now - last_report >= self.telemetry_interval
                ):
                    last_report = now
                    self._report_telemetry()

        self._thread = threading.Thread(
            target=loop, daemon=True, name="edl-serve-heartbeat"
        )
        self._thread.start()

    def _beat_once(self) -> None:
        try:
            self.coordinator.heartbeat(self.replica_id)
        except KeyError:
            # Evicted while alive (long GC/compile outlived the lease):
            # rejoin, same as a trainer (elastic._beat_once).
            try:
                self.coordinator.register(
                    self.replica_id, address=self.address
                )
            except Exception:
                pass
        except Exception:
            pass  # coordinator unreachable; retry next beat

    def _report_telemetry(self) -> None:
        rep = getattr(self.coordinator, "report_telemetry", None)
        if rep is None:
            return
        events = self.recorder.events_since(self._events_sent_seq)[:64]
        self._seq += 1
        try:
            rep(
                self.replica_id,
                snapshot=self.telemetry.snapshot(),
                seq=self._seq,
                events=[e.to_dict() for e in events],
                boot=self._boot,
            )
        except Exception:
            return  # best effort, like the trainer's cadence
        if events:
            self._events_sent_seq = events[-1].seq
        self._m_reports.inc()

    def tick(self) -> None:
        """Synchronous heartbeat+report (tests / single-threaded
        drivers that don't want the background thread)."""
        self._beat_once()
        self._report_telemetry()


def serve_run(
    entrypoint: str = "",
    coordinator_addr: str = "",
    checkpoint_dir: str = "",
    port: int = 0,
    max_batch: int = 0,
    queue_limit: int = 0,
    deadline_ms: int = 0,
    pod_address: str = "",
    replica_id: str = "",
) -> ServingReplica:
    """Build a serving replica from args + the ``EDL_SERVE_*`` pod env
    contract (the launcher analog for the serving workload).  Returns
    the started replica; the caller owns its lifetime."""
    from edl_tpu.checkpoint import HostDRAMStore
    from edl_tpu.launcher import configure_compile_cache, env_config
    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.coord_service import HTTPCoordinator

    cfg = env_config()
    configure_compile_cache(cfg["compile_cache_dir"])
    model = get_model(
        entrypoint or cfg["entrypoint"] or "mnist",
        workspace=cfg["workspace"],
    )
    spill = checkpoint_dir or cfg["checkpoint_dir"]
    store = HostDRAMStore(spill_dir=spill or None)
    engine = InferenceEngine(
        model,
        store,
        max_batch=max_batch or cfg["serve_max_batch"],
    )
    batcher = ContinuousBatcher(
        engine,
        queue_limit=queue_limit or cfg["serve_queue_limit"],
        default_deadline_s=(deadline_ms or cfg["serve_deadline_ms"])
        / 1000.0,
    )
    server = ServingServer(batcher, port=port or cfg["serve_port"])
    coordinator = None
    if coordinator_addr or cfg["coordinator_addr"]:
        coordinator = HTTPCoordinator(
            coordinator_addr or cfg["coordinator_addr"]
        )
    replica = ServingReplica(
        engine,
        batcher,
        server,
        coordinator=coordinator,
        replica_id=replica_id or cfg["pod_name"],
        address=pod_address or cfg["pod_address"],
        telemetry_interval=cfg["telemetry_interval"],
    )
    return replica.start()


def main(argv=None):  # pragma: no cover - pod entrypoint
    import argparse

    p = argparse.ArgumentParser(description="EDL-TPU serving replica")
    p.add_argument("--entrypoint", default="", help="registered model name")
    p.add_argument("--coordinator", default="", help="serving coordinator")
    p.add_argument("--checkpoint-dir", default="")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--max-batch", type=int, default=0)
    p.add_argument("--queue-limit", type=int, default=0)
    p.add_argument("--deadline-ms", type=int, default=0)
    p.add_argument("--platform", default="")
    args = p.parse_args(argv)
    if args.platform:
        from edl_tpu.launcher import force_platform

        force_platform(args.platform)
    replica = serve_run(
        entrypoint=args.entrypoint,
        coordinator_addr=args.coordinator,
        checkpoint_dir=args.checkpoint_dir,
        port=args.port,
        max_batch=args.max_batch,
        queue_limit=args.queue_limit,
        deadline_ms=args.deadline_ms,
    )
    print(
        f"edl-tpu serving replica {replica.replica_id} "
        f"({replica.engine.model.name}) on port "
        f"{replica.server.port if replica.server else '-'}"
    )
    threading.Event().wait()  # serve until killed


if __name__ == "__main__":  # pragma: no cover
    main()
