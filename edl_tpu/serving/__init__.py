"""``edl_tpu.serving`` — elastic inference serving.

Continuous-batched, checkpoint-hot-swapping replicas driven by the
SAME control plane that scales training (coordinator membership +
autoscaler): an ``InferenceEngine`` serves the latest *verified*
checkpoint through AOT-warmed padded-bucket forwards (zero XLA
compiles on the request path), a ``ContinuousBatcher`` turns a bounded
admission queue into occupancy-maximizing micro-batches (Orca,
OSDI '22), and ``ServingServer``/``ServingReplica`` put an HTTP front
on it and register it into a serving world the autoscaler's
``ServingLane`` (edl_tpu.autoscaler.serving) scales on p95 latency and
queue depth.

Generative (autoregressive) traffic runs the TRUE-Orca path: a
``DecodeEngine`` holds separate AOT-warmed prefill/decode executables
over a paged KV cache (``KVBlockPool`` — fixed-size blocks, host-side
free list), and a ``TokenContinuousBatcher`` schedules per-TOKEN
iterations: requests join/leave the running batch at token
boundaries, finished sequences release their blocks the same
iteration they emit EOS, and a checkpoint hot swap re-prefills
in-flight sequences so no sequence ever mixes weight generations.
Long prompts prefill in block-aligned CHUNKS under a per-iteration
token budget riding beside the decode step (Sarathi-Serve's
stall-free batching), so an admission never stalls the running
batch's token cadence.

The fleet front door (``edl_tpu.serving.router``, ISSUE 20) hides all
of that churn from clients: a coordinator-fed ``RequestRouter`` (and
its ``routerd`` HTTP front) spreads admissions by live queue depth /
KV occupancy, steers new work off draining replicas before the 503,
absorbs 503/429/connection-refused under a per-request retry budget
(``RetryingClient``, the shared client-side fallback library),
ejects failing replicas on passive health and re-admits them by
active probe, and re-drives a cut /generate stream on a survivor
without duplicating or dropping a token.

Drains and preemptions MIGRATE live sequences instead of waiting
(``edl_tpu.serving.migrate``): filled KV blocks + cursor move to a
survivor over a fabric-style chunked-TCP push and decode resumes
mid-generation, bit-identical to an unmigrated run — drain latency is
O(KV bytes), independent of generation length, with re-prefill on the
survivor as the fallback ladder's last rung.
"""

from edl_tpu.serving.batcher import (
    ContinuousBatcher,
    DeadlineExceededError,
    DrainingError,
    GenerateTicket,
    QueueFullError,
    Ticket,
    TokenContinuousBatcher,
)
from edl_tpu.serving.engine import (
    BlockOwnershipError,
    DecodeEngine,
    DispatchWedgedError,
    InferenceEngine,
    KVBlockPool,
    NotReadyError,
    PromptTooLongError,
)
from edl_tpu.serving.client import (
    HTTPTarget,
    RetryBudgetExhausted,
    RetryingClient,
    UpstreamClientError,
    http_call,
)
from edl_tpu.serving.prefix import PrefixCache, chain_hashes
from edl_tpu.serving.router import (
    ReplicaView,
    RequestRouter,
    RouterServer,
    route_run,
)
from edl_tpu.serving.migrate import (
    MigrationError,
    MigrationReceiver,
    MigrationRefusedError,
    TornMigrationError,
    migrate_out,
)
from edl_tpu.serving.server import ServingReplica, ServingServer, serve_run

__all__ = [
    "BlockOwnershipError",
    "ContinuousBatcher",
    "DeadlineExceededError",
    "DecodeEngine",
    "DispatchWedgedError",
    "DrainingError",
    "GenerateTicket",
    "InferenceEngine",
    "KVBlockPool",
    "MigrationError",
    "MigrationReceiver",
    "MigrationRefusedError",
    "HTTPTarget",
    "NotReadyError",
    "PrefixCache",
    "PromptTooLongError",
    "QueueFullError",
    "ReplicaView",
    "RequestRouter",
    "RetryBudgetExhausted",
    "RetryingClient",
    "RouterServer",
    "ServingReplica",
    "ServingServer",
    "Ticket",
    "TokenContinuousBatcher",
    "TornMigrationError",
    "UpstreamClientError",
    "chain_hashes",
    "http_call",
    "migrate_out",
    "route_run",
    "serve_run",
]
