"""``edl_tpu.serving`` — elastic inference serving.

Continuous-batched, checkpoint-hot-swapping replicas driven by the
SAME control plane that scales training (coordinator membership +
autoscaler): an ``InferenceEngine`` serves the latest *verified*
checkpoint through AOT-warmed padded-bucket forwards (zero XLA
compiles on the request path), a ``ContinuousBatcher`` turns a bounded
admission queue into occupancy-maximizing micro-batches (Orca,
OSDI '22), and ``ServingServer``/``ServingReplica`` put an HTTP front
on it and register it into a serving world the autoscaler's
``ServingLane`` (edl_tpu.autoscaler.serving) scales on p95 latency and
queue depth.
"""

from edl_tpu.serving.batcher import (
    ContinuousBatcher,
    DeadlineExceededError,
    QueueFullError,
    Ticket,
)
from edl_tpu.serving.engine import InferenceEngine, NotReadyError
from edl_tpu.serving.server import ServingReplica, ServingServer, serve_run

__all__ = [
    "ContinuousBatcher",
    "DeadlineExceededError",
    "InferenceEngine",
    "NotReadyError",
    "QueueFullError",
    "ServingReplica",
    "ServingServer",
    "Ticket",
    "serve_run",
]
