"""``edl_tpu.serving.client`` — the shared retry-against-the-fleet
client (ISSUE 20).

Before the router existed, every caller of the serving plane
hand-rolled the same loop: submit against a replica, and when it
answers 503/DrainingError (it is leaving) go to ANOTHER replica, when
it answers 429/QueueFullError (it is full) back off and retry HERE,
when the connection is refused (it is dead) move on.  Those loops
lived in tests/test_serving_chaos.py and tests/test_serving_migrate.py
and in bench drivers, each subtly different.  ``RetryingClient`` is
that contract once:

- **429 / QueueFullError → back off HERE.**  The replica is the right
  place, it is momentarily full; honor its Retry-After hint and retry
  the same target (a bounded number of times before conceding the
  pass).
- **503 / DrainingError → go ELSEWHERE.**  The replica is leaving;
  retrying it only burns budget.  The draining mark is surfaced via
  ``on_attempt`` so a router can steer future admissions off it.
- **connection refused / reset → dead, go elsewhere.**
- **anything else 5xx-shaped → transient, go elsewhere.**

The loop is bounded by a per-request wall-clock budget and an attempt
cap; spending both raises the typed ``RetryBudgetExhausted``, which
remembers whether the LAST full pass over the fleet saw nothing but
queue-full rejections — that is the "whole fleet is saturated" signal
the router maps to 503 + Retry-After (any other exhaustion means the
fleet is gone, not busy, and advertising a Retry-After would lie).

Backoff between passes is capped exponential and deliberately
UNjittered: the chaos soaks assert bit-identical journals across
same-seed runs, and this client sits on their request path.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Callable, List, Optional, Sequence, Union

from edl_tpu.serving.batcher import DrainingError, QueueFullError

__all__ = [
    "HTTPTarget",
    "RetryBudgetExhausted",
    "RetryingClient",
    "UpstreamClientError",
    "http_call",
]


class RetryBudgetExhausted(RuntimeError):
    """The per-request retry budget (wall clock and/or attempts) is
    spent without any replica serving the request.  ``saturated`` is
    True when the final pass over every candidate ended in queue-full
    rejections only — the whole fleet is busy, not broken — and
    ``retry_after`` then carries the largest backend hint seen, for
    the router's own Retry-After header."""

    def __init__(
        self,
        msg: str,
        retry_after: float = 1.0,
        saturated: bool = False,
        attempts: int = 0,
    ):
        super().__init__(msg)
        self.retry_after = float(retry_after)
        self.saturated = bool(saturated)
        self.attempts = int(attempts)


class UpstreamClientError(RuntimeError):
    """The backend rejected the REQUEST (4xx), not the attempt: bad
    JSON, prompt too long, unknown path.  Never retried — every
    replica would say the same thing — and passed through with its
    original status."""

    def __init__(self, status: int, body: dict):
        super().__init__(body.get("error") or f"upstream {status}")
        self.status = int(status)
        self.body = body


def _retry_after_of(headers, body: dict, default: float) -> float:
    try:
        h = headers.get("Retry-After") if headers is not None else None
        if h is not None:
            return float(h)
    except (TypeError, ValueError):
        pass
    try:
        return float(body.get("retry_after_s", default))
    except (TypeError, ValueError):
        return default


def http_call(
    address: str,
    path: str,
    payload: dict,
    timeout: float = 30.0,
) -> dict:
    """One POST against a serving replica, with the fleet's status
    contract decoded into the batcher's typed exceptions: 429 ->
    ``QueueFullError`` (back off here), 503 -> ``DrainingError`` (go
    elsewhere; the server marks real drains with ``draining: true``
    but every 503 means "this replica can't take it, another might"),
    4xx -> ``UpstreamClientError`` (never retried), refused/reset ->
    ``ConnectionError`` (dead replica), other 5xx -> ``RuntimeError``
    (transient)."""
    url = address if address.startswith("http") else f"http://{address}"
    req = urllib.request.Request(
        url.rstrip("/") + path,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read() or b"{}")
        except ValueError:
            body = {}
        if e.code == 429:
            raise QueueFullError(
                body.get("error", "queue full"),
                retry_after=_retry_after_of(e.headers, body, 0.05),
            ) from None
        if e.code == 503:
            raise DrainingError(
                body.get("error", "unavailable"),
                retry_after=_retry_after_of(e.headers, body, 0.5),
            ) from None
        if 400 <= e.code < 500:
            raise UpstreamClientError(e.code, body) from None
        raise RuntimeError(body.get("error") or f"upstream {e.code}")
    except urllib.error.URLError as e:
        raise ConnectionError(str(e.reason)) from None
    except (ConnectionError, TimeoutError, OSError) as e:
        raise ConnectionError(str(e)) from None


class HTTPTarget:
    """A replica address as a RetryingClient target: calling it POSTs
    the request to ``path`` and decodes the status contract."""

    __slots__ = ("address", "path", "timeout")

    def __init__(self, address: str, path: str = "/predict",
                 timeout: float = 30.0):
        self.address = address
        self.path = path
        self.timeout = timeout

    def __call__(self, request: dict) -> dict:
        return http_call(
            self.address, self.path, request, timeout=self.timeout
        )

    def __repr__(self):
        return f"HTTPTarget({self.address}{self.path})"


#: per-attempt outcome names surfaced through ``on_attempt`` (and the
#: reasons the router counts under edl_route_retries_total)
OK, QUEUE_FULL, DRAINING, REFUSED, ERROR = (
    "ok", "queue_full", "draining", "refused", "error",
)


class RetryingClient:
    """Submit a request against an ordered fleet of targets until one
    serves it, within a wall-clock + attempt budget.

    ``targets``: a sequence of targets, or a zero-arg callable
    returning the CURRENT ordered candidate list (the router passes
    its live, health-filtered pick order so every pass reflects
    reality, not the admission-time snapshot).  ``submit(target,
    request)`` performs one attempt (default: ``target(request)``);
    it must raise ``QueueFullError`` / ``DrainingError`` /
    ``ConnectionError`` for the typed outcomes — anything else
    non-``UpstreamClientError`` counts as a transient error.

    ``on_attempt(target, outcome, exc)`` observes every attempt
    (outcome is one of ok/queue_full/draining/refused/error) — the
    router's passive-health and retry accounting hang off it.
    """

    def __init__(
        self,
        targets: Union[Sequence[Any], Callable[[], Sequence[Any]]],
        submit: Optional[Callable[[Any, Any], Any]] = None,
        budget_s: float = 15.0,
        attempts: int = 64,
        same_target_retries: int = 2,
        base_backoff_s: float = 0.02,
        max_backoff_s: float = 1.0,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
        on_attempt=None,
    ):
        self._targets = targets
        self._submit = submit or (lambda t, req: t(req))
        self.budget_s = float(budget_s)
        self.max_attempts = int(attempts)
        self.same_target_retries = int(same_target_retries)
        self.base_backoff_s = float(base_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self._sleep = sleep
        self._clock = clock
        self._on_attempt = on_attempt

    def _candidates(self) -> List[Any]:
        t = self._targets
        return list(t() if callable(t) else t)

    def _note(self, target, outcome, exc) -> None:
        if self._on_attempt is not None:
            self._on_attempt(target, outcome, exc)

    def call(self, request: Any) -> Any:
        deadline = self._clock() + self.budget_s
        attempts = 0
        backoff = self.base_backoff_s
        hint = 0.0
        last: Optional[BaseException] = None
        last_pass_saturated = False

        def exhausted(msg: str) -> RetryBudgetExhausted:
            return RetryBudgetExhausted(
                f"{msg} after {attempts} attempts: {last}",
                retry_after=max(hint, backoff),
                saturated=last_pass_saturated,
                attempts=attempts,
            )

        while True:
            order = self._candidates()
            if not order:
                last_pass_saturated = False
                raise exhausted("no routable backend")
            pass_saturated = True
            for target in order:
                full_here = 0
                while True:
                    if attempts >= self.max_attempts:
                        raise exhausted("attempt budget spent")
                    if self._clock() >= deadline:
                        raise exhausted("retry budget spent")
                    attempts += 1
                    try:
                        result = self._submit(target, request)
                    except QueueFullError as e:
                        # back off HERE: the replica is right, just full
                        last, hint = e, max(hint, e.retry_after)
                        self._note(target, QUEUE_FULL, e)
                        full_here += 1
                        if full_here > self.same_target_retries:
                            break  # concede the pass; next target
                        self._sleep(
                            min(e.retry_after, max(0.0,
                                                   deadline - self._clock()))
                        )
                        continue
                    except DrainingError as e:
                        # go ELSEWHERE: the replica is leaving
                        last, hint = e, max(hint, e.retry_after)
                        pass_saturated = False
                        self._note(target, DRAINING, e)
                        break
                    except UpstreamClientError:
                        raise  # the REQUEST is bad; no replica differs
                    except ConnectionError as e:
                        last = e
                        pass_saturated = False
                        self._note(target, REFUSED, e)
                        break
                    except Exception as e:
                        last = e
                        pass_saturated = False
                        self._note(target, ERROR, e)
                        break
                    self._note(target, OK, None)
                    return result
            last_pass_saturated = pass_saturated
            # the whole pass failed; breathe before re-walking the
            # fleet (capped exponential, deterministic on purpose)
            wait = max(backoff, hint if pass_saturated else 0.0)
            if self._clock() + wait >= deadline:
                raise exhausted("retry budget spent")
            self._sleep(wait)
            backoff = min(backoff * 2.0, self.max_backoff_s)
