"""Checkpoint-backed inference engine: forward-only, bucket-compiled,
hot-swapping.

The serving half of the elastic story (ROADMAP north star: "serves
heavy traffic from millions of users") reuses every layer the training
stack already paid for instead of inventing a parallel one:

- **Weights** come from the SAME checkpoint machinery training writes:
  ``HostDRAMStore.latest_verified`` (CRC-verified DRAM snapshots) with
  the durable-dir spill as the cold-start source (``load_from_disk``).
  A corrupted candidate is *rejected*, never served — the engine keeps
  the old weights and counts ``edl_serve_swap_rejected_total``.
- **Compilation** follows ``Trainer.warm_step``'s AOT discipline: one
  forward executable per padded batch bucket (power-of-2 rows), lowered
  from ABSTRACT shapes and HELD — on this jax ``.lower().compile()``
  does not warm the jit dispatch cache, so holding the executable is
  what makes the request path perform ZERO XLA compiles after warmup
  (the same seam bench.py asserts warm resizes at).
- **Hot swap** is generation-keyed like ``BatchStager``: ``_weights``
  is one immutable record swapped atomically between batches; a batch
  in flight bound its params reference at dispatch, so it can never
  observe torn (mixed-generation) weights, and no request is dropped
  during a swap.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from edl_tpu.checkpoint import HostDRAMStore
from edl_tpu.checkpoint.hostdram import HostCheckpoint, leaf_placer
from edl_tpu.consensus.watchdog import CollectiveTimeout, CollectiveWatchdog
from edl_tpu.models.base import ModelDef
from edl_tpu.parallel.mesh import MeshSpec, build_mesh, partition_shardings


class NotReadyError(RuntimeError):
    """No verified checkpoint has been loaded yet (the /healthz 503)."""


class PromptTooLongError(ValueError):
    """The prompt exceeds the engine's context window.  Raised at
    ADMISSION (coerce_prompt) — a too-long prompt must be rejected
    before it costs any compute or KV blocks, never discovered
    mid-chunk.  Subclasses ValueError so the HTTP front's existing
    400 mapping applies."""


class DispatchWedgedError(RuntimeError):
    """A prefill/chunk/decode dispatch missed the dispatch watchdog's
    deadline (wedged device, hung runtime) — or a chaos
    ``serve.dispatch.wedged`` trip simulated one.  By the time this
    raises the engine has already rebuilt its (donated) KV pools and
    bumped ``cache_epoch``: the token batcher treats it as a
    RECOVERABLE condition — live sequences re-prefill on the fresh
    cache instead of being rejected (the request survives a wedge; a
    genuine compute error still rejects)."""


class BlockOwnershipError(RuntimeError):
    """A ``KVBlockPool`` block was freed or referenced without being
    owned.  The double-free case is the dangerous one: a repeated
    free-list entry would eventually hand ONE block to TWO sequences,
    whose decode steps then write each other's K/V — silent output
    corruption, not a crash.  Raising at the bad ``free`` turns that
    into an immediate, attributable bug."""


@dataclass(frozen=True)
class _Weights:
    """One installed weight set.  Immutable and swapped atomically:
    a predict call reads the record ONCE, so the params it binds are
    consistent even if a swap lands mid-batch."""

    generation: int  # engine-local swap counter (monotonic)
    step: int        # training step of the source checkpoint
    digest: int      # checkpoint content fingerprint
    params: Any      # device params, replicated over the serving mesh


class InferenceEngine:
    """Forward-only engine over one model + one checkpoint store.

    ``model`` must declare ``predict_fn`` (the forward-only apply path;
    every built-in family does — ``pipeline_lm`` routes through its
    GPipe forward).  ``optimizer`` is needed ONLY to reconstruct the
    TrainState treedef for durable-dir cold loads (the spill format is
    positional); it must match the training job's optimizer family.
    """

    def __init__(
        self,
        model: ModelDef,
        store: Optional[HostDRAMStore] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        max_batch: int = 64,
        seed: int = 0,
        optimizer=None,
        chaos=None,
        tp: int = 1,
    ):
        if model.predict_fn is None:
            raise ValueError(
                f"model {model.name!r} declares no predict_fn (forward-"
                "only apply path); it cannot serve"
            )
        if not model.predict_inputs:
            raise ValueError(
                f"model {model.name!r} declares predict_fn but no "
                "predict_inputs (the request schema)"
            )
        self.model = model
        self.store = store if store is not None else HostDRAMStore()
        self.seed = seed
        self.optimizer = optimizer
        self.chaos = chaos if chaos is not None else getattr(
            self.store, "chaos", None
        )
        devs = list(devices) if devices is not None else jax.devices()
        tp = int(tp)
        if tp < 1:
            raise ValueError(f"tp must be >= 1, got {tp}")
        if len(devs) % tp != 0:
            raise ValueError(
                f"tp {tp} does not divide the {len(devs)}-device replica "
                "(the serving mesh is dp x tp)"
            )
        #: serving mesh extents.  ``tp`` shards attention heads / FFN
        #: hidden dims (and the KV pools' head axis) via the SAME
        #: partition rules training uses; ``dp`` replicates weights and
        #: shards the single-shot /predict batch.  tp=1 keeps the axis
        #: (MeshSpec keeps size-1 axes so PartitionSpecs stay valid at
        #: every scale) — a tp=1 engine is bit-for-bit the old
        #: replicated one.
        self.tp = tp
        self.dp = len(devs) // tp
        self.mesh: Mesh = build_mesh(
            MeshSpec.create(dp=self.dp, tp=tp), devs
        )
        dp = self.dp
        if max_batch < dp:
            raise ValueError(
                f"max_batch {max_batch} < the replica's dp extent {dp} "
                "(the smallest bucket must shard over it)"
            )
        #: padded batch buckets: dp, 2*dp, 4*dp ... plus max_batch
        #: itself as the final bucket — power-of-2 growth keeps the
        #: executable count logarithmic while the exact top bucket
        #: honors the CONFIGURED cap (a spec-validated max_batch must
        #: not silently shrink to the nearest power of two).  Only a
        #: cap not divisible by the device count narrows, and that is
        #: said out loud.
        eff = (max_batch // dp) * dp
        if eff != max_batch:
            import sys

            print(
                f"[edl-serve] max_batch {max_batch} rounded down to "
                f"{eff} (must be a multiple of the replica's dp "
                f"extent {dp})",
                file=sys.stderr,
            )
        buckets: List[int] = []
        b = dp
        while b < eff:
            buckets.append(b)
            b *= 2
        buckets.append(eff)
        self.buckets: Tuple[int, ...] = tuple(buckets)
        self.max_batch = eff

        #: how often (seconds) refresh() may rescan the durable spill
        #: dir.  The DRAM step comparison runs every batch (cheap);
        #: the os.listdir of a possibly network-backed checkpoint
        #: volume must not sit between every micro-batch.
        self.spill_poll_interval: float = 1.0
        self._last_spill_poll = 0.0

        self._jit = jax.jit(model.predict_fn)
        #: bucket -> held AOT executable (the zero-compile request path)
        self._compiled: Dict[int, Any] = {}
        #: step of the newest candidate already rejected at a refresh —
        #: a torn checkpoint sits in the store until a newer clean save
        #: supersedes it, and every poll re-seeing it must not re-count
        #: (or re-journal, or re-hash) the same rejection: one torn
        #: candidate = one rejection, which also keeps chaos-soak
        #: journals deterministic under refresh-poll interleave
        self._last_rejected_step = -1
        self._weights: Optional[_Weights] = None
        self._swap_lock = threading.Lock()
        #: serializes refresh(): the single-shot and token batchers may
        #: share one engine, and two concurrent refreshes would install
        #: the same checkpoint twice (a phantom generation bump)
        self._refresh_lock = threading.Lock()
        #: request schema: input key -> (trailing shape, dtype), probed
        #: from the model's own synthetic batch so serving cannot drift
        #: from the model's actual shapes
        probe = model.synth_batch(np.random.RandomState(0), 1)
        self.input_schema: Dict[str, Tuple[tuple, Any]] = {
            k: (tuple(probe[k].shape[1:]), probe[k].dtype)
            for k in model.predict_inputs
        }
        self._batch_sharding = {
            k: NamedSharding(
                self.mesh, P("dp", *([None] * len(shape)))
            )
            for k, (shape, _) in self.input_schema.items()
        }
        self._abstract_params = jax.eval_shape(
            model.init_params, jax.random.key(seed)
        )
        #: per-leaf weight placement on the serving mesh: the model's
        #: OWN partition rules (the ones training shards with),
        #: filtered to the axes this mesh has — so qkv/out kernels and
        #: MoE expert FFNs shard over tp while fsdp/ep entries drop out
        #: (weights replicate over dp; "dp" never names a weight dim).
        #: Models without rules replicate every leaf — the pre-tp
        #: behaviour.
        if model.param_partition is not None:
            self._param_shardings = partition_shardings(
                self.mesh, model.param_partition(self._abstract_params)
            )
        else:
            replicated = NamedSharding(self.mesh, P())
            self._param_shardings = jax.tree_util.tree_map(
                lambda _: replicated, self._abstract_params
            )

        from edl_tpu import telemetry

        self.telemetry = telemetry.get_registry()
        self.recorder = telemetry.get_recorder()
        self._m_swaps = self.telemetry.counter("edl_serve_hot_swaps_total")
        self._m_swap_rejected = self.telemetry.counter(
            "edl_serve_swap_rejected_total"
        )
        self._m_weights_step = self.telemetry.gauge("edl_serve_weights_step")
        self._m_compile_seconds = self.telemetry.histogram(
            "edl_compile_seconds"
        )
        # Mesh-shape + per-device footprint gauges: the fleet view must
        # be able to tell a replicated engine from a sharded one.
        self.telemetry.gauge("edl_serve_mesh_dp").set(self.dp)
        self.telemetry.gauge("edl_serve_mesh_tp").set(self.tp)
        self._m_weight_shard_bytes = self.telemetry.gauge(
            "edl_serve_weight_shard_bytes_per_device"
        )
        self._m_weight_shard_bytes.set(self.weight_shard_bytes_per_device())

    # -- per-device footprint ------------------------------------------------
    def weight_full_bytes(self) -> int:
        """Unsharded weight footprint (what a tp=1 device holds)."""
        return sum(
            int(np.prod(l.shape, dtype=np.int64))
            * np.dtype(l.dtype).itemsize
            for l in jax.tree_util.tree_leaves(self._abstract_params)
        )

    def weight_shard_bytes_per_device(self) -> int:
        """Weight bytes ONE device holds under the partition rules —
        ``shard_shape`` applies jax's ceil-chunk split (the
        ``checkpoint.fabric.gspmd_chunk`` rule), so a tp-sharded kernel
        counts at 1/tp.  This is also the hot-swap staging traffic per
        device: ``leaf_placer`` stages exactly each device's slice."""
        total = 0
        for l, s in zip(
            jax.tree_util.tree_leaves(self._abstract_params),
            jax.tree_util.tree_leaves(self._param_shardings),
        ):
            shp = s.shard_shape(tuple(l.shape))
            total += (
                int(np.prod(shp, dtype=np.int64))
                * np.dtype(l.dtype).itemsize
            )
        return total

    # -- weights ------------------------------------------------------------
    @property
    def weights_step(self) -> int:
        w = self._weights
        return w.step if w is not None else -1

    @property
    def weights_generation(self) -> int:
        w = self._weights
        return w.generation if w is not None else 0

    @property
    def ready(self) -> bool:
        return self._weights is not None

    def current_weights(self) -> Optional[_Weights]:
        """The installed weight record (immutable).  The token batcher
        binds this ONCE per iteration and passes it to prefill/decode
        explicitly, so a swap landing mid-iteration cannot mix
        generations within one dispatch."""
        return self._weights

    def _template_state(self):
        """Abstract TrainState schema for positional durable-dir loads
        (treedef + leaf count only; no allocation).  Lazy: DRAM
        checkpoints carry their own treedef and never need it."""
        import optax

        from edl_tpu.runtime.train import TrainState

        opt = self.optimizer if self.optimizer is not None else optax.adam(
            1e-3
        )

        def init_fn(rng):
            import jax.numpy as jnp

            params = self.model.init_params(rng)
            return TrainState(
                step=jnp.zeros((), jnp.int32),
                params=params,
                opt_state=opt.init(params),
            )

        return jax.eval_shape(init_fn, jax.random.key(self.seed))

    def _stage_params(self, params_host):
        """Stage host params onto the serving mesh, each device slice
        assembled through the checkpoint fabric's row-aligned
        ``ShardLayout`` (``stage_slice_from_shards``) instead of the
        retired per-leaf index slicing.  Here the shard source is a
        view into the full host leaf — zero extra copies, bytes
        bit-identical to the old ``x[idx]`` path — and the SAME
        assembler serves the shard-only durable-dir swap
        (``_install_from_shard_spills``), where shards come from
        per-rank npz files and no full leaf ever exists.  Non-CPU and
        cross-process meshes keep ``leaf_placer``'s DMA/collective
        paths unchanged."""
        import jax.numpy as jnp

        from edl_tpu.checkpoint import fabric as fab

        place = leaf_placer(self.mesh)
        multiproc = any(
            d.process_index != jax.process_index()
            for d in self.mesh.devices.flat
        )
        cpu = all(d.platform == "cpu" for d in self.mesh.devices.flat)
        p_leaves, p_def = jax.tree_util.tree_flatten(params_host)
        s_leaves = jax.tree_util.tree_flatten(self._param_shardings)[0]
        if multiproc or not cpu:
            placed = [place(x, s) for x, s in zip(p_leaves, s_leaves)]
            return jax.tree_util.tree_unflatten(p_def, placed)
        layout = fab.ShardLayout.build(
            [int(np.asarray(x).nbytes) for x in p_leaves],
            1,
            shard_bytes=fab.deployment_shard_bytes(),
            rows=fab.leaf_rows(p_leaves),
        )
        placed = []
        for i, (x, s) in enumerate(zip(p_leaves, s_leaves)):
            if isinstance(x, np.ndarray) and not s.is_fully_replicated:

                def src(sh, _x=x):
                    return fab.byte_view(_x)[
                        sh.offset : sh.offset + sh.length
                    ]

                placed.append(
                    jax.make_array_from_callback(
                        x.shape,
                        s,
                        lambda idx, _i=i, _x=x, _src=src: jnp.array(
                            fab.stage_slice_from_shards(
                                layout, _i, _x, idx, _src
                            )
                        ),
                    )
                )
            else:
                placed.append(place(x, s))
        return jax.tree_util.tree_unflatten(p_def, placed)

    def _install(self, ckpt: HostCheckpoint) -> None:
        """Place ``ckpt``'s params on the serving mesh via the model's
        partition rules and publish them as the next weight
        generation.  ONLY the params leave the host — serving never
        pays the optimizer state's placement or memory — and on a tp
        mesh each device stages only ITS weight shard (row-aligned
        ``ShardLayout`` slices via ``_stage_params``), so swap traffic
        is 1/tp per device."""
        state_host = ckpt.unflatten()
        params_host = getattr(state_host, "params", state_host)
        params = self._stage_params(params_host)
        with self._swap_lock:
            gen = (self._weights.generation + 1) if self._weights else 1
            self._weights = _Weights(
                generation=gen,
                step=int(ckpt.step),
                digest=ckpt.digest(),
                params=params,
            )
        # A successful install clears the rejection dedup: the next
        # torn candidate (whatever its step) counts/journals again.
        self._last_rejected_step = -1
        self._m_weights_step.set(int(ckpt.step))

    def _install_from_shard_spills(
        self, step: int, mans: Dict[int, tuple], initial: bool = False
    ) -> bool:
        """Hot-swap staged straight out of a shard-only durable dir:
        each device slice is assembled from the covering per-rank
        shard files (CRC-gated per shard, lazily read), so a tp
        serving fleet swaps from shard-only training hosts with NO
        process — trainer or server — materializing full state.  Host
        traffic here is the params' bytes read shard-by-shard; the
        optimizer state's shards are never opened."""
        import os
        import zlib

        import jax.numpy as jnp

        from edl_tpu.checkpoint import fabric as fab

        template = self._template_state()
        leaves_abs, treedef = jax.tree_util.tree_flatten(template)
        any_man = next(iter(mans.values()))[1]
        if [int(b) for b in any_man.get("leaf_nbytes", ())] != [
            fab.leaf_nbytes(l) for l in leaves_abs
        ]:
            raise RuntimeError(
                f"shard spills at step {step} do not match the serving "
                "model's leaf schema (wrong model?)"
            )
        layout = fab.ShardLayout.build(
            [fab.leaf_nbytes(l) for l in leaves_abs],
            max(1, int(any_man.get("world", 1))),
            k=int(any_man.get("k", 1)),
            shard_bytes=int(any_man["shard_bytes"]),
            rows=fab.leaf_rows(leaves_abs),
        )
        if len(layout.shards) != int(any_man.get("n_shards", -1)):
            raise RuntimeError(
                f"shard spills at step {step} use a different shard "
                "granularity than this deployment"
            )
        # Which global state-leaf indices are params: flatten a tree of
        # indices and read its params subtree — no schema guessing.
        idx_tree = jax.tree_util.tree_unflatten(
            treedef, list(range(len(leaves_abs)))
        )
        params_abs = getattr(template, "params", template)
        param_idx_tree = getattr(idx_tree, "params", idx_tree)
        param_idxs = jax.tree_util.tree_leaves(param_idx_tree)
        p_def = jax.tree_util.tree_flatten(params_abs)[1]
        s_leaves = jax.tree_util.tree_flatten(self._param_shardings)[0]
        owner_of: Dict[int, int] = {}
        digs: Dict[int, int] = {}
        for rank, (name, man) in mans.items():
            for i, dg in zip(man.get("indices", ()), man.get("digests", ())):
                owner_of[int(i)] = rank
                digs[int(i)] = int(dg)
        opened: Dict[int, Any] = {}

        def shard_src(sh):
            rank = owner_of[sh.index]
            if rank not in opened:
                name = mans[rank][0]
                opened[rank] = np.load(
                    os.path.join(
                        self.store.spill_dir,
                        name[: -len(".json")] + ".npz",
                    )
                )
            arr = np.asarray(opened[rank][f"s_{sh.index}"], np.uint8)
            if zlib.crc32(arr) != digs.get(sh.index):
                raise RuntimeError(
                    f"shard {sh.index} at step {step} failed CRC "
                    "verification (torn shard spill)"
                )
            return arr

        try:
            placed = [
                jax.make_array_from_callback(
                    tuple(leaves_abs[gi].shape),
                    s,
                    lambda idx, _gi=gi: jnp.array(
                        fab.stage_slice_from_shards(
                            layout, _gi, leaves_abs[_gi], idx, shard_src
                        )
                    ),
                )
                for gi, s in zip(param_idxs, s_leaves)
            ]
        finally:
            for z in opened.values():
                try:
                    z.close()
                except Exception:
                    pass
        params = jax.tree_util.tree_unflatten(p_def, placed)
        # Shard-granular fingerprint (crc32 over the manifest's shard
        # digest vector): no full-leaf bytes exist to hash.
        digest = zlib.crc32(
            np.asarray(
                any_man.get("shard_digests", []), np.uint32
            ).tobytes()
        )
        with self._swap_lock:
            gen = (self._weights.generation + 1) if self._weights else 1
            self._weights = _Weights(
                generation=gen,
                step=int(step),
                digest=int(digest),
                params=params,
            )
        self._last_rejected_step = -1
        self._m_weights_step.set(int(step))
        if not initial:
            self._m_swaps.inc()
        self.recorder.record(
            "serve.swap",
            {
                "step": int(step),
                "initial": bool(initial),
                "source": "shard_spill",
                "ranks": len(mans),
            },
            step=int(step),
        )
        return True

    def _newest_full_spill_step(self) -> int:
        """Newest full-copy spill step in the durable dir (-1 when
        only shard spills — or nothing — exist)."""
        import os

        best = -1
        try:
            names = os.listdir(self.store.spill_dir)
        except OSError:
            return best
        for name in names:
            if (
                name.endswith(".json")
                and ".tmp." not in name
                and ".shard-r" not in name
            ):
                try:
                    best = max(
                        best, int(name[len("ckpt-") : -len(".json")])
                    )
                except ValueError:
                    continue
        return best

    def load(self) -> bool:
        """Initial load: newest verified DRAM checkpoint, falling back
        to the durable spill dir (the launcher's EDL_CHECKPOINT_DIR).
        A shard-only durable dir (per-rank shard spills from a
        shard-only training fleet) stages straight from the shard
        files when its newest covered step beats any full spill.
        Returns False when nothing restorable exists."""
        ckpt = self.store.latest_verified()
        if ckpt is None and self.store.spill_dir:
            from edl_tpu.checkpoint.hostdram import (
                newest_covered_shard_step,
            )

            found = newest_covered_shard_step(self.store.spill_dir)
            if found is not None and found[0] >= self._newest_full_spill_step():
                try:
                    return self._install_from_shard_spills(
                        found[0], found[1], initial=True
                    )
                except Exception:
                    self._m_swap_rejected.inc()
                    self.recorder.record(
                        "serve.swap.rejected",
                        {"source": "shard_spill", "serving_step": -1},
                        step=0,
                    )
            try:
                ckpt = self.store.load_from_disk(self._template_state())
            except FileNotFoundError:
                ckpt = None
        if ckpt is None:
            return False
        self._install(ckpt)
        self.recorder.record(
            "serve.swap",
            {"step": int(ckpt.step), "initial": True},
            step=int(ckpt.step),
        )
        return True

    def refresh(self) -> bool:
        """Hot-swap to a newer *verified* checkpoint if one appeared —
        called by the batcher BETWEEN batches, never mid-batch.  A
        candidate that fails CRC verification (``latest_verified``
        drops it) or an unreadable durable spill is rejected and the
        engine keeps serving the current weights; no request is ever
        dropped for a swap.  Cheap when nothing changed: one step
        comparison, no hash pass."""
        with self._refresh_lock:
            return self._refresh_locked()

    def _refresh_locked(self) -> bool:
        current = self.weights_step
        if self.chaos is not None:
            for _ in self.chaos.due("serve.swap.torn"):
                # chaos[serve.swap.torn]: the newest DRAM candidate's
                # bytes rot before verification — latest_verified must
                # reject it (falling back past it), and the engine must
                # keep answering from the old weights.
                newest = self.store.latest()
                if newest is not None and newest.leaves:
                    newest.leaves[0] = newest.leaves[0].copy()
                    newest.leaves[0].reshape(-1).view(np.uint8)[0] ^= 0xFF
        now = time.monotonic()
        if self.store.spill_dir and (
            now - self._last_spill_poll >= self.spill_poll_interval
        ):
            # Durable-dir poll: a TRAINING fleet spills here; a serving
            # replica in another process sees new steps only on disk.
            # Throttled — a listdir on a network-backed volume must not
            # run between every micro-batch.
            self._last_spill_poll = now
            try:
                if self._poll_spill_dir(current):
                    # Shard-only spills staged and swapped directly
                    # (no full-copy DRAM intermediate exists to verify).
                    return True
            except Exception:
                self._m_swap_rejected.inc()
                self.recorder.record(
                    "serve.swap.rejected",
                    {"source": "disk", "serving_step": current},
                    step=max(0, current),
                )
        newest = self.store.latest()
        if newest is None or int(newest.step) <= current:
            return False
        if int(newest.step) == self._last_rejected_step:
            # The newest candidate is the one already rejected: nothing
            # changed since, so skip the re-verify (one hash pass per
            # candidate, not per poll) and the duplicate count/journal.
            return False
        ckpt = self.store.latest_verified()
        if ckpt is None or int(ckpt.step) <= current:
            # The newer candidate failed verification (torn/corrupt):
            # latest_verified discarded it and whatever remains is not
            # newer than what we serve.  Keep the old weights.
            self._last_rejected_step = int(newest.step)
            self._m_swap_rejected.inc()
            self.recorder.record(
                "serve.swap.rejected",
                {"source": "dram", "serving_step": current},
                step=max(0, current),
            )
            return False
        self._install(ckpt)
        self._m_swaps.inc()
        self.recorder.record(
            "serve.swap",
            {"step": int(ckpt.step), "from_step": current},
            step=int(ckpt.step),
        )
        return True

    def _poll_spill_dir(self, current: int) -> bool:
        """Pull a newer durable spill into the store (so the normal
        DRAM verify/swap path below picks it up).  Manifest scan only —
        bytes load (and CRC-verify) once per NEW step, not per poll.
        Shard-only spills (per-rank ``ckpt-*.shard-r*`` families from a
        shard-only training fleet) have no full copy to pull: when the
        newest FULLY COVERED shard step beats everything else, the swap
        stages straight from the shard files and returns True."""
        import os

        from edl_tpu.checkpoint.hostdram import newest_covered_shard_step

        dram = self.store.latest()
        dram_step = int(dram.step) if dram is not None else -1
        best = -1
        for name in os.listdir(self.store.spill_dir):
            if (
                name.endswith(".json")
                and ".tmp." not in name
                and ".shard-r" not in name
            ):
                try:
                    best = max(best, int(name[len("ckpt-"):-len(".json")]))
                except ValueError:
                    continue
        found = newest_covered_shard_step(self.store.spill_dir)
        if found is not None and found[0] > max(current, dram_step, best):
            return self._install_from_shard_spills(found[0], found[1])
        if best > max(current, dram_step):
            self.store.load_from_disk(self._template_state(), step=best)
        return False

    # -- compilation --------------------------------------------------------
    def _abstract_batch(self, bucket: int) -> Dict[str, Any]:
        return {
            k: jax.ShapeDtypeStruct(
                (bucket,) + shape, dtype, sharding=self._batch_sharding[k]
            )
            for k, (shape, dtype) in self.input_schema.items()
        }

    def warm(self, buckets: Optional[Sequence[int]] = None) -> int:
        """AOT-compile the forward for every bucket (abstract shapes —
        zero device allocation) and HOLD the executables.  Idempotent;
        returns how many compiles happened.  A replica warms BEFORE
        taking traffic (ServingReplica.start / the scale-up contract),
        so its first request dispatches a held executable."""
        # The hot-swap path's per-leaf CPU staging conversions compile
        # tiny programs too (leaf_placer's jnp.array, same as restore):
        # warm them here so even the FIRST swap stages zero compiles.
        from edl_tpu.checkpoint.hostdram import warm_leaf_conversions

        # Replicated leaves stage whole; tp-sharded leaves stage each
        # device's SLICE (leaf_placer's sharded branch) — warm the
        # staging conversion at the shape it will actually run.
        staging = [
            jax.ShapeDtypeStruct(
                l.shape
                if s.is_fully_replicated
                else s.shard_shape(tuple(l.shape)),
                l.dtype,
            )
            for l, s in zip(
                jax.tree_util.tree_leaves(self._abstract_params),
                jax.tree_util.tree_leaves(self._param_shardings),
            )
        ]
        warm_leaf_conversions(staging)
        abs_params = jax.tree_util.tree_map(
            lambda a, s: jax.ShapeDtypeStruct(
                a.shape, a.dtype, sharding=s
            ),
            self._abstract_params,
            self._param_shardings,
        )
        warmed = 0
        for b in buckets if buckets is not None else self.buckets:
            if b in self._compiled:
                continue
            t0 = time.perf_counter()
            with self.mesh:
                self._compiled[b] = self._jit.lower(
                    abs_params, self._abstract_batch(b)
                ).compile()
            dt = time.perf_counter() - t0
            self._m_compile_seconds.observe(dt)
            self.recorder.record(
                "serve.warm",
                {"bucket": b, "model": self.model.name},
                timing={"seconds": round(dt, 6)},
            )
            warmed += 1
        return warmed

    @property
    def warm_buckets(self) -> Tuple[int, ...]:
        return tuple(sorted(self._compiled))

    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(
            f"batch of {n} rows exceeds the largest bucket "
            f"{self.buckets[-1]} (max_batch)"
        )

    # -- the request path ---------------------------------------------------
    def _pad(self, inputs: Dict[str, np.ndarray], n: int, bucket: int):
        if n == bucket:
            return inputs
        out = {}
        for k, v in inputs.items():
            pad = np.broadcast_to(
                v[-1:], (bucket - n,) + tuple(v.shape[1:])
            )
            out[k] = np.concatenate([v, pad], axis=0)
        return out

    def coerce_inputs(
        self, inputs: Dict[str, Any]
    ) -> Tuple[Dict[str, np.ndarray], int]:
        """Validate a request's inputs against the model schema and
        coerce to the schema dtypes.  Returns (arrays, rows)."""
        missing = [k for k in self.input_schema if k not in inputs]
        if missing:
            raise ValueError(
                f"request missing input(s) {missing}; model "
                f"{self.model.name!r} expects {sorted(self.input_schema)}"
            )
        arrays: Dict[str, np.ndarray] = {}
        n = None
        for k, (shape, dtype) in self.input_schema.items():
            a = np.asarray(inputs[k], dtype=dtype)
            if a.ndim == len(shape):  # single example: add the batch dim
                a = a[None]
            if (
                tuple(a.shape[1:]) != shape
                and len(shape) == 1
                and a.ndim == 2
                and np.issubdtype(np.dtype(dtype), np.integer)
                and a.shape[1] < shape[0]
            ):
                # Token-like rows shorter than the schema (the schema
                # is probed from the training corpus, whose rows carry
                # the shifted-label extra position): right-pad with 0 —
                # the LM families' pad id — so a natural L-token
                # next-token request serves without a dummy position.
                a = np.concatenate(
                    [
                        a,
                        np.zeros(
                            (a.shape[0], shape[0] - a.shape[1]), dtype
                        ),
                    ],
                    axis=1,
                )
            if tuple(a.shape[1:]) != shape:
                raise ValueError(
                    f"input {k!r} rows have shape {tuple(a.shape[1:])}, "
                    f"expected {shape}"
                )
            if n is None:
                n = a.shape[0]
            elif a.shape[0] != n:
                raise ValueError(
                    "request inputs disagree on row count "
                    f"({k!r}: {a.shape[0]} vs {n})"
                )
            arrays[k] = a
        return arrays, int(n or 0)

    def predict(
        self, inputs: Dict[str, np.ndarray]
    ) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
        """Run the forward on ``inputs`` (schema-shaped numpy arrays,
        leading dim = rows).  Pads to the smallest warmed bucket,
        dispatches the HELD executable (zero compiles on the steady
        path), and returns (host outputs sliced to the real rows,
        meta).  ``meta`` carries the weight generation/step the batch
        was computed with — the hot-swap consistency receipt the soak
        tests assert on (every row of one batch = one generation)."""
        w = self._weights  # ONE read: the whole batch binds this record
        if w is None:
            raise NotReadyError(
                "no verified checkpoint loaded (engine.load() found "
                "nothing to serve)"
            )
        n = next(iter(inputs.values())).shape[0]
        bucket = self.bucket_for(n)
        padded = self._pad(inputs, n, bucket)
        dev_batch = {
            k: jax.device_put(v, self._batch_sharding[k])
            for k, v in padded.items()
        }
        fn = self._compiled.get(bucket)
        with self.mesh:
            if fn is not None:
                out = fn(w.params, dev_batch)
            else:
                # Cold bucket: the jit path compiles (counted at the
                # backend_compile seam) — steady state never lands here
                # once warm() ran.
                out = self._jit(w.params, dev_batch)
        host = jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x))[:n], out
        )
        meta = {
            "weights_step": w.step,
            "weights_generation": w.generation,
            "bucket": bucket,
            "rows": n,
        }
        return host, meta


class KVBlockPool:
    """Preallocated paged KV cache: fixed-size blocks in one device
    pool, free-list managed HOST-side (the device only ever sees block
    tables).  Block 0 is the trash block (padding rows of a decode
    batch write there); real sequences allocate from 1..num_blocks-1.

    Allocation is all-or-nothing (``alloc`` returns None rather than a
    partial grant) so a prompt either gets its full block run or waits
    at admission — a half-allocated sequence could neither prefill nor
    free cleanly.

    Blocks are REFCOUNTED so the prefix cache (``serving/prefix.py``)
    can share one filled block across every sequence whose prompt
    starts with its tokens: ``alloc`` grants at refcount 1, ``ref``
    bumps an existing owner, and ``free`` decrements — a block returns
    to circulation only at refcount 0.  A refcount-0 block that the
    prefix cache PUBLISHED is not freed outright: it parks on an LRU
    of cached blocks (its K/V stays valid and claimable) and is
    evicted back to the free list lazily, only when ``alloc`` would
    otherwise come up short — so prefix retention can never starve
    admission.  Sharing is copy-on-write by construction rather than
    by copying: a claiming sequence's writes all land at cache
    positions ≥ its skip offset, i.e. in its own freshly allocated
    blocks — shared blocks are only ever READ through the table, and
    the trailing partial block of any prompt is always private.
    """

    def __init__(
        self,
        layers: int,
        heads: int,
        head_dim: int,
        num_blocks: int,
        block_tokens: int,
        dtype,
        sharding,
    ):
        import jax.numpy as jnp
        from collections import deque

        if num_blocks < 2:
            raise ValueError("pool needs >= 2 blocks (block 0 is trash)")
        self.num_blocks = int(num_blocks)
        self.block_tokens = int(block_tokens)
        self._shape = (layers, num_blocks, block_tokens, heads, head_dim)
        self._dtype = dtype
        self._sharding = sharding
        self.kpool = jax.device_put(jnp.zeros(self._shape, dtype), sharding)
        self.vpool = jax.device_put(jnp.zeros(self._shape, dtype), sharding)
        self._free = deque(range(1, num_blocks))
        self._ref: Dict[int, int] = {}       # owned block -> refcount
        self._published: set = set()          # prefix-indexed blocks
        self._cached: Dict[int, None] = {}    # refcount-0 published, LRU
        self._lock = threading.Lock()
        # Called with a block id when a cached block is evicted, so the
        # prefix index drops its entry before the id can be re-granted.
        self.on_evict = None
        # Called (no args) on reset(): the prefix index drops wholesale
        # without counting the drops as capacity evictions.
        self.on_reset = None
        self.evictions = 0

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1

    @property
    def free_blocks(self) -> int:
        """Allocatable blocks: truly free + evictable cached."""
        return len(self._free) + len(self._cached)

    @property
    def cached_blocks(self) -> int:
        return len(self._cached)

    @property
    def used_blocks(self) -> int:
        """Blocks held by live sequences (cached ones count as free —
        they are reclaimable on demand)."""
        return self.usable_blocks - self.free_blocks

    def occupancy(self) -> float:
        return self.used_blocks / max(1, self.usable_blocks)

    def _evict_locked(self) -> None:
        b = next(iter(self._cached))  # LRU end (insertion order)
        del self._cached[b]
        self._published.discard(b)
        if self.on_evict is not None:
            self.on_evict(b)
        self._free.append(b)
        self.evictions += 1

    def evict_cached(self, n: int = 1) -> int:
        """Evict up to ``n`` LRU cached blocks back to the free list
        (chaos ``serve.prefix.evicted`` forces this; ``alloc`` does it
        lazily under pressure).  Returns how many were evicted."""
        with self._lock:
            k = min(int(n), len(self._cached))
            for _ in range(k):
                self._evict_locked()
            return k

    def alloc(self, n: int) -> Optional[List[int]]:
        """Take ``n`` blocks at refcount 1, or None (never a partial
        grant).  Evicts LRU cached prefix blocks if the free list
        alone is short — retention never starves admission."""
        with self._lock:
            if n > len(self._free) + len(self._cached):
                return None
            while len(self._free) < n:
                self._evict_locked()
            got = [self._free.popleft() for _ in range(n)]
            for b in got:
                self._ref[b] = 1
            return got

    def ref(self, block: int) -> None:
        """Claim a share of an owned or cached block (prefix reuse):
        an owner's refcount bumps; a cached block revives at 1."""
        b = int(block)
        with self._lock:
            if b in self._ref:
                self._ref[b] += 1
            elif b in self._cached:
                del self._cached[b]
                self._ref[b] = 1
            else:
                raise BlockOwnershipError(
                    f"block {b} is neither owned nor cached"
                )

    def refcount(self, block: int) -> int:
        return self._ref.get(int(block), 0)

    def publish(self, block: int) -> None:
        """Mark an owned block as prefix-indexed: at refcount 0 it
        parks on the cached LRU instead of returning to the free
        list."""
        b = int(block)
        with self._lock:
            if b not in self._ref and b not in self._cached:
                raise BlockOwnershipError(
                    f"cannot publish unowned block {b}"
                )
            self._published.add(b)

    def drop_published(self) -> None:
        """Forget every published mark (prefix-pool invalidation on a
        hot swap / rebuild): cached blocks return to the free list;
        blocks still held by live sequences only lose the mark — their
        eventual ``free`` goes straight to the free list."""
        with self._lock:
            for b in self._cached:
                self._free.append(b)
            self._cached.clear()
            self._published.clear()

    def free(self, blocks: Sequence[int]) -> None:
        """Drop one reference per id.  Freeing a block this pool does
        not consider owned raises ``BlockOwnershipError`` — the silent
        pre-guard behaviour let a double free enqueue one id twice and
        hand the block to two sequences."""
        with self._lock:
            for b in blocks:
                b = int(b)
                if b == 0:
                    raise ValueError("block 0 (trash) is never owned")
                n = self._ref.get(b)
                if n is None:
                    raise BlockOwnershipError(
                        f"block {b} freed without being owned "
                        "(double free or stray id)"
                    )
                if n > 1:
                    self._ref[b] = n - 1
                elif b in self._published:
                    del self._ref[b]
                    self._cached[b] = None  # MRU end of the LRU
                else:
                    del self._ref[b]
                    self._free.append(b)

    def reset(self) -> None:
        """Return every block to the free list (engine re-warm /
        tests).  Stale bytes need no scrub: a reused block is fully
        overwritten by prefill, and decode masks never expose
        positions beyond a sequence's written length.  Any prefix
        index over this pool must be invalidated alongside (the
        batcher's generation rekey does; ``on_reset`` fires here as a
        belt-and-braces hook — NOT ``on_evict``, so a routine re-warm
        never inflates capacity-eviction stats)."""
        from collections import deque

        with self._lock:
            if self.on_reset is not None:
                self.on_reset()
            self._ref.clear()
            self._published.clear()
            self._cached.clear()
            self._free = deque(range(1, self.num_blocks))

    def rebuild(self) -> None:
        """Replace the device arrays with fresh zeros, keeping the
        free-list/ownership state.  The recovery path for a failed
        dispatch whose DONATED inputs may already be consumed: the
        old buffers are unusable either way, and the cached contents
        are lost — callers must re-prefill every live sequence (the
        engine bumps ``cache_epoch`` to say so)."""
        import jax.numpy as jnp

        self.kpool = jax.device_put(
            jnp.zeros(self._shape, self._dtype), self._sharding
        )
        self.vpool = jax.device_put(
            jnp.zeros(self._shape, self._dtype), self._sharding
        )


class DecodeEngine(InferenceEngine):
    """KV-cached autoregressive decode on top of the single-shot
    engine: separate prefill and decode executables AOT-lowered from
    abstract shapes and HELD per padded bucket (``warm``'s discipline
    — this jax's ``.lower().compile()`` does not warm the jit dispatch
    cache), with the paged pool buffers DONATED so steady-state decode
    updates the cache in place and performs ZERO XLA compiles.

    Shape discipline:

    - **prefill** compiles per padded prompt bucket (block-aligned
      powers of two of ``block_tokens``), one sequence per dispatch —
      the Orca posture: a joining request pays its own prefill, the
      running decode batch never waits on a stranger's prompt shape.
    - **decode** compiles per active-sequence-count bucket (powers of
      two up to ``max_seqs``); ragged sequence lengths ride ONE
      executable because the block tables absorb the raggedness.
    - **chunk** (ISSUE 14) compiles per (chunk-bucket x past-length-
      bucket): a block-aligned prompt SLICE carrying an explicit cache
      offset, attending causally over every previously-filled position
      through a window-truncated table.  The token batcher feeds these
      under a per-iteration token budget so a long admission never
      stalls the running decode cadence (Sarathi-Serve's stall-free
      posture); the first sampled token is exact vs monolithic
      prefill.

    Weights are passed EXPLICITLY (``current_weights()`` record): the
    token batcher binds one record per iteration, so a hot swap can
    only take effect at a token boundary — and the batcher then
    re-prefills affected sequences against the new weights rather than
    ever mixing generations within one sequence.
    """

    def __init__(
        self,
        model: ModelDef,
        store: Optional[HostDRAMStore] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        max_batch: int = 8,
        seed: int = 0,
        optimizer=None,
        chaos=None,
        max_seqs: int = 8,
        block_tokens: int = 16,
        max_context: Optional[int] = None,
        num_blocks: Optional[int] = None,
        max_chunk_tokens: Optional[int] = None,
        dispatch_timeout: Optional[float] = None,
        tp: int = 1,
    ):
        if model.decode is None:
            raise ValueError(
                f"model {model.name!r} declares no DecodeSpec; it can "
                "only serve single-shot forwards (InferenceEngine)"
            )
        devs = list(devices) if devices is not None else jax.devices()
        tp = int(tp)
        if tp >= 1 and model.decode.heads % tp != 0:
            # Checked BEFORE the base engine builds weight shardings: a
            # non-dividing tp would otherwise surface as an opaque
            # GSPMD shard-shape error from the byte-accounting gauges.
            raise ValueError(
                f"tp {tp} does not divide the model's "
                f"{model.decode.heads} KV heads (attention kernels and "
                "the pool shard their head axis across tp)"
            )
        dp_extent = len(devs) // tp if tp >= 1 and len(devs) % tp == 0 else 1
        if max_batch < dp_extent:
            # The single-shot /predict buckets must shard over the dp
            # extent, but a decode-focused fleet sizes max_batch for
            # generate traffic (decode tensors are replicated over dp,
            # any count works) — lift the single-shot cap instead of
            # refusing to boot.  The lift target is the DP extent
            # (devices / tp), NOT the device count: on a dp×tp mesh the
            # tp devices hold shards of ONE replica, and lifting to
            # len(devs) would over-size every /predict bucket (and its
            # held executable) tp-fold.
            import sys

            print(
                f"[edl-serve] max_batch {max_batch} raised to the "
                f"dp extent {dp_extent} ({len(devs)} devices / tp {tp}; "
                "single-shot bucket floor — decode batching is "
                "unaffected)",
                file=sys.stderr,
            )
            max_batch = dp_extent
        super().__init__(
            model,
            store,
            devices=devs,
            max_batch=max_batch,
            seed=seed,
            optimizer=optimizer,
            chaos=chaos,
            tp=tp,
        )
        spec = model.decode
        self.spec = spec
        self.block_tokens = int(block_tokens)
        ctx = min(max_context or spec.max_len, spec.max_len)
        #: blocks per sequence: the whole context window, block-aligned
        #: (rounded DOWN — a partial trailing block could never be
        #: addressed by the table)
        self.blocks_per_seq = max(1, ctx // self.block_tokens)
        self.max_context = self.blocks_per_seq * self.block_tokens
        self.max_seqs = int(max_seqs)
        if num_blocks is None:
            # Enough for every slot's full context + the trash block.
            num_blocks = self.max_seqs * self.blocks_per_seq + 1
        self._replicated = NamedSharding(self.mesh, P())
        #: KV pools shard their HEAD axis over tp — each device holds
        #: [L, blocks, block_tokens, H/tp, D] — while block tables, the
        #: free list, refcounts and the prefix index stay host-side and
        #: tp-invariant (they speak block ids, never head slices).
        self._kv_sharding = NamedSharding(
            self.mesh, P(None, None, None, "tp", None)
        )
        self.pool = KVBlockPool(
            spec.layers,
            spec.heads,
            spec.head_dim,
            num_blocks,
            self.block_tokens,
            spec.cache_dtype,
            self._kv_sharding,
        )
        self._m_kv_shard_bytes = self.telemetry.gauge(
            "edl_serve_kv_pool_bytes_per_device"
        )
        self._m_kv_shard_bytes.set(self.kv_pool_bytes_per_device())
        #: decode-batch buckets (active sequence counts)
        buckets = []
        b = 1
        while b < self.max_seqs:
            buckets.append(b)
            b *= 2
        buckets.append(self.max_seqs)
        self.decode_buckets: Tuple[int, ...] = tuple(buckets)
        #: padded prompt buckets (block-aligned, capped at the context)
        pbuckets = []
        p = self.block_tokens
        while p < self.max_context:
            pbuckets.append(p)
            p *= 2
        pbuckets.append(self.max_context)
        self.prompt_buckets: Tuple[int, ...] = tuple(pbuckets)
        #: chunked-prefill chunk buckets (ISSUE 14): block-aligned
        #: powers of two up to ``max_chunk_tokens`` — the largest
        #: prompt slice one dispatch may carry.  Small by design: the
        #: chunk IS the prefill/decode interference quantum, so its cap
        #: bounds how long one admission can stall the running batch.
        if max_chunk_tokens is None:
            max_chunk_tokens = 4 * self.block_tokens
        mc = max(
            self.block_tokens,
            min(
                (max_chunk_tokens // self.block_tokens)
                * self.block_tokens,
                self.max_context,
            ),
        )
        self.max_chunk_tokens = mc
        cbuckets = []
        c = self.block_tokens
        while c < mc:
            cbuckets.append(c)
            c *= 2
        cbuckets.append(mc)
        self.chunk_buckets: Tuple[int, ...] = tuple(cbuckets)
        # Pools donated (argnums 3, 4 of (params, tokens, lengths,
        # kpool, vpool, tables)): steady-state decode reuses the cache
        # buffers in place instead of copying the pool every token.
        self._prefill_jit = jax.jit(spec.prefill_fn, donate_argnums=(3, 4))
        self._decode_jit = jax.jit(spec.decode_fn, donate_argnums=(3, 4))
        # chunk_fn's pools sit after the extra offsets arg: (params,
        # tokens, offsets, lengths, kpool, vpool, tables).
        self._chunk_jit = (
            jax.jit(spec.chunk_fn, donate_argnums=(4, 5))
            if spec.chunk_fn is not None
            else None
        )
        #: ("prefill", P) / ("decode", B) / ("chunk", C, window_blocks)
        #: -> held AOT executable
        self._decode_compiled: Dict[Tuple, Any] = {}
        #: bumped whenever the cache contents were lost (pool rebuilt
        #: after a failed dispatch): the token batcher re-prefills
        #: every live sequence when it sees a new epoch, exactly like
        #: a weights-generation change
        self.cache_epoch = 0
        # -- dispatch watchdog (ISSUE 15): the PR 6 deadline-fetch
        # pattern on the SERVING data plane.  A wedged prefill/chunk/
        # decode dispatch (hung device runtime, stuck transfer) would
        # otherwise hang the token batcher's worker thread forever —
        # the same failure shape a wedged gloo collective has in
        # training, with the same answer: run the blocking fetch under
        # a deadline on an abandonable helper thread, and surface
        # expiry as a typed error into the existing pool-rebuild +
        # cache-epoch re-prefill recovery.  ``dispatch_timeout`` <= 0
        # disables the deadline (single-process CPU default — a wedge
        # is not a real failure mode there and the thread hop would tax
        # every token); the ``serve.dispatch.wedged`` chaos trip stays
        # live either way, so the recovery path is testable anywhere.
        if dispatch_timeout is None:
            import os

            dispatch_timeout = float(
                os.environ.get("EDL_SERVE_DISPATCH_TIMEOUT", "0") or 0
            )
        self.dispatch_timeout = float(dispatch_timeout)
        #: chaos source for the wedge trip — defaults to the engine's
        #: schedule; tests may point it elsewhere so a shared schedule's
        #: swap-torn events stay with the engines that should pop them
        self.dispatch_chaos = self.chaos
        self._m_wedged = self.telemetry.counter(
            "edl_serve_dispatch_wedged_total"
        )

        def _wedge_due() -> bool:
            c = self.dispatch_chaos
            return c is not None and bool(c.due("serve.dispatch.wedged"))

        def _wedge_trip(what: str, waited: float) -> None:
            self._m_wedged.inc()
            self.recorder.record(
                "serve.watchdog",
                {"what": what, "waited_s": round(waited, 3)},
            )

        self.watchdog = CollectiveWatchdog(
            timeout=self.dispatch_timeout,
            chaos_check=_wedge_due,
            on_trip=_wedge_trip,
        )

    # -- per-device footprint ------------------------------------------------
    def kv_pool_bytes_per_device(self) -> int:
        """Bytes ONE device holds for BOTH pool planes (k + v): the
        head axis shards over tp, so a tp=2 engine's per-device pool is
        half a tp=1 engine's."""
        shard = self._kv_sharding.shard_shape(self.pool.kpool.shape)
        per_plane = int(np.prod(shard, dtype=np.int64)) * np.dtype(
            self.pool.kpool.dtype
        ).itemsize
        return 2 * per_plane

    # -- buckets ------------------------------------------------------------
    @property
    def max_prompt(self) -> int:
        """Longest admissible prompt: one position must remain for the
        first generated token."""
        return self.max_context - 1

    def prompt_bucket_for(self, n: int) -> int:
        for p in self.prompt_buckets:
            if n <= p:
                return p
        raise ValueError(
            f"prompt of {n} tokens exceeds the context window "
            f"{self.max_context}"
        )

    def decode_bucket_for(self, n: int) -> int:
        for b in self.decode_buckets:
            if n <= b:
                return b
        raise ValueError(
            f"{n} active sequences exceed max_seqs {self.max_seqs}"
        )

    def chunk_bucket_for(self, n: int) -> int:
        for c in self.chunk_buckets:
            if n <= c:
                return c
        raise ValueError(
            f"chunk of {n} tokens exceeds max_chunk_tokens "
            f"{self.max_chunk_tokens}"
        )

    def chunk_window_blocks(self, offset: int, chunk_bucket: int) -> int:
        """Table columns a chunk executable at ``offset`` gathers: the
        smallest prompt bucket covering offset + chunk (so compute
        scales with the filled prefix), in blocks.  This is the
        past-length-bucket half of the (chunk-bucket x past-bucket)
        executable key."""
        return self.prompt_bucket_for(
            min(offset + chunk_bucket, self.max_context)
        ) // self.block_tokens

    def coerce_prompt(self, inputs: Dict[str, Any]) -> np.ndarray:
        """Validate one generate request's prompt: a 1-D (or [1, n])
        int token row, 1 <= n <= max_prompt."""
        if "tokens" not in inputs:
            raise ValueError(
                "generate request missing 'tokens' (the prompt row)"
            )
        a = np.asarray(inputs["tokens"])
        if a.ndim == 2 and a.shape[0] == 1:
            a = a[0]
        if a.ndim != 1:
            raise ValueError(
                f"prompt must be one token row, got shape {a.shape}"
            )
        if not np.issubdtype(a.dtype, np.integer):
            raise ValueError(f"prompt dtype {a.dtype} is not integral")
        if a.shape[0] > self.max_prompt:
            # Typed admission rejection (ISSUE 14 satellite): the HTTP
            # front 400s it and the chunked scheduler never starts a
            # prompt it could not finish.
            raise PromptTooLongError(
                f"prompt of {a.shape[0]} tokens exceeds max_prompt "
                f"{self.max_prompt} (context {self.max_context})"
            )
        if a.shape[0] < 1:
            raise ValueError(
                f"prompt of {a.shape[0]} tokens outside [1, "
                f"{self.max_prompt}] (context {self.max_context})"
            )
        return a.astype(np.int32)

    # -- warm ---------------------------------------------------------------
    def warm(self, buckets: Optional[Sequence[int]] = None) -> int:
        """Single-shot buckets (the /predict path) PLUS the decode
        stack: one prefill executable per prompt bucket, one decode
        executable per sequence-count bucket."""
        warmed = super().warm(buckets)
        return warmed + self.warm_decode()

    def _abs_decode_args(self, key: Tuple):
        kind = key[0]
        spec = self.spec
        rep = self._replicated
        # Pools carry the tp head-sharding; params their partition-rule
        # shardings; host-fed inputs (tokens/lengths/tables/offsets)
        # stay replicated — block tables are tp-invariant.
        pool = jax.ShapeDtypeStruct(
            self.pool.kpool.shape,
            self.pool.kpool.dtype,
            sharding=self._kv_sharding,
        )
        abs_params = jax.tree_util.tree_map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            getattr(self._abstract_params, "params", self._abstract_params),
            self._param_shardings,
        )
        if kind in ("prefill", "chunk"):
            tokens = jax.ShapeDtypeStruct(
                (1, key[1]), np.int32, sharding=rep
            )
            rows = 1
        else:
            tokens = jax.ShapeDtypeStruct((key[1],), np.int32, sharding=rep)
            rows = key[1]
        lengths = jax.ShapeDtypeStruct((rows,), np.int32, sharding=rep)
        if kind == "chunk":
            # The chunk executable's table is TRUNCATED to its window
            # (past-bucket + chunk-bucket blocks): the gather — and so
            # the attention compute — scales with the filled prefix.
            fn = spec.chunk_fn
            offsets = jax.ShapeDtypeStruct((rows,), np.int32, sharding=rep)
            tables = jax.ShapeDtypeStruct(
                (rows, key[2]), np.int32, sharding=rep
            )
            return fn, (
                abs_params, tokens, offsets, lengths, pool, pool, tables
            ), (4, 5)
        tables = jax.ShapeDtypeStruct(
            (rows, self.blocks_per_seq), np.int32, sharding=rep
        )
        fn = spec.prefill_fn if kind == "prefill" else spec.decode_fn
        return fn, (abs_params, tokens, lengths, pool, pool, tables), (3, 4)

    def _chunk_keys(self) -> List[Tuple]:
        """Every (chunk-bucket x past-length-bucket) executable key:
        chunk buckets cross the window buckets (prompt buckets, in
        blocks) that can contain them."""
        keys = []
        for c in self.chunk_buckets:
            for w in self.prompt_buckets:
                if w >= c:
                    keys.append(("chunk", c, w // self.block_tokens))
        return keys

    def warm_decode(self) -> int:
        """AOT-compile + HOLD every prefill/decode/chunk bucket from
        abstract shapes (zero device allocation).  Idempotent."""
        warmed = 0
        todo: List[Tuple] = [("prefill", p) for p in self.prompt_buckets]
        todo += [("decode", b) for b in self.decode_buckets]
        if self.spec.chunk_fn is not None:
            todo += self._chunk_keys()
        for key in todo:
            if key in self._decode_compiled:
                continue
            fn, abs_args, donate = self._abs_decode_args(key)
            t0 = time.perf_counter()
            with self.mesh:
                self._decode_compiled[key] = jax.jit(
                    fn, donate_argnums=donate
                ).lower(*abs_args).compile()
            dt = time.perf_counter() - t0
            self._m_compile_seconds.observe(dt)
            self.recorder.record(
                "serve.warm",
                {
                    "bucket": key[1],
                    "kind": key[0],
                    "model": self.model.name,
                },
                timing={"seconds": round(dt, 6)},
            )
            warmed += 1
        return warmed

    @property
    def warm_decode_buckets(self) -> Tuple[Tuple[str, int], ...]:
        return tuple(sorted(self._decode_compiled))

    # -- the decode request path --------------------------------------------
    def _put(self, a: np.ndarray):
        return jax.device_put(a, self._replicated)

    def _run(
        self, key: Tuple, params, tokens, lengths, tables, offsets=None
    ):
        head = (params, self._put(tokens))
        if key[0] == "chunk":
            head = head + (self._put(offsets),)
        args = head + (
            self._put(lengths),
            self.pool.kpool,
            self.pool.vpool,
            self._put(tables),
        )
        fn = self._decode_compiled.get(key)

        def dispatch():
            # Dispatch AND device fetch under one deadline: a wedged
            # runtime can hang either the call or the blocking
            # device_get, and both must surface as a trip, not a
            # stuck worker thread.
            with self.mesh:
                if fn is not None:
                    ids, kp, vp = fn(*args)
                else:
                    # Cold bucket (counted at the backend_compile seam)
                    # — steady state never lands here once warm() ran.
                    jfn = {
                        "prefill": self._prefill_jit,
                        "chunk": self._chunk_jit,
                        "decode": self._decode_jit,
                    }[key[0]]
                    ids, kp, vp = jfn(*args)
            return np.asarray(jax.device_get(ids)), kp, vp

        try:
            out, kp, vp = self.watchdog.fetch(dispatch, what=key[0])
        except CollectiveTimeout as e:
            # Wedged dispatch (deadline expiry or the chaos trip): the
            # DONATED pools may be half-consumed by the abandoned
            # fetch, so rebuild + epoch-bump exactly like a failed
            # dispatch — then raise the RECOVERABLE typed error so the
            # batcher re-prefills live sequences instead of rejecting
            # them.
            self.pool.rebuild()
            self.cache_epoch += 1
            raise DispatchWedgedError(str(e)) from e
        except BaseException:
            # The pools were DONATED: after a failed dispatch the old
            # buffers may already be consumed, so keeping them would
            # poison every later call ("buffer has been deleted").
            # Rebuild fresh zeros and bump the cache epoch — the
            # batcher re-prefills every live sequence.
            self.pool.rebuild()
            self.cache_epoch += 1
            raise
        # Rebind the (donated) pools: the returned buffers ARE the
        # cache after this token.
        self.pool.kpool = kp
        self.pool.vpool = vp
        return out

    def prefill(
        self, weights: _Weights, prompt: np.ndarray, table_row: np.ndarray
    ) -> int:
        """Run one sequence's prompt (1-D int32, true length) through
        the prefill executable for its padded bucket.  ``table_row``:
        the sequence's block table [blocks_per_seq] (unallocated tail
        = trash block 0).  Returns the first generated token."""
        plen = int(prompt.shape[0])
        bucket = self.prompt_bucket_for(plen)
        tok = np.zeros((1, bucket), np.int32)
        tok[0, :plen] = prompt
        ids = self._run(
            ("prefill", bucket),
            weights.params,
            tok,
            np.asarray([plen], np.int32),
            np.asarray(table_row, np.int32)[None],
        )
        return int(ids[0])

    def prefill_chunk(
        self,
        weights: _Weights,
        chunk: np.ndarray,
        offset: int,
        table_row: np.ndarray,
    ) -> int:
        """Run ONE block-aligned prompt slice (1-D int32, true length)
        at cache ``offset`` through the chunk executable for its
        (chunk-bucket x past-length-bucket) pair.  Non-final chunks
        must be block_tokens multiples so the next chunk's offset stays
        block-aligned; the final chunk pads to its bucket like
        monolithic prefill.  ``table_row`` is the sequence's FULL block
        table — the window truncation happens here.  Returns the greedy
        id read at the chunk's last real position (the first sampled
        token when this is the prompt's final chunk)."""
        if self.spec.chunk_fn is None:
            raise ValueError(
                f"model {self.model.name!r} declares no chunk_fn; use "
                "monolithic prefill"
            )
        clen = int(chunk.shape[0])
        offset = int(offset)
        if offset % self.block_tokens != 0:
            raise ValueError(
                f"chunk offset {offset} not block-aligned "
                f"(block_tokens {self.block_tokens})"
            )
        bucket = self.chunk_bucket_for(clen)
        if offset + bucket > self.max_context:
            # A padded bucket past the window would clamp the scatter's
            # table gather and silently corrupt the last block's K/V —
            # fail loudly instead; the batcher caps its chunks so the
            # bucket always fits.
            raise ValueError(
                f"chunk bucket {bucket} at offset {offset} overruns the "
                f"context window {self.max_context}; split the chunk"
            )
        wblk = self.chunk_window_blocks(offset, bucket)
        tok = np.zeros((1, bucket), np.int32)
        tok[0, :clen] = chunk
        ids = self._run(
            ("chunk", bucket, wblk),
            weights.params,
            tok,
            np.asarray([offset + clen], np.int32),
            np.asarray(table_row, np.int32)[None, :wblk],
            offsets=np.asarray([offset], np.int32),
        )
        return int(ids[0])

    def decode_step(
        self,
        weights: _Weights,
        tokens: np.ndarray,
        lengths: np.ndarray,
        tables: np.ndarray,
    ) -> np.ndarray:
        """One token of compute for a padded decode batch.  ``tokens``
        [n]: each row's last token; ``lengths`` [n]: its position;
        ``tables`` [n, blocks_per_seq].  Padding rows point at the
        trash block with length 0.  Returns the next ids [n]."""
        n = int(tokens.shape[0])
        return self._run(
            ("decode", n),
            weights.params,
            np.asarray(tokens, np.int32),
            np.asarray(lengths, np.int32),
            np.asarray(tables, np.int32),
        )

    # -- live KV sequence migration (device<->host block movement) ------

    def export_kv(
        self, block_ids: Sequence[int]
    ) -> Tuple[np.ndarray, np.ndarray]:
        """Gather a sequence's filled K/V blocks to host memory for
        migration: [layers, n, block_tokens, heads, head_dim] per
        plane.  Must run at a token boundary with the batcher frozen —
        the next donated dispatch invalidates the pool buffers these
        reads come from."""
        ids = np.asarray(list(block_ids), np.int32)
        k = np.asarray(jax.device_get(self.pool.kpool[:, ids]))
        v = np.asarray(jax.device_get(self.pool.vpool[:, ids]))
        return k, v

    def import_kv(
        self,
        block_ids: Sequence[int],
        k: np.ndarray,
        v: np.ndarray,
    ) -> None:
        """Scatter migrated host K/V planes into freshly granted pool
        slots (the dest half of a live migration).  Rebinds the pool
        arrays like ``_run`` does after a donated dispatch, keeping the
        head-sharded layout the held executables were lowered for.
        The WIRE format stays tp-invariant full-head blocks
        (``export_kv`` gathers shards to host), so a sequence can
        migrate between replicas of different tp."""
        import jax.numpy as jnp

        pool = self.pool
        ids = jnp.asarray(list(block_ids), jnp.int32)
        pool.kpool = jax.device_put(
            pool.kpool.at[:, ids].set(jnp.asarray(k, pool.kpool.dtype)),
            self._kv_sharding,
        )
        pool.vpool = jax.device_put(
            pool.vpool.at[:, ids].set(jnp.asarray(v, pool.vpool.dtype)),
            self._kv_sharding,
        )
