"""Content-addressed KV prefix cache: shared-prefix admissions skip
straight to the first cold block.

At millions of users, serving traffic is dominated by shared prefixes
— system prompts, few-shot templates, multi-turn history — yet a
plain paged-KV admission re-prefills every prompt from token 0.  This
module is the vLLM prefix-caching recipe (PAPERS.md) on the repo's
TPU posture: a REPLICA-LOCAL, content-addressed index over the
``KVBlockPool``'s already-filled blocks.

**Hash scheme.**  Prompts are hashed per BLOCK with a chain hash:

    h_0 = crc32(block 0 token bytes, seed)
    h_i = crc32(block i token bytes, h_{i-1})

so ``h_i`` names the entire prefix up to and including block i, not
just block i's own tokens — two prompts share an index entry iff they
share everything before it.  A lookup walks the chain block by block
and returns the longest run of already-published blocks.  CRC32 is
not collision-proof, so every entry stores ``(h_prev, token bytes)``
and a match requires BOTH to equal the probe's — a colliding hash is
a miss, never someone else's K/V (the ``serve.prefix.hash.skew``
chaos point forces this rejection path).

**Claiming is refcounting, not copying.**  ``claim`` bumps each
matched block's refcount (``KVBlockPool.ref``) and the admission
seeds the sequence's block run + table with the claimed ids; the
chunked-prefill FIFO then starts at ``skip = matched_blocks *
block_tokens`` — the first cold block.  Copy-on-write needs no copy:
the claimer's writes all land at cache positions ≥ ``skip``, i.e. in
its own private blocks, and the trailing partial block of any prompt
is never published, so it is ALWAYS private.  ``skip`` is capped at
``((plen - 1) // block_tokens) * block_tokens`` so at least the final
prompt token is always prefilled — that final chunk produces the
first sampled token, which is why a fully-cached prompt's TTFT
collapses to roughly ONE chunk dispatch rather than zero.

**Publication and eviction.**  When a sequence's prefill completes,
its fully-filled prompt blocks are published into the index; its own
refcount keeps them alive while it decodes, and at refcount 0 a
published block parks on the pool's cached LRU instead of returning
to the free list.  ``alloc`` under pressure evicts that LRU lazily
(``pool.on_evict`` drops the index entry first), so retention can
never starve admission.

**Generation keying.**  The index is keyed by ``(weights generation,
cache_epoch)``: a hot swap or a pool rebuild changes the key and
``rekey`` invalidates the WHOLE index atomically (and releases the
pool's cached blocks) — a reused block can never carry
old-generation K/V, which is what makes reused-block decode
bit-identical to cold prefill.
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

_H_SEED = 0x45444C50  # "EDLP"


class _Entry:
    """One published block: keyed in the index by its chain hash."""

    __slots__ = ("block", "h_prev", "tokens")

    def __init__(self, block: int, h_prev: int, tokens: bytes):
        self.block = block
        self.h_prev = h_prev
        self.tokens = tokens


def chain_hashes(prompt: np.ndarray, block_tokens: int) -> List[int]:
    """The per-block chain hashes of every FULLY-FILLED block of
    ``prompt`` (the trailing partial block is never hashed — it is
    always private)."""
    bt = int(block_tokens)
    toks = np.ascontiguousarray(np.asarray(prompt, np.int32))
    out: List[int] = []
    h = _H_SEED
    for i in range(len(toks) // bt):
        h = zlib.crc32(toks[i * bt:(i + 1) * bt].tobytes(), h)
        out.append(h)
    return out


class PrefixCache:
    """Replica-local content-addressed index over a ``KVBlockPool``'s
    published blocks.  All mutation happens on the batcher worker
    thread except ``_on_evict``, which the pool may call from any
    allocating thread (migration receiver grants) — both sides are
    serialized by the pool's lock plus GIL-atomic dict ops here.
    """

    def __init__(self, pool, block_tokens: int, chaos=None):
        self.pool = pool
        self.block_tokens = int(block_tokens)
        self.chaos = chaos
        #: (weights generation, cache_epoch) the index was built under
        self.key: Optional[Tuple[int, int]] = None
        self._index: Dict[int, _Entry] = {}   # chain hash -> entry
        self._by_block: Dict[int, int] = {}   # block id -> chain hash
        self.stats = {
            "hits": 0, "misses": 0, "blocks_reused": 0,
            "evictions": 0, "invalidations": 0, "skew_rejected": 0,
        }
        pool.on_evict = self._on_evict
        pool.on_reset = self._on_reset

        from edl_tpu import telemetry

        reg = telemetry.get_registry()
        self.recorder = telemetry.get_recorder()
        self._m_hits = reg.counter("edl_serve_prefix_hits_total")
        self._m_misses = reg.counter("edl_serve_prefix_misses_total")
        self._m_reused = reg.counter("edl_serve_prefix_blocks_reused_total")
        self._m_evictions = reg.counter("edl_serve_prefix_evictions_total")
        self._g_ratio = reg.gauge("edl_serve_prefix_hit_ratio")

    # -- index maintenance --------------------------------------------------
    def __len__(self) -> int:
        return len(self._index)

    def _on_evict(self, block: int) -> None:
        h = self._by_block.pop(int(block), None)
        if h is not None:
            self._index.pop(h, None)
        self.stats["evictions"] += 1
        self._m_evictions.inc()

    def _on_reset(self) -> None:
        """Pool reset (engine re-warm / tests): drop the whole index.
        Unlike ``_on_evict`` this does not touch eviction stats — a
        reset is not capacity pressure, and conflating the two would
        skew the eviction counters the observability relies on."""
        self._index.clear()
        self._by_block.clear()

    def rekey(self, key: Tuple[int, int]) -> bool:
        """Bind the index to ``(generation, cache_epoch)``; a changed
        key invalidates everything the previous generation published —
        atomically, BEFORE any admission under the new weights can
        look up.  Returns True if an invalidation happened."""
        if key == self.key:
            return False
        invalidated = self.key is not None
        prev = self.key
        self.key = key
        if invalidated:
            dropped = len(self._index)
            self._index.clear()
            self._by_block.clear()
            self.pool.drop_published()
            self.stats["invalidations"] += 1
            # Entry/reuse counts at the moment of a swap are
            # scheduling-dependent; they ride the non-identity timing
            # field so same-seed journals stay bit-identical.
            self.recorder.record(
                "serve.prefix",
                {
                    "outcome": "invalidated",
                    "from": list(prev),
                    "to": list(key),
                },
                timing={"entries_dropped": dropped,
                        "hits": self.stats["hits"],
                        "blocks_reused": self.stats["blocks_reused"]},
            )
        return invalidated

    # -- admission side -----------------------------------------------------
    def claim(self, prompt: np.ndarray) -> Tuple[List[int], int]:
        """Walk the chain for ``prompt`` and claim (refcount-bump) the
        longest published run.  Returns ``(blocks, skip_tokens)`` —
        empty/0 on a miss.  The run is capped one block short of the
        prompt's end so the final token is always prefilled cold."""
        bt = self.block_tokens
        plen = int(len(prompt))
        limit = (plen - 1) // bt  # max claimable blocks
        if limit <= 0:
            return [], 0
        toks = np.ascontiguousarray(np.asarray(prompt, np.int32))
        skew: Optional[bool] = None
        run: List[int] = []
        h_prev = _H_SEED
        for i in range(limit):
            blk = toks[i * bt:(i + 1) * bt].tobytes()
            h = zlib.crc32(blk, h_prev)
            ent = self._index.get(h)
            if ent is None:
                break
            if skew is None:
                # Consulted lazily, once per lookup, and only when a
                # candidate entry exists: a cold lookup has nothing to
                # verify and must not consume the trip.
                skew = self.chaos is not None and bool(
                    self.chaos.due("serve.prefix.hash.skew")
                )
            if skew or ent.h_prev != h_prev or ent.tokens != blk:
                # A chain-hash collision (or a chaos-forced skew
                # simulating one): the stored (h_prev, tokens) pair is
                # the ground truth and it disagrees — treat as a miss
                # rather than serve someone else's K/V.
                self.stats["skew_rejected"] += 1
                self.recorder.record(
                    "serve.prefix",
                    {"outcome": "hash_skew_rejected",
                     "forced": bool(skew)},
                    timing={"at_block": i},
                )
                break
            try:
                self.pool.ref(ent.block)
            except Exception:
                # Raced an eviction between index read and claim —
                # the entry is already being dropped; stop the run.
                break
            if self._by_block.get(ent.block) != h:
                # The block was evicted AND re-granted to another
                # sequence between the lock-free index read and the
                # ref (one allocating lock hold can do both), so the
                # ref landed on a now-foreign private block.
                # ``_on_evict`` pops ``_by_block`` under the pool lock
                # before the id can be re-granted, and a ref'd block
                # can no longer be evicted — so this check is
                # race-free: mismatch means foreign, drop the share.
                self.pool.free([ent.block])
                break
            run.append(ent.block)
            h_prev = h
        skip = len(run) * bt
        if run:
            self.stats["hits"] += 1
            self.stats["blocks_reused"] += len(run)
            self._m_hits.inc()
            self._m_reused.inc(len(run))
        else:
            self.stats["misses"] += 1
            self._m_misses.inc()
        total = self.stats["hits"] + self.stats["misses"]
        if total:
            self._g_ratio.set(self.stats["hits"] / total)
        return run, skip

    def publish(self, prompt: np.ndarray, blocks: List[int]) -> int:
        """Index a finished prefill's fully-filled prompt blocks (the
        trailing partial block stays private).  Blocks already indexed
        — including the ones this sequence itself claimed — are left
        alone.  Returns how many NEW entries were added."""
        bt = self.block_tokens
        toks = np.ascontiguousarray(np.asarray(prompt, np.int32))
        full = min(int(len(toks)) // bt, len(blocks))
        added = 0
        h_prev = _H_SEED
        for i in range(full):
            blk = toks[i * bt:(i + 1) * bt].tobytes()
            h = zlib.crc32(blk, h_prev)
            if h not in self._index and blocks[i] not in self._by_block:
                b = int(blocks[i])
                self.pool.publish(b)
                self._index[h] = _Entry(b, h_prev, blk)
                self._by_block[b] = h
                added += 1
            h_prev = h
        return added

    # -- chaos --------------------------------------------------------------
    def chaos_tick(self) -> None:
        """Fire due ``serve.prefix.evicted`` trips: force-evict LRU
        cached blocks as if allocation pressure demanded it."""
        if self.chaos is None:
            return
        for ev in self.chaos.due("serve.prefix.evicted"):
            want = int(ev.arg or 1)
            got = self.pool.evict_cached(want)
            self.recorder.record(
                "serve.prefix",
                {"outcome": "chaos_evicted", "requested": want},
                timing={"evicted": got},
            )
