from edl_tpu.utils.quantity import (
    parse_cpu_milli,
    parse_memory_mega,
    parse_quantity_bytes,
    format_cpu_milli,
    format_memory_mega,
    add_resource_list,
)
from edl_tpu.utils.retry import GiveUpError, RetryPolicy

__all__ = [
    "parse_cpu_milli",
    "parse_memory_mega",
    "parse_quantity_bytes",
    "format_cpu_milli",
    "format_memory_mega",
    "add_resource_list",
    "GiveUpError",
    "RetryPolicy",
]
