"""Kubernetes-style resource quantity parsing.

The reference reads trainer resource quantities through client-go's
``resource.Quantity`` (``pkg/autoscaler.go:39-52`` —
``TrainerGPULimit``/``TrainerCPURequestMilli``/``TrainerMemRequestMega``)
and sums them with ``AddResourceList`` (``pkg/utils.go:23-34``).  We keep
quantities as plain strings in specs and normalize at the edge:

- CPU      -> integer **millicores** ("250m" -> 250, "2" -> 2000)
- memory   -> integer **mebibytes**  ("1Gi" -> 1024, "500M" -> ~477)
- tpu/gpu  -> integer chip count

No kubernetes client library is required.
"""

from __future__ import annotations

import math
import re
from typing import Dict, Mapping, Union

Quantity = Union[str, int, float]

# k8s suffix multipliers, decimal + binary.  Ref semantics: client-go
# resource.Quantity (vendored in the reference; not reimplemented here —
# we support the common subset used in TrainingJob specs).
_DECIMAL = {"": 1, "k": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15, "E": 10**18}
_BINARY = {"Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60}

_QTY_RE = re.compile(r"^\s*([+-]?[0-9]*\.?[0-9]+)\s*([a-zA-Z]*)\s*$")


def _split(q: Quantity) -> tuple[float, str]:
    if isinstance(q, (int, float)):
        return float(q), ""
    m = _QTY_RE.match(q)
    if not m:
        raise ValueError(f"invalid quantity: {q!r}")
    return float(m.group(1)), m.group(2)


def parse_cpu_milli(q: Quantity) -> int:
    """CPU quantity -> millicores (reference: TrainerCPURequestMilli,
    ``pkg/autoscaler.go:44-47``: ``q.ScaledValue(resource.Milli)``)."""
    if q in ("", None):
        return 0
    value, suffix = _split(q)
    if suffix == "m":
        return int(round(value))
    if suffix in _DECIMAL:
        return int(round(value * _DECIMAL[suffix] * 1000))
    if suffix in _BINARY:
        return int(round(value * _BINARY[suffix] * 1000))
    raise ValueError(f"invalid cpu quantity: {q!r}")


def parse_quantity_bytes(q: Quantity) -> int:
    """Memory quantity -> bytes."""
    if q in ("", None):
        return 0
    value, suffix = _split(q)
    if suffix in _BINARY:
        return int(round(value * _BINARY[suffix]))
    if suffix in _DECIMAL:
        return int(round(value * _DECIMAL[suffix]))
    if suffix == "m":  # milli-bytes: legal in k8s, round up to bytes
        return int(math.ceil(value / 1000.0))
    raise ValueError(f"invalid memory quantity: {q!r}")


def parse_memory_mega(q: Quantity) -> int:
    """Memory quantity -> MiB (reference: TrainerMemRequestMega,
    ``pkg/autoscaler.go:49-52``: ``q.ScaledValue(resource.Mega)`` — the
    reference uses decimal mega; we use MiB uniformly on both the spec
    and inventory sides, so comparisons stay consistent)."""
    return parse_quantity_bytes(q) // (2**20)


def parse_count(q: Quantity) -> int:
    """Integer device count (gpu/tpu chips).  Reference: TrainerGPULimit
    ``pkg/autoscaler.go:39-42``."""
    if q in ("", None):
        return 0
    value, suffix = _split(q)
    if suffix not in ("",):
        raise ValueError(f"device count must be a bare integer: {q!r}")
    if value != int(value):
        raise ValueError(f"device count must be integral: {q!r}")
    if value < 0:
        raise ValueError(f"device count must be >= 0: {q!r}")
    return int(value)


def format_cpu_milli(milli: int) -> str:
    return f"{milli}m"


def format_memory_mega(mega: int) -> str:
    return f"{mega}Mi"


def add_resource_list(a: Dict[str, int], b: Mapping[str, int]) -> Dict[str, int]:
    """Element-wise addition of normalized resource dicts into ``a``.

    Reference: ``AddResourceList`` (``pkg/utils.go:23-34``) — same
    semantics (keys absent in ``a`` are inserted), minus the reference's
    redundant double-write quirk (SURVEY.md §2.1 quirks)."""
    for name, v in b.items():
        a[name] = a.get(name, 0) + v
    return a
