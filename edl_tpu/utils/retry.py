"""One retry policy for every control- and data-plane loop.

Before this module, retry behavior was scattered and inconsistent:
``coord_service.HTTPCoordinator`` hardcoded a ``0.2 * 2**attempt``
backoff, ``controller/coordclient.py`` swallowed ``ConnectionError``
and hoped the next 5s tick worked, and ``Cluster.update_parallelism``
looped on ``ConflictError`` with no backoff at all.  Every robustness
claim this repo makes (resize under churn, actuation under conflict
storms) rests on those loops behaving predictably — so there is
exactly one policy type, and the chaos suite (``tests/test_chaos.py``)
tests against it.

Design points:

- **Capped exponential backoff** with a **deterministic jitter**:
  the jitter for attempt ``k`` is a pure function of ``(seed, k)``
  (crc32-derived), so a seeded chaos run replays the identical delay
  sequence — bit-reproducible soak runs need no real randomness.
- **Deadline**: a total wall-clock budget across all attempts, so a
  caller inside a 5s control tick can bound its worst case.
- **Give-up classification**: ``retryable`` decides which exceptions
  are transient; non-retryable ones surface immediately.  Exhaustion
  raises the typed ``GiveUpError`` so callers can tell "the operation
  failed" from "the operation kept failing transiently" — the
  autoscaler logs-and-skips the latter instead of crashing its tick.
"""

from __future__ import annotations

import time
import zlib
from dataclasses import dataclass
from typing import Callable, Optional


class GiveUpError(RuntimeError):
    """A retried operation exhausted its attempts or deadline.

    ``last_error`` is the final transient failure (also chained as
    ``__cause__``); ``attempts`` is how many tries ran."""

    def __init__(self, msg: str, last_error: Optional[BaseException] = None,
                 attempts: int = 0):
        super().__init__(msg)
        self.last_error = last_error
        self.attempts = attempts


def _unit_hash(seed: int, attempt: int) -> float:
    """Deterministic uniform-ish value in [0, 1) from (seed, attempt)."""
    return zlib.crc32(f"{seed}:{attempt}".encode()) / 2**32


@dataclass(frozen=True)
class RetryPolicy:
    """Deadline + capped exponential backoff + deterministic jitter.

    ``max_attempts``: total tries (>= 1).
    ``base_delay``: sleep after the first failure, seconds.
    ``max_delay``: backoff cap.
    ``multiplier``: exponential growth per attempt.
    ``deadline``: optional total wall-clock budget (seconds) across all
    attempts; a sleep that would overshoot it gives up instead.
    ``jitter``: fraction of each delay randomized deterministically —
    delay ``d`` becomes ``d * (1 - jitter + 2*jitter*h)`` for a hash
    ``h`` in [0,1) derived from ``(seed, attempt)``.
    """

    max_attempts: int = 3
    base_delay: float = 0.2
    max_delay: float = 5.0
    multiplier: float = 2.0
    deadline: Optional[float] = None
    jitter: float = 0.25

    def delay(self, attempt: int, seed: int = 0) -> float:
        """Backoff to sleep after failed attempt ``attempt`` (0-based)."""
        raw = min(self.base_delay * self.multiplier**attempt, self.max_delay)
        if not self.jitter:
            return raw
        h = _unit_hash(seed, attempt)
        return raw * (1.0 - self.jitter + 2.0 * self.jitter * h)

    def run(
        self,
        fn: Callable,
        retryable: Callable[[BaseException], bool] = lambda e: True,
        seed: int = 0,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
        describe: str = "",
    ):
        """Call ``fn()`` under this policy; return its result.

        Exceptions ``retryable`` rejects re-raise immediately (the
        server answered with a real error — not transient).  When the
        attempts or the deadline run out, raises ``GiveUpError``
        chaining the last transient failure.  ``sleep``/``clock`` are
        injectable so tests and chaos runs never wait on real time."""
        start = clock()
        attempts = max(1, self.max_attempts)
        last: Optional[BaseException] = None
        tried = 0
        what = describe or getattr(fn, "__name__", "operation")
        for attempt in range(attempts):
            tried = attempt + 1
            try:
                return fn()
            # Exception, not BaseException: KeyboardInterrupt/SystemExit
            # must never be classified transient and retried.
            except Exception as e:
                if not retryable(e):
                    raise
                last = e
                # Telemetry: every absorbed transient failure is
                # counted and journaled — "the run retried 40 times
                # before the give-up" is exactly the post-mortem signal
                # that used to vanish (flight-recorder step/generation
                # come from the recorder's ambient context).
                _note_retry(what, attempt, e)
                if attempt + 1 >= attempts:
                    break
                d = self.delay(attempt, seed)
                if (
                    self.deadline is not None
                    and clock() - start + d > self.deadline
                ):
                    break
                sleep(d)
        _note_giveup(what, tried)
        raise GiveUpError(
            f"{what} gave up after {tried} attempt(s): {last}",
            last_error=last,
            attempts=tried,
        ) from last


def _note_retry(op: str, attempt: int, err: BaseException) -> None:
    from edl_tpu import telemetry

    telemetry.get_registry().counter("edl_retry_attempts_total").inc(op=op)
    telemetry.get_recorder().record(
        "retry",
        {"op": op, "attempt": attempt, "error": type(err).__name__},
    )


def _note_giveup(op: str, attempts: int) -> None:
    from edl_tpu import telemetry

    telemetry.get_registry().counter("edl_retry_giveups_total").inc(op=op)
    telemetry.get_recorder().record(
        "retry.giveup", {"op": op, "attempts": attempts}
    )
