"""Opt-in ``jax.profiler`` integration.

The reference has no tracing at all (SURVEY.md §5.1: closest thing is
debug logging of autoscaler dry runs).  The TPU rebuild's hot paths —
the compiled train step and the resize window — get first-class device
traces:

- Set ``EDL_PROFILE_DIR=/some/dir`` (or pass ``profile_dir``) and the
  elastic runtime captures a TensorBoard-loadable trace of the first
  ``EDL_PROFILE_STEPS`` (default 10) steps after startup, with each
  step wrapped in a ``StepTraceAnnotation`` and each resize phase in a
  named ``TraceAnnotation`` so the trace viewer separates
  flush/re-mesh/restore from stepping.
- ``annotate(name)`` is a no-op-cheap context manager usable anywhere
  in the runtime (it only touches the profiler when a trace is live).

Nothing here activates unless the env var / argument is set: the
default path adds one attribute check per step.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Optional


class StepProfiler:
    """Captures a bounded device trace of the training hot loop."""

    def __init__(
        self,
        profile_dir: Optional[str] = None,
        max_steps: Optional[int] = None,
    ):
        self.profile_dir = profile_dir or os.environ.get("EDL_PROFILE_DIR", "")
        self.max_steps = (
            max_steps
            if max_steps is not None
            else int(os.environ.get("EDL_PROFILE_STEPS", "10"))
        )
        self._live = False
        self._steps_seen = 0

    @property
    def enabled(self) -> bool:
        return bool(self.profile_dir)

    @property
    def tracing(self) -> bool:
        """Whether a bounded trace is LIVE right now (enabled stays
        true for the whole process; this window closes after
        ``max_steps``) — async callers sync their in-flight device
        work only inside this window."""
        return self._live

    def maybe_start(self) -> None:
        if not self.enabled or self._live or self._steps_seen > 0:
            return
        import jax

        os.makedirs(self.profile_dir, exist_ok=True)
        jax.profiler.start_trace(self.profile_dir)
        self._live = True

    def step(self, step_num: int):
        """Context for one train step; stops the trace after max_steps."""
        if not self._live:
            return _null_ctx()
        import jax

        self._steps_seen += 1
        return jax.profiler.StepTraceAnnotation("train", step_num=step_num)

    def maybe_stop(self) -> None:
        if self._live and self._steps_seen >= self.max_steps:
            self.stop()

    def stop(self) -> None:
        if not self._live:
            return
        import jax

        try:
            jax.profiler.stop_trace()
        finally:
            self._live = False


@contextmanager
def _null_ctx():
    yield


def annotate(name: str):
    """Named trace region (resize phases, checkpoint flush, ...).
    Free when no trace is live — jax's TraceMe is a no-op then."""
    import jax

    return jax.profiler.TraceAnnotation(name)
