"""Opt-in ``jax.profiler`` integration.

The reference has no tracing at all (SURVEY.md §5.1: closest thing is
debug logging of autoscaler dry runs).  The TPU rebuild's hot paths —
the compiled train step and the resize window — get first-class device
traces:

- Set ``EDL_PROFILE_DIR=/some/dir`` (or pass ``profile_dir``) and the
  elastic runtime captures a TensorBoard-loadable trace of a bounded
  window of ``EDL_PROFILE_STEPS`` (default 10) steps, with each step
  wrapped in a ``StepTraceAnnotation`` and each resize phase in a
  named ``TraceAnnotation`` so the trace viewer separates
  flush/re-mesh/restore from stepping.
- The window opens at startup by default; ``EDL_PROFILE_AT_STEP=N``
  defers it until the global step counter reaches N (capture a LATER
  regression window, e.g. around a known-bad resize), and
  ``EDL_PROFILE_EACH_RESIZE=1`` re-arms after every resize so a
  bounded window opens around each new generation's first steps.
  ``rearm()`` does the same programmatically.
- Each window's open/close journals a ``profile.window`` flight event,
  so the merged cluster timeline (``edl trace``) shows exactly which
  steps the device trace covers — the two instruments align by step.
- ``annotate(name)`` is a no-op-cheap context manager usable anywhere
  in the runtime (it only touches the profiler when a trace is live).

Nothing here activates unless the env var / argument is set: the
default path adds one attribute check per step.
"""

from __future__ import annotations

import os
from contextlib import contextmanager
from typing import Optional


class StepProfiler:
    """Captures bounded device-trace windows of the training hot loop."""

    def __init__(
        self,
        profile_dir: Optional[str] = None,
        max_steps: Optional[int] = None,
        at_step: Optional[int] = None,
        rearm_on_resize: Optional[bool] = None,
    ):
        self.profile_dir = profile_dir or os.environ.get("EDL_PROFILE_DIR", "")
        self.max_steps = (
            max_steps
            if max_steps is not None
            else int(os.environ.get("EDL_PROFILE_STEPS", "10"))
        )
        #: open the window only once the step counter reaches this
        #: (-1 = immediately); consumed by the NEXT window to open
        self.at_step = (
            at_step
            if at_step is not None
            else int(os.environ.get("EDL_PROFILE_AT_STEP", "-1"))
        )
        self.rearm_on_resize = (
            rearm_on_resize
            if rearm_on_resize is not None
            else os.environ.get("EDL_PROFILE_EACH_RESIZE", "0") == "1"
        )
        self._live = False
        self._steps_seen = 0
        #: windows opened so far (a closed window disarms the profiler
        #: until rearm() — the pre-rearm behavior, kept as the default)
        self._windows = 0
        self._armed = True

    @property
    def enabled(self) -> bool:
        return bool(self.profile_dir)

    @property
    def tracing(self) -> bool:
        """Whether a bounded trace is LIVE right now (enabled stays
        true for the whole process; this window closes after
        ``max_steps``) — async callers sync their in-flight device
        work only inside this window."""
        return self._live

    def rearm(self, at_step: Optional[int] = None) -> None:
        """Allow a new bounded window to open (the original profiler
        captured exactly one window per process, so a device trace
        could never cover a LATER resize).  ``at_step``: defer the new
        window until that global step (None = open at the next step)."""
        self._armed = True
        self._steps_seen = 0
        self.at_step = -1 if at_step is None else int(at_step)

    def note_resize(self) -> None:
        """A resize completed: under ``EDL_PROFILE_EACH_RESIZE`` the
        profiler re-arms so the new generation's first steps (the
        post-resize window a regression hunt actually wants) get their
        own bounded trace."""
        if self.enabled and self.rearm_on_resize and not self._live:
            self.rearm()

    def maybe_start(self, step: Optional[int] = None) -> None:
        """Open the window when armed (and, with ``at_step`` set, once
        the step counter reaches it)."""
        if not self.enabled or self._live or not self._armed:
            return
        if self._steps_seen > 0:
            return
        if self.at_step >= 0 and (step is None or step < self.at_step):
            return
        import jax

        os.makedirs(self.profile_dir, exist_ok=True)
        jax.profiler.start_trace(self.profile_dir)
        self._live = True
        self._windows += 1
        self._journal("open", step)

    def step(self, step_num: int):
        """Context for one train step; stops the trace after max_steps."""
        if not self._live:
            return _null_ctx()
        import jax

        self._steps_seen += 1
        return jax.profiler.StepTraceAnnotation("train", step_num=step_num)

    def maybe_stop(self) -> None:
        if self._live and self._steps_seen >= self.max_steps:
            self.stop()

    def stop(self) -> None:
        if not self._live:
            return
        import jax

        try:
            jax.profiler.stop_trace()
        finally:
            self._live = False
            self._armed = False  # one window per arm; rearm() re-opens
            self._journal("close", None)

    def _journal(self, phase: str, step: Optional[int]) -> None:
        """Flight-event marker aligning this device-trace window with
        the merged cluster timeline.  Best-effort and lazy — this
        module must stay importable without the telemetry package."""
        try:
            from edl_tpu import telemetry

            data = {
                "phase": phase,
                "window": self._windows,
                "dir": self.profile_dir,
            }
            telemetry.get_recorder().record(
                "profile.window", data, step=step
            )
        except Exception:
            pass


@contextmanager
def _null_ctx():
    yield


def annotate(name: str):
    """Named trace region (resize phases, checkpoint flush, ...).
    Free when no trace is live — jax's TraceMe is a no-op then."""
    import jax

    return jax.profiler.TraceAnnotation(name)
