"""Hermetic virtual-CPU child provisioning.

Multi-chip code paths are validated on an n-device virtual CPU mesh in
a fresh subprocess (SURVEY.md §4's "multi-host TPU simulation").  The
recipe has two halves, and both are needed in THIS environment:

1. the parent builds a child env pinning ``JAX_PLATFORMS=cpu`` and the
   forced device count (env vars are read at backend init), and
2. the child re-pins via ``jax.config.update`` — the image's
   sitecustomize imports jax (TPU plugin) at interpreter start, before
   the env is consulted, so the config update is the authoritative pin.

Used by ``__graft_entry__.dryrun_multichip`` and ``bench.py``'s
cross-size resize child; keep them on this one helper so the recipe
cannot diverge.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def virtual_cpu_env(
    n_devices: int, base_env: Optional[Dict[str, str]] = None
) -> Dict[str, str]:
    """Child environment forcing an ``n_devices`` virtual-CPU platform."""
    env = dict(base_env if base_env is not None else os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [
        f for f in env.get("XLA_FLAGS", "").split() if not f.startswith(_COUNT_FLAG)
    ]
    flags.append(f"{_COUNT_FLAG}={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env


def pin_cpu_platform() -> None:
    """Child-side platform pin; call before any jax op or device query."""
    import jax

    jax.config.update("jax_platforms", "cpu")
