"""Pod launcher: the ``paddle_k8s`` replacement.

The reference's pods booted through an external ``paddle_k8s`` shell
script that resolved peers from env/etcd and exec'd the right binary
(``pkg/jobparser.go:78-82,118-122,197``).  Our launcher is in-framework
(SURVEY.md §2.2: "our own launcher"):

1. read the ``EDL_*`` env contract (``controller/jobparser.py``),
2. ``jax.distributed.initialize`` when the pod is part of a multi-host
   TPU slice (JAX's coordination service replaces etcd discovery),
3. register with the job coordinator (``EDL_COORDINATOR_ADDR``),
4. build the model named by the entrypoint and run the elastic loop.

Also runnable by hand for local/smoke use:
``python -m edl_tpu.launcher --entrypoint mnist --steps 100``.
"""

from __future__ import annotations

import argparse
import os
import signal
import uuid
from typing import Callable, Optional


def env_config() -> dict:
    """Parse the EDL_* pod env contract into a config dict."""
    e = os.environ
    return {
        "job_name": e.get("EDL_JOB_NAME", "local"),
        "coordinator_addr": e.get("EDL_COORDINATOR_ADDR", ""),
        "entrypoint": e.get("EDL_ENTRYPOINT", ""),
        "workspace": e.get("EDL_WORKSPACE", ""),
        "slice_topology": e.get("EDL_SLICE_TOPOLOGY", "v5e-1"),
        "min_instance": int(e.get("EDL_MIN_INSTANCE", "1")),
        "max_instance": int(e.get("EDL_MAX_INSTANCE", "1")),
        "num_passes": int(e.get("EDL_NUM_PASSES", "1")),
        "global_batch_size": int(e.get("EDL_GLOBAL_BATCH_SIZE", "0")),
        "checkpoint_interval": int(e.get("EDL_CHECKPOINT_INTERVAL", "100")),
        # steady-state async pipeline depth (0 = synchronous loop)
        "pipeline_depth": int(e.get("EDL_PIPELINE_DEPTH", "2")),
        "fault_tolerant": e.get("EDL_FAULT_TOLERANT", "0") == "1",
        "data_dir": e.get("EDL_DATA_DIR", ""),
        # durable checkpoint volume; "" = host-DRAM only
        "checkpoint_dir": e.get("EDL_CHECKPOINT_DIR", ""),
        # persistent XLA compilation cache volume; "" = no cache
        "compile_cache_dir": e.get("EDL_COMPILE_CACHE_DIR", ""),
        # shard-only host checkpoints: each member's DRAM holds only
        # its own GSPMD slice + K ring-buddy shards; spills are
        # per-rank shard files (ElasticRuntime reads the same env var
        # directly — carried here so operators see the whole contract)
        "shard_only": e.get("EDL_SHARD_ONLY", "0") == "1",
        # "fsdp=2,tp=2" (jobparser's EDL_PARALLELISM); "" = pure dp.
        "parallelism": e.get("EDL_PARALLELISM", ""),
        "pod_name": e.get("EDL_POD_NAME", ""),
        # This pod's reachable host:port — seeds the per-generation JAX
        # process group.  Explicit EDL_POD_ADDRESS wins; otherwise built
        # from the downward-API pod IP (jobparser's manifests) + the
        # jaxcoord base port.
        "pod_address": e.get("EDL_POD_ADDRESS", "")
        or (
            f"{e['EDL_POD_IP']}:{e.get('EDL_JAX_COORD_PORT', '8476')}"
            if e.get("EDL_POD_IP")
            else ""
        ),
        "history_file": e.get("EDL_HISTORY_FILE", ""),
        # flight-recorder JSONL spill ("" = ring buffer only): every
        # stamped event (resizes, retries, chaos, saves, transfers)
        # survives the pod for post-mortems
        "flight_recorder_file": e.get("EDL_FLIGHT_RECORDER_FILE", ""),
        # deterministic fault schedule for THIS pod, as JSON
        # ({"seed": 0, "events": [{"step": 0, "point": "...", "arg":
        # ...}]}) — how subprocess-worker tests inject per-member chaos
        # (e.g. the delayed-plan-poll scale-down reproducer)
        "chaos_spec": e.get("EDL_CHAOS_SPEC", ""),
        # collective-watchdog deadline override in seconds ("" = auto:
        # 120s on multipod worlds, disabled single-process)
        "collective_timeout": (
            float(e["EDL_COLLECTIVE_TIMEOUT"])
            if e.get("EDL_COLLECTIVE_TIMEOUT")
            else None
        ),
        # per-step consensus control word (EDL_CONSENSUS=0 disables —
        # diagnostic escape hatch only: scale-downs then race again)
        "consensus": e.get("EDL_CONSENSUS", "1") != "0",
        # how often (seconds) the telemetry snapshot + event tail +
        # clock-offset estimate piggyback on the heartbeat cadence
        # (0 disables reporting; tests tighten it so merged traces
        # converge fast)
        "telemetry_interval": float(
            e.get("EDL_TELEMETRY_INTERVAL", "5.0")
        ),
        # Serving-replica pod contract (edl_tpu.serving.server.serve_run
        # reads these; jobparser's serving manifests set them from
        # spec.serving).
        "serve_port": int(e.get("EDL_SERVE_PORT", "7180")),
        "serve_max_batch": int(e.get("EDL_SERVE_MAX_BATCH", "64")),
        "serve_queue_limit": int(e.get("EDL_SERVE_QUEUE_LIMIT", "256")),
        "serve_deadline_ms": int(e.get("EDL_SERVE_DEADLINE_MS", "2000")),
        # Router pod contract (edl_tpu.serving.router.main reads these;
        # jobparser's router Deployment sets them).
        "route_port": int(e.get("EDL_ROUTE_PORT", "7190")),
        "route_retry_budget_ms": float(
            e.get("EDL_ROUTE_RETRY_BUDGET_MS", "10000")
        ),
        "route_probe_ms": float(e.get("EDL_ROUTE_PROBE_MS", "500")),
        "route_eject_after": int(e.get("EDL_ROUTE_EJECT_AFTER", "3")),
        # Multi-host slice placement: replica index from the per-replica
        # Job's env; host index from the Indexed Job's completion index
        # (k8s injects JOB_COMPLETION_INDEX; EDL_HOST_INDEX overrides
        # for tests/local runs).
        "replica": (
            int(e["EDL_REPLICA"]) if e.get("EDL_REPLICA") else None
        ),
        "host_index": (
            int(e["EDL_HOST_INDEX"])
            if e.get("EDL_HOST_INDEX")
            else (
                int(e["JOB_COMPLETION_INDEX"])
                if e.get("JOB_COMPLETION_INDEX")
                else None
            )
        ),
    }


_compile_counting_on = False


def enable_compile_counting() -> None:
    """Count TRUE XLA backend compiles into the shared registry
    (``edl_xla_compiles_total``) by wrapping the ``backend_compile``
    seam — persistent-cache hits bypass it, so the counter moves only
    when XLA really compiled.  This is the same seam ``bench.py``
    patches ad hoc; behind ``EDL_COUNT_XLA_COMPILES=1`` a deployed pod
    gets it too, which is what lets the fleet real-process tests
    assert "this warm resize performed ZERO compiles" from a worker's
    journal instead of only in-process.  Idempotent; the ~100ns
    counter inc per compile is noise against any real compile."""
    global _compile_counting_on
    if _compile_counting_on:
        return
    import jax._src.compiler as _compiler

    from edl_tpu import telemetry

    m = telemetry.get_registry().counter("edl_xla_compiles_total")
    real = _compiler.backend_compile

    def counting_backend_compile(*args, **kwargs):
        m.inc()
        return real(*args, **kwargs)

    _compiler.backend_compile = counting_backend_compile
    _compile_counting_on = True


def configure_compile_cache(cache_dir: str) -> None:
    """Wire the persistent XLA compilation cache at ``cache_dir``
    (EDL_COMPILE_CACHE_DIR, from the TrainingJob's
    ``spec.compile_cache_dir``).

    With it, a compile whose HLO was ever compiled before — by THIS
    pod in a previous generation, by a peer sharing the mounted volume,
    or by a previous incarnation of a restarted pod — deserializes from
    disk instead of recompiling, which removes the cold-compile cost
    from joiner restores and whole-world cold starts entirely.  The
    threshold knobs drop to "cache everything": elastic train steps are
    exactly the repeated-compile workload the thresholds exist to
    filter out of one-shot jobs.  Knob names are pinned per jax
    version; a renamed knob degrades to that knob's default rather
    than failing the pod at boot."""
    if not cache_dir:
        return
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    for knob, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, val)
        except Exception:  # pragma: no cover - knob renamed upstream
            import sys

            print(
                f"[edl] compile-cache knob {knob} unavailable on this "
                "jax; persistent cache keeps that knob's default",
                file=sys.stderr,
            )
    _enable_all_rank_cache_writes()


def _enable_all_rank_cache_writes() -> None:
    """Make rank>0 members benefit from the persistent cache at all.

    This jax (0.4.37) only WRITES persistent-cache entries from
    process 0 (``_cache_write``'s gate — its stated reason is write
    contention on shared filesystems like GCS), while its cache KEYS
    are process-dependent on this backend — so the key a rank-1 member
    looks up is one only a rank-1 member could have written, and
    nobody ever writes it.  Measured on a 2-process gloo CPU world:
    across two identical runs sharing one cache dir, rank 0's second
    run pays 0 backend compiles, rank 1 re-pays EVERY compile — and
    the same asymmetry makes a standby member re-pay its whole world's
    compiles on every rejoin (the fleet storm's restore transition
    measured 7 true compiles on the rejoining member vs 0 on the
    survivor).  Letting every rank persist its own keys removes the
    asymmetry: keys are per-rank distinct, so there is no cross-rank
    write contention, and a local/PV cache dir has none of the GCS
    concern anyway.  Version-pinned monkeypatch like the gloo
    collectives flip; a future jax that restructures the seam simply
    keeps upstream behavior."""
    try:
        import jax._src.compiler as _compiler
        from jax._src import compilation_cache as _cc

        def cache_write_all_ranks(
            cache_key, compile_time_secs, module_name, backend,
            executable, host_callbacks,
        ):
            if host_callbacks:
                return  # baked into the HLO, unshareable (upstream rule)
            try:
                _cc.put_executable_and_time(
                    cache_key, module_name, executable, backend,
                    int(compile_time_secs),
                )
            except Exception:
                pass  # a cache-write failure must never fail a step

        _compiler._cache_write = cache_write_all_ranks
    except Exception:  # pragma: no cover - seam moved upstream
        import sys

        print(
            "[edl] per-rank compile-cache writes unavailable on this "
            "jax; rank>0 members keep paying formation compiles",
            file=sys.stderr,
        )


def force_platform(platform: str) -> None:
    """Pin the JAX platform (tests / CPU smoke runs).  Must run before
    the first device query; config.update beats any platform selection
    an early jax import latched from the environment."""
    import jax

    jax.config.update("jax_platforms", platform)
    # NOTE: multi-process CPU worlds need gloo collectives (TPU worlds
    # get theirs from ICI/DCN natively), but gloo is NOT configured
    # here: jaxlib's make_gloo_tcp_collectives requires a LIVE
    # distributed client, and any jax op dispatched before
    # jax.distributed.initialize — model binding's layout validation,
    # an abstract prewarm — would try to build the CPU backend with
    # gloo configured and no client, killing the pod at boot.  The
    # world builder flips gloo on right after each successful
    # initialize (backends are cleared every generation) and back off
    # at teardown, so backends built while unformed stay plain.


def _set_cpu_collectives(impl: str) -> None:
    """Switch the CPU collectives implementation (no-op off the forced
    CPU platform).  Only meaningful between backend builds — the world
    builder calls it with backends cleared."""
    import jax

    if (jax.config.jax_platforms or "") != "cpu":
        return
    jax.config.update("jax_cpu_collectives_implementation", impl)


def _install_nonfatal_heartbeat_callback() -> None:
    """Patch the distributed-client factory to log coordination-service
    failures instead of terminating the process (idempotent).

    This reaches into a private jax API
    (``jax._src.distributed._jax.get_distributed_runtime_client`` and
    its ``missed_heartbeat_callback`` kwarg), so every step is guarded:
    on a jax that moved the attribute or dropped the kwarg we fall back
    to UNPATCHED behavior with a warning (survivable elasticity
    degrades: an ungraceful peer death then kills its peers via the
    default QFATAL callback) instead of failing every world formation
    at startup (ADVICE r3)."""
    import inspect
    import sys

    def warn(why: str) -> None:
        print(
            "[edl] cannot install non-fatal heartbeat callback "
            f"({why}); ungraceful peer death will terminate peer "
            "processes (jax private API drifted — pin jax or update "
            "edl_tpu.launcher)",
            file=sys.stderr,
        )

    try:
        from jax._src import distributed as _dist
    except ImportError as e:
        return warn(f"jax._src.distributed unavailable: {e}")
    # The factory's host module moved across jax versions: newer jax
    # calls ``_dist._jax.get_distributed_runtime_client``, 0.4.x calls
    # ``_dist.xla_extension.get_distributed_runtime_client`` — patch
    # whichever alias THIS jax's initialize() actually reads.
    jaxlib = getattr(_dist, "_jax", None)
    if jaxlib is None or not hasattr(jaxlib, "get_distributed_runtime_client"):
        jaxlib = getattr(_dist, "xla_extension", None)
    if jaxlib is None or not hasattr(jaxlib, "get_distributed_runtime_client"):
        return warn("get_distributed_runtime_client attribute missing")
    if getattr(jaxlib, "_edl_nonfatal_heartbeats", False):
        return

    orig = jaxlib.get_distributed_runtime_client
    try:
        params = inspect.signature(orig).parameters
        accepts_kwarg = "missed_heartbeat_callback" in params or any(
            p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
        )
    except (TypeError, ValueError):
        # C-extension callables often have no inspectable signature;
        # the current jaxlib's does take the kwarg — try, and let the
        # patched wrapper retry without it if the call rejects it.
        accepts_kwarg = True
    if not accepts_kwarg:
        return warn("missed_heartbeat_callback kwarg no longer accepted")

    def _log_only(status, *rest):
        print(
            f"[edl] coordination service reported failure (peer death?): "
            f"{status}",
            file=sys.stderr,
        )

    def patched(*args, **kwargs):
        kwargs.setdefault("missed_heartbeat_callback", _log_only)
        try:
            return orig(*args, **kwargs)
        except TypeError as e:
            # Scope the fallback to ACTUAL signature drift: only a
            # TypeError naming our kwarg means it was rejected; any
            # other TypeError is a real bug that must surface, not be
            # retried (the factory may have partially connected).
            if "missed_heartbeat_callback" not in str(e):
                raise
            kwargs.pop("missed_heartbeat_callback", None)
            warn("kwarg rejected at call time")
            return orig(*args, **kwargs)

    jaxlib.get_distributed_runtime_client = patched
    jaxlib._edl_nonfatal_heartbeats = True


def _check_slice_topology(topology: str, devices) -> None:
    """Cross-check the formed world against the declared slice topology.

    A trainer pod owns one host's worth of its slice (ref trainer spec
    ``pkg/resource/training_job.go:128-134``: a replica is a whole
    slice), so on TPU the local device count must equal the topology's
    chips-per-host.  The mesh itself is derived from the *actual*
    formed world (``ElasticTrainer._rebuild_world``); this check only
    surfaces spec/deployment drift loudly instead of letting a
    mis-labeled nodepool silently train at the wrong scale."""
    import sys

    import jax

    local = [d for d in devices if d.process_index == jax.process_index()]
    if not local or local[0].platform != "tpu":
        return  # CPU smoke/test worlds force arbitrary device counts
    from edl_tpu.cluster.tpu_topology import get_topology

    try:
        topo = get_topology(topology)
    except ValueError:
        return
    per_host = topo.chips // max(1, topo.hosts)
    if topo.chips and len(local) != per_host:
        print(
            f"[edl] slice topology {topology} expects {per_host} "
            f"chips/host but this pod sees {len(local)} local devices; "
            "check the nodepool's tpu-topology labels",
            file=sys.stderr,
        )


#: Per-generation coordination ports rotate through this window above
#: the pod's base port.  Wide enough that a port recurs only after
#: hundreds of generations (no TIME_WAIT collisions on fast churn);
#: bounded so the k8s container port range stays declarable.
_PORT_WINDOW = 2048
#: Formation attempts per generation.  Every member derives the SAME
#: port sequence (f(generation, attempt)), so a bind failure on the new
#: rank 0 (stray listener, straggler socket) resolves by all members
#: timing out in lockstep and retrying on the next port — agreement
#: with no extra round-trip.
_FORMATION_ATTEMPTS = 3
_FORMATION_TIMEOUT_S = 30


def make_world_builder(
    trainer_id: str, formation_log: Optional[Callable] = None
) -> Callable:
    """Build the multi-pod world (re)formation hook.

    Each generation's process group is a fresh ``jax.distributed``
    world: coordinator = new rank 0's advertised host, port derived
    deterministically from (generation, attempt) so every member picks
    the same one with no extra round-trip.  Teardown before re-init is
    what makes elasticity possible — XLA collectives cannot span
    worlds, so membership change means "re-form the world", the direct
    analog of the reference trainers re-registering through master/etcd
    (``pkg/jobparser.go:174-191``).

    ``formation_log``: optional callback receiving a timing dict per
    formation (teardown/init breakdown — the <60s resize budget's
    dominant unknown at scale, BASELINE.md).
    """
    import time as _time

    import jax

    # Elastic worlds do not use jax's preemption sync service (our
    # preemption/failure handling is lease-based through the job
    # coordinator), and its polling thread is one of the C++ threads
    # that can terminate() a SURVIVOR after an ungraceful peer death
    # (observed: "Failed to retrieve preemption notice ... Socket
    # closed" followed by std::bad_cast while holding for a missing
    # cross-pod-tp peer).  Disable it outright.
    try:
        jax.config.update("jax_enable_preemption_service", False)
    except Exception:  # pragma: no cover - option renamed/removed
        pass

    # Defuse the coordination service's poison pill.  By default the
    # distributed client's missed-heartbeat callback LOG(QFATAL)s the
    # process when the service reports a peer failure OR when a
    # disconnect can't reach the service — so one ungracefully-dead pod
    # kills every survivor, and a torn-down generation can kill a
    # leaver.  Elastic worlds must outlive their members: inject a
    # log-only callback, so peer death surfaces as a *catchable*
    # collective error in the step (handled by ElasticTrainer's
    # broken-world path) instead of process termination.
    if os.environ.get("EDL_NO_HB_PATCH") == "1":
        # Diagnostic escape hatch only: without the patch, ANY peer
        # failure terminates every pod via the default QFATAL callback.
        import sys as _sys

        print(
            "[edl] EDL_NO_HB_PATCH=1: heartbeat patch DISABLED — "
            "ungraceful peer death will terminate peer processes",
            file=_sys.stderr,
        )
    else:
        _install_nonfatal_heartbeat_callback()

    broken = [False]
    #: dead worlds' distributed handles, kept referenced so their C++
    #: destructors never run (a destructor-triggered shutdown would hit
    #: the same barrier the leak avoids).  Each entry pins a client's
    #: threads/fds (and, on rank 0, a service holding its old port —
    #: the formation port formula wraps every _PORT_WINDOW /
    #: _FORMATION_ATTEMPTS generations, at which point a leaked port
    #: costs one burned formation attempt).  Hard-capped: a process
    #: that survives this many ungraceful world deaths is pathological
    #: — fail loudly and let the pod restart rejoin cleanly.
    graveyard = []
    _MAX_DEAD_WORLDS = 32

    def mark_broken():
        broken[0] = True

    def _bury(gs):
        """Graveyard the live distributed handles (no destructors, no
        barrier), then enforce the leak budget.  The handles are
        secured BEFORE the cap check raises: a budget-exhausted process
        must still exit with a traceback, not a destructor-triggered
        barrier abort."""
        graveyard.append(
            (gs.client, gs.service, gs.preemption_sync_manager)
        )
        gs.client = None
        gs.service = None
        gs.preemption_sync_manager = None
        if len(graveyard) > _MAX_DEAD_WORLDS:
            from edl_tpu.runtime.elastic import FatalWorldError

            raise FatalWorldError(
                f"{_MAX_DEAD_WORLDS} ungraceful world deaths in one "
                "process: leaked-handle budget exhausted; restart the "
                "trainer pod (it will rejoin and restore from the "
                "coordinator's checkpoint)"
            )

    def teardown():
        from jax._src import distributed

        gs = distributed.global_state
        if broken[0]:
            broken[0] = False
            # The world died UNGRACEFULLY (peer SIGKILL/preemption): the
            # shutdown barrier can never complete — dead peers don't
            # arrive — and jaxlib's coordination service then propagates
            # the barrier failure to every polling client, which can
            # terminate() the surviving process from a background C++
            # thread (observed as std::bad_cast under load; no Python
            # except can catch it).  Leak the dead world's handles
            # instead: the per-generation port window guarantees the
            # next formation never reuses this world's port, so a
            # leaked service holding its old port is inert.
            if gs.client is not None or gs.service is not None:
                _bury(gs)
        elif gs.client is not None or gs.service is not None:
            try:
                jax.distributed.shutdown()
            except Exception:
                # Peers already gone (scale-down races the shutdown
                # barrier): the world may be un-barrierable, so treat
                # its handles like a broken world's — graveyarded, not
                # dropped to GC, whose destructors would re-enter the
                # same shutdown machinery.
                _bury(gs)
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            from jax.extend.backend import clear_backends

            clear_backends()
            # The restore path's staging-conversion executables died
            # with the backend: forget they were warm, or the next
            # generation's first restore pays them back inside the
            # resize window believing them compiled.
            from edl_tpu.checkpoint.hostdram import (
                reset_leaf_conversion_warmth,
            )

            reset_leaf_conversion_warmth()
        # Unformed process: the next backend build (standby-hold jax
        # ops, restart-path model binding) must not reach for gloo —
        # there is no distributed client for it to ride on.
        _set_cpu_collectives("none")

    def build(plan):
        t0 = _time.perf_counter()
        teardown()
        t_teardown = _time.perf_counter() - t0
        if trainer_id not in plan.members:
            return None  # standby: not part of this generation's world
        if not plan.addresses or not all(plan.addresses):
            raise RuntimeError(
                f"plan generation {plan.generation} carries no member "
                "addresses; multi-pod world formation needs every pod "
                "registered with EDL_POD_ADDRESS"
            )
        rank = plan.members.index(trainer_id)
        host, base = plan.addresses[0].rsplit(":", 1)
        t1 = _time.perf_counter()
        # Teardown-barrier patience: long enough that a loaded peer's
        # graceful leave (both parties alive, skewed tens of seconds
        # under CI load) still completes the barrier — a timeout here
        # risks the coordination service's error propagation — yet far
        # under the 300s default so a standby pod doesn't stall its
        # hold.  Dead-peer worlds never reach this barrier at all (see
        # teardown()).  The knob is newer than some supported jax
        # versions; passing it unconditionally would fail EVERY
        # formation with a TypeError the hold-and-retry loop silently
        # eats — the world then never forms at all.
        import inspect

        init_kwargs = {}
        if "shutdown_timeout_seconds" in inspect.signature(
            jax.distributed.initialize
        ).parameters:
            init_kwargs["shutdown_timeout_seconds"] = 30
        for attempt in range(_FORMATION_ATTEMPTS):
            port = int(base) + 1 + (
                (plan.generation * _FORMATION_ATTEMPTS + attempt)
                % _PORT_WINDOW
            )
            try:
                jax.distributed.initialize(
                    coordinator_address=f"{host}:{port}",
                    # members lists every POD; world_size counts trainer
                    # REPLICAS (a multi-host replica is `hosts` pods,
                    # each its own process) — they coincide only on
                    # single-host topologies.
                    num_processes=len(plan.members),
                    process_id=rank,
                    initialization_timeout=_FORMATION_TIMEOUT_S,
                    **init_kwargs,
                )
                break
            except Exception:
                # A FAILED initialize leaves the coordination agent in
                # an error state: Shutdown() on it logs
                # "Shutdown() was called while coordination agent is in
                # error state" and its error-poll thread can terminate()
                # the process from C++ (the std::bad_cast, observed when
                # a restarted pod races a STALE dead member still in the
                # plan — whole-world preemption recovery).  Treat the
                # half-initialized world exactly like a broken one:
                # graveyard its handles, never barrier.
                mark_broken()
                teardown()
                if attempt == _FORMATION_ATTEMPTS - 1:
                    raise
        # The distributed client is live and backends were cleared in
        # teardown(): the jax.devices() below builds this generation's
        # backend, and on CPU it must carry gloo collectives riding
        # that client (configuring gloo any earlier kills the process —
        # see force_platform).
        _set_cpu_collectives("gloo")
        devices = jax.devices()
        if formation_log is not None:
            formation_log(
                {
                    "generation": plan.generation,
                    "world_size": plan.world_size,
                    "rank": rank,
                    "devices": len(devices),
                    "local_devices": jax.local_device_count(),
                    "teardown_s": round(t_teardown, 4),
                    "init_s": round(_time.perf_counter() - t1, 4),
                }
            )
        return devices

    def leak_dead_world():
        """Abandon the current world's handles WITHOUT the shutdown
        barrier — for fatal exit paths where no next formation will
        run teardown (e.g. the broken-world cap re-raising).  Leaving
        the handles live would let interpreter-exit destructors hit the
        dead-peer barrier and abort the process from a C++ thread,
        replacing the diagnostic traceback with a terminate()."""
        mark_broken()
        teardown()

    # ElasticTrainer calls these: mark_broken when a collective dies
    # mid-step (so the NEXT teardown knows the world is unbarrierable),
    # leak_dead_world when it is about to re-raise fatally.
    build.mark_broken = mark_broken
    build.leak_dead_world = leak_dead_world
    return build


def init_distributed() -> None:
    """Join the slice's JAX coordination service when this pod is part
    of a multi-host TPU slice.  On GKE TPU podslices the TPU runtime
    env (``TPU_WORKER_HOSTNAMES`` etc.) carries everything
    ``jax.distributed.initialize`` needs; single-host pods skip this.
    (This one call replaces the reference's entire port/etcd discovery
    plumbing, SURVEY.md §2.5.)"""
    import jax

    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if "," in hostnames or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        jax.distributed.initialize()


def run(
    entrypoint: str,
    steps: Optional[int] = None,
    coordinator_addr: str = "",
    global_batch_size: int = 0,
    checkpoint_interval: Optional[int] = None,
    seed: int = 0,
    dataset_examples: int = 4096,
    pod_address: str = "",
    history_file: str = "",
    data_dir: str = "",
    parallelism: str = "",
    checkpoint_dir: str = "",
    compile_cache_dir: str = "",
    lr: float = 1e-3,
) -> "ElasticTrainer":
    """Build and run the elastic training loop for a registered model.

    Returns the ElasticTrainer (with history) for inspection."""
    import jax
    import optax

    from edl_tpu.models.base import bind_model
    from edl_tpu.resource.training_job import ParallelismSpec
    from edl_tpu.runtime.coord_service import HTTPCoordinator
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.runtime.data import ShardedDataIterator
    from edl_tpu.runtime.elastic import ElasticTrainer

    cfg = env_config()
    # Before any compile: every generation's step executable lands in /
    # loads from the shared cache (joiners and cold starts skip XLA).
    configure_compile_cache(compile_cache_dir or cfg["compile_cache_dir"])
    if os.environ.get("EDL_COUNT_XLA_COMPILES", "0") == "1":
        enable_compile_counting()
    if cfg["flight_recorder_file"]:
        # Durable flight-recorder journal: the ring buffer's events
        # also append to this JSONL so a crashed pod leaves its last
        # moments on disk (the telemetry half of EDL_HISTORY_FILE).
        from edl_tpu import telemetry

        telemetry.get_recorder().spill_to(cfg["flight_recorder_file"])
    par = ParallelismSpec.from_env(parallelism or cfg["parallelism"])
    layout = par.axes()
    # bind_model validates layout-vs-entrypoint up front (boot-time
    # failure, not a mid-resize one); model_factory(None) is the
    # mesh-free instance used for dataset shapes below.  Unregistered
    # entrypoints load from EDL_WORKSPACE/model.py (the user-code
    # contract, ref pkg/jobparser.go:288-291).
    model_factory = bind_model(
        entrypoint or cfg["entrypoint"], layout, workspace=cfg["workspace"]
    )
    model = model_factory(None)
    gbs = global_batch_size or cfg["global_batch_size"]
    pod_address = pod_address or cfg["pod_address"]
    history_file = history_file or cfg["history_file"]
    trainer_id = cfg["pod_name"] or f"trainer-{uuid.uuid4().hex[:8]}"
    addr = coordinator_addr or cfg["coordinator_addr"]
    world_builder = None
    heartbeat_ids = [trainer_id]
    sigterm_handler = [None]

    hist_f = None
    if history_file:
        hist_f = open(history_file, "a", buffering=1)

    if addr:
        coordinator = HTTPCoordinator(addr)
        if pod_address:
            # Multi-pod: each generation re-forms the JAX process group
            # from the plan's rank-ordered addresses.  Device queries
            # must wait for world formation.
            formation_log = None
            if hist_f is not None:
                def formation_log(stats):
                    import json

                    hist_f.write(json.dumps({"formation": stats}) + "\n")

            raw_builder = make_world_builder(
                trainer_id, formation_log=formation_log
            )

            def world_builder(plan):
                devs = raw_builder(plan)
                # jax.distributed's C++ runtime replaces the SIGTERM
                # disposition at initialize; take the graceful-leave
                # handler back or scale-down pods can never deregister.
                if sigterm_handler[0] is not None:
                    signal.signal(signal.SIGTERM, sigterm_handler[0])
                if devs is not None:
                    _check_slice_topology(cfg["slice_topology"], devs)
                return devs

            # the broken-world signals must reach the RAW builder's
            # teardown through this wrapper
            world_builder.mark_broken = raw_builder.mark_broken
            world_builder.leak_dead_world = raw_builder.leak_dead_world

            gbs = gbs or 64
        coordinator.register(
            trainer_id,
            address=pod_address,
            replica=cfg["replica"],
            host=cfg["host_index"],
        )
        n_dev = 1 if pod_address else len(jax.devices())
    else:
        n_dev = len(jax.devices())
    gbs = gbs or max(64, 8 * n_dev)

    if not addr:
        # Local mode: in-process coordinator, one membership per device.
        max_w = max(cfg["max_instance"], n_dev)
        legal = None
        if gbs or layout:
            # same quantization the deployed coordinator gets via
            # --legal-sizes: worlds must factor into the layout and
            # divide the global batch (one device per local trainer)
            from edl_tpu.resource.training_job import quantized_world_sizes

            legal = quantized_world_sizes(1, max_w, 1, gbs, par)
            if not legal:
                # Surface the layout misconfiguration NOW: an empty
                # legal list would pin the plan's world_size to 0 and
                # die 300s later with a membership-sounding barrier
                # timeout.
                raise ValueError(
                    f"no legal world size <= {max_w} devices: layout "
                    f"{layout} (product {par.product()}) with global "
                    f"batch {gbs} admits none"
                )
        coordinator = LocalCoordinator(
            target_world=min(cfg["max_instance"], n_dev) or n_dev,
            max_world=max_w,
            legal_sizes=legal,
        )
        heartbeat_ids = [f"{trainer_id}-{i}" for i in range(n_dev)]
        for tid in heartbeat_ids:
            coordinator.register(tid)

    from edl_tpu.runtime.datasets import resolve_dataset

    dataset = resolve_dataset(
        model, data_dir or cfg["data_dir"], max(dataset_examples, gbs)
    )
    data = ShardedDataIterator(dataset, global_batch_size=gbs, seed=seed)

    # Per-pod deterministic chaos (EDL_CHAOS_SPEC): the schedule rides
    # the checkpoint store's chaos seam — the same plumbing the
    # in-process soaks use — so subprocess-worker tests can chaos one
    # member of a real multi-pod world (delayed plan polls, watchdog
    # trips) without monkeypatching across a process boundary.
    chaos_sched = None
    if cfg["chaos_spec"]:
        import json as _json

        from edl_tpu.chaos.schedule import FaultEvent, FaultSchedule

        spec = _json.loads(cfg["chaos_spec"])
        chaos_sched = FaultSchedule(
            seed=int(spec.get("seed", 0)),
            events=[
                FaultEvent(
                    step=int(ev["step"]),
                    point=ev["point"],
                    arg=ev.get("arg"),
                )
                for ev in spec.get("events", ())
            ],
        )

    spill_dir = checkpoint_dir or cfg["checkpoint_dir"]
    store = None
    if spill_dir or chaos_sched is not None:
        from edl_tpu.checkpoint import HostDRAMStore

        # Durable checkpoints: every DRAM checkpoint also spills to the
        # mounted volume, and ElasticTrainer's restore paths fall back
        # to it on a cold start (whole-world loss) — see
        # elastic._latest_or_disk.
        store = HostDRAMStore(
            spill_dir=spill_dir or None, chaos=chaos_sched
        )

    et = ElasticTrainer(
        model_factory if layout else model,
        optax.adam(lr),
        data,
        coordinator,
        store=store,
        checkpoint_interval=(
            checkpoint_interval
            if checkpoint_interval is not None
            else cfg["checkpoint_interval"]
        ),
        seed=seed,
        world_builder=world_builder,
        layout=layout,
    )
    et.pipeline_depth = cfg["pipeline_depth"]
    et.consensus_bus = cfg["consensus"]
    et.collective_timeout = cfg["collective_timeout"]
    et.telemetry_interval = cfg["telemetry_interval"]
    et.heartbeat_ids = heartbeat_ids
    et.register_address = pod_address
    et.register_replica = cfg["replica"]
    et.register_host = cfg["host_index"]
    if hist_f is not None:
        def on_resize(ev):
            import dataclasses
            import json

            hist_f.write(
                json.dumps({"resize": dataclasses.asdict(ev)}) + "\n"
            )

        et.on_resize = on_resize

    # Graceful scale-down handshake: on SIGTERM (k8s pod deletion),
    # deregister + flush synchronously so the survivors' resize window
    # never waits out the heartbeat lease (VERDICT r1 §missing-3).  The
    # reference relied on the lease expiring — a 30s budget hole.
    def _deregister_all():
        """Leave the membership.  Transport retries live in the HTTP
        client itself (HTTPCoordinator._request: 3 tries, 5s timeout)
        — stacking another retry loop here could blow the k8s
        termination grace period from inside the SIGTERM handler.  A
        final failure is LOGGED (it used to be silently swallowed,
        leaving a ghost member until the lease expired — the 30s
        budget hole the handshake exists to close — with zero trace
        of why)."""
        for tid in heartbeat_ids:
            try:
                coordinator.deregister(tid)
            except Exception as e:
                # os.write is signal-safe; print() can raise a
                # reentrant-buffered-IO RuntimeError inside the SIGTERM
                # handler and abort the loop mid-deregistration.
                try:
                    os.write(
                        2,
                        (
                            f"[edl] deregister {tid} failed (ghost "
                            f"member until lease expiry): {e}\n"
                        ).encode(errors="backslashreplace"),
                    )
                except Exception:
                    pass

    def _graceful_leave(signum, frame):
        # Every phase is independently guarded: an exception in the
        # flush (or a stuck heartbeat join) must NOT skip the
        # deregistration — the finally's os._exit would swallow it and
        # leave a ghost member with zero trace.
        try:
            try:
                et.stop_heartbeat()
            except Exception as e:
                try:
                    os.write(
                        2,
                        f"[edl] stop_heartbeat failed: {e}\n".encode(
                            errors="backslashreplace"
                        ),
                    )
                except Exception:
                    pass
            try:
                if et.state is not None and jax.process_count() == 1:
                    et.store.save_async(et.state, generation=et.generation)
                    et.store.wait()
            except Exception as e:
                try:
                    os.write(
                        2,
                        f"[edl] graceful-leave flush failed: {e}\n".encode(
                            errors="backslashreplace"
                        ),
                    )
                except Exception:
                    pass
            _deregister_all()
        finally:
            os._exit(0)

    sigterm_handler[0] = _graceful_leave
    prev_term = signal.signal(signal.SIGTERM, _graceful_leave)

    on_step = None
    if hist_f is not None:

        def on_step(rec):
            import json

            hist_f.write(
                json.dumps(
                    {
                        "step": rec.step,
                        "generation": rec.generation,
                        "world_size": rec.world_size,
                        "loss": rec.loss,
                        "seconds": rec.seconds,
                    }
                )
                + "\n"
            )

    if chaos_sched is not None:
        # The env-installed schedule has no soak driver: its clock
        # rides the harvested step stream (advance is monotonic).
        _inner_on_step = on_step

        def on_step(rec):
            chaos_sched.advance(rec.step)
            if _inner_on_step is not None:
                _inner_on_step(rec)

    try:
        if steps is None:
            steps = cfg["num_passes"] * data.batches_per_epoch
        et.run(steps, on_step=on_step)
        # Final flush: the durable dir must hold the FINISHED state,
        # not just the last interval/resize checkpoint (every member
        # completes the same step, so the save's collectives — if any —
        # are dispatched in lockstep like interval saves).
        if et.state is not None:
            et.store.save_async(et.state, generation=et.generation)
        et.store.wait()
        # The job ran its passes to completion: tell the coordinator so
        # the controller can flip the CR to Succeed and tear the
        # coordinator down (ref Complete, pkg/trainingjober.go:126-132,
        # which nothing in the reference ever called).  Idempotent, so
        # every finishing pod may report.
        try:
            last_step = et.history[-1].step if et.history else -1
            coordinator.report_complete(step=last_step)
        except Exception:
            pass
        # Leave the membership on completion: a finished pod must not
        # linger in the plan's rank order (peers would try to form a
        # world with a process that no longer exists).  Heartbeats stop
        # FIRST — an in-flight beat after the deregister would resurrect
        # this pod as a ghost member.
        et.stop_heartbeat()
        _deregister_all()
    finally:
        signal.signal(signal.SIGTERM, prev_term)
        et.stop_heartbeat()
    return et


def main(argv=None):  # pragma: no cover - process entrypoint
    p = argparse.ArgumentParser(description="EDL-TPU trainer launcher")
    p.add_argument("--entrypoint", default="", help="registered model name")
    p.add_argument("--steps", type=int, default=None)
    p.add_argument("--coordinator", default="", help="coordinator address")
    p.add_argument(
        "--address",
        default="",
        help=(
            "this pod's reachable host:port (enables multi-pod world "
            "formation; normally from EDL_POD_ADDRESS)"
        ),
    )
    p.add_argument("--global-batch-size", type=int, default=0)
    p.add_argument("--checkpoint-interval", type=int, default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument(
        "--platform",
        default="",
        help="force a JAX platform (e.g. cpu for multi-process smoke tests)",
    )
    p.add_argument(
        "--history-file", default="", help="append per-step JSONL records here"
    )
    p.add_argument(
        "--parallelism",
        default="",
        help=(
            'mesh layout beyond elastic dp, e.g. "fsdp=2,tp=2" '
            "(normally from EDL_PARALLELISM)"
        ),
    )
    p.add_argument(
        "--checkpoint-dir",
        default="",
        help=(
            "durable checkpoint directory (normally from "
            "EDL_CHECKPOINT_DIR); cold starts restore from it"
        ),
    )
    p.add_argument(
        "--compile-cache-dir",
        default="",
        help=(
            "persistent XLA compilation cache directory (normally from "
            "EDL_COMPILE_CACHE_DIR); joiners/cold starts skip "
            "recompilation"
        ),
    )
    p.add_argument(
        "--lr",
        type=float,
        default=1e-3,
        help="adam learning rate for the training step",
    )
    args = p.parse_args(argv)

    if args.platform:
        force_platform(args.platform)
    if not (args.address or env_config()["pod_address"]):
        # Static multi-host slice (no elastic coordinator-driven world):
        # join the slice's process group once at boot.
        init_distributed()
    et = run(
        entrypoint=args.entrypoint,
        steps=args.steps,
        coordinator_addr=args.coordinator,
        global_batch_size=args.global_batch_size,
        checkpoint_interval=args.checkpoint_interval,
        seed=args.seed,
        pod_address=args.address,
        history_file=args.history_file,
        parallelism=args.parallelism,
        checkpoint_dir=args.checkpoint_dir,
        compile_cache_dir=args.compile_cache_dir,
        lr=args.lr,
    )
    last = et.history[-1] if et.history else None
    print(
        f"done: steps={len(et.history)} "
        f"final_loss={last.loss if last else float('nan'):.4f} "
        f"resizes={len(et.resize_events)}"
    )


if __name__ == "__main__":  # pragma: no cover
    main()
