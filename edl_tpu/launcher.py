"""Pod launcher: the ``paddle_k8s`` replacement.

The reference's pods booted through an external ``paddle_k8s`` shell
script that resolved peers from env/etcd and exec'd the right binary
(``pkg/jobparser.go:78-82,118-122,197``).  Our launcher is in-framework
(SURVEY.md §2.2: "our own launcher"):

1. read the ``EDL_*`` env contract (``controller/jobparser.py``),
2. ``jax.distributed.initialize`` when the pod is part of a multi-host
   TPU slice (JAX's coordination service replaces etcd discovery),
3. register with the job coordinator (``EDL_COORDINATOR_ADDR``),
4. build the model named by the entrypoint and run the elastic loop.

Also runnable by hand for local/smoke use:
``python -m edl_tpu.launcher --entrypoint mnist --steps 100``.
"""

from __future__ import annotations

import argparse
import os
import uuid
from typing import Optional


def env_config() -> dict:
    """Parse the EDL_* pod env contract into a config dict."""
    e = os.environ
    return {
        "job_name": e.get("EDL_JOB_NAME", "local"),
        "coordinator_addr": e.get("EDL_COORDINATOR_ADDR", ""),
        "entrypoint": e.get("EDL_ENTRYPOINT", ""),
        "workspace": e.get("EDL_WORKSPACE", ""),
        "slice_topology": e.get("EDL_SLICE_TOPOLOGY", "v5e-1"),
        "min_instance": int(e.get("EDL_MIN_INSTANCE", "1")),
        "max_instance": int(e.get("EDL_MAX_INSTANCE", "1")),
        "num_passes": int(e.get("EDL_NUM_PASSES", "1")),
        "global_batch_size": int(e.get("EDL_GLOBAL_BATCH_SIZE", "0")),
        "checkpoint_interval": int(e.get("EDL_CHECKPOINT_INTERVAL", "100")),
        "fault_tolerant": e.get("EDL_FAULT_TOLERANT", "0") == "1",
        "pod_name": e.get("EDL_POD_NAME", ""),
    }


def init_distributed() -> None:
    """Join the slice's JAX coordination service when this pod is part
    of a multi-host TPU slice.  On GKE TPU podslices the TPU runtime
    env (``TPU_WORKER_HOSTNAMES`` etc.) carries everything
    ``jax.distributed.initialize`` needs; single-host pods skip this.
    (This one call replaces the reference's entire port/etcd discovery
    plumbing, SURVEY.md §2.5.)"""
    import jax

    if os.environ.get("TPU_WORKER_HOSTNAMES") or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    ):
        jax.distributed.initialize()


def run(
    entrypoint: str,
    steps: Optional[int] = None,
    coordinator_addr: str = "",
    global_batch_size: int = 0,
    checkpoint_interval: Optional[int] = None,
    seed: int = 0,
    dataset_examples: int = 4096,
) -> "ElasticTrainer":
    """Build and run the elastic training loop for a registered model.

    Returns the ElasticTrainer (with history) for inspection."""
    import jax
    import optax

    from edl_tpu.models.base import get_model
    from edl_tpu.runtime.coord_service import HTTPCoordinator
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.runtime.data import ShardedDataIterator, synthetic_dataset
    from edl_tpu.runtime.elastic import ElasticTrainer

    cfg = env_config()
    model = get_model(entrypoint or cfg["entrypoint"])
    n_dev = len(jax.devices())
    gbs = global_batch_size or cfg["global_batch_size"] or max(64, 8 * n_dev)
    data = ShardedDataIterator(
        synthetic_dataset(model.synth_batch, max(dataset_examples, gbs)),
        global_batch_size=gbs,
        seed=seed,
    )

    trainer_id = cfg["pod_name"] or f"trainer-{uuid.uuid4().hex[:8]}"
    addr = coordinator_addr or cfg["coordinator_addr"]
    heartbeat_ids = [trainer_id]
    if addr:
        coordinator = HTTPCoordinator(addr)
        coordinator.register(trainer_id)
    else:
        # Local mode: in-process coordinator, one membership per device.
        max_w = max(cfg["max_instance"], n_dev)
        legal = None
        if gbs:
            # same quantization the deployed coordinator gets via
            # --legal-sizes: only worlds dividing the global batch
            legal = [w for w in range(1, max_w + 1) if gbs % w == 0]
        coordinator = LocalCoordinator(
            target_world=min(cfg["max_instance"], n_dev) or n_dev,
            max_world=max_w,
            legal_sizes=legal,
        )
        heartbeat_ids = [f"{trainer_id}-{i}" for i in range(n_dev)]
        for tid in heartbeat_ids:
            coordinator.register(tid)

    et = ElasticTrainer(
        model,
        optax.adam(1e-3),
        data,
        coordinator,
        checkpoint_interval=(
            checkpoint_interval
            if checkpoint_interval is not None
            else cfg["checkpoint_interval"]
        ),
        seed=seed,
    )
    et.heartbeat_ids = heartbeat_ids
    if steps is None:
        steps = cfg["num_passes"] * data.batches_per_epoch
    et.run(steps)
    et.store.wait()
    return et


def main(argv=None):  # pragma: no cover - process entrypoint
    p = argparse.ArgumentParser(description="EDL-TPU trainer launcher")
    p.add_argument("--entrypoint", default="", help="registered model name")
    p.add_argument("--steps", type=int, default=None)
    p.add_argument("--coordinator", default="", help="coordinator address")
    p.add_argument("--global-batch-size", type=int, default=0)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    init_distributed()
    et = run(
        entrypoint=args.entrypoint,
        steps=args.steps,
        coordinator_addr=args.coordinator,
        global_batch_size=args.global_batch_size,
        seed=args.seed,
    )
    last = et.history[-1] if et.history else None
    print(
        f"done: steps={len(et.history)} "
        f"final_loss={last.loss if last else float('nan'):.4f} "
        f"resizes={len(et.resize_events)}"
    )


if __name__ == "__main__":  # pragma: no cover
    main()
