from edl_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_FSDP,
    AXIS_TP,
    AXIS_PP,
    AXIS_SP,
    AXIS_EP,
    MeshSpec,
    build_mesh,
    dp_mesh,
    batch_sharding,
    replicated_sharding,
    hint_activation,
)
from edl_tpu.parallel.pipeline import pipeline_1f1b_loss, pipeline_apply

__all__ = [
    "AXIS_DP",
    "AXIS_FSDP",
    "AXIS_TP",
    "AXIS_PP",
    "AXIS_SP",
    "AXIS_EP",
    "MeshSpec",
    "build_mesh",
    "dp_mesh",
    "batch_sharding",
    "replicated_sharding",
    "hint_activation",
    "pipeline_apply",
    "pipeline_1f1b_loss",
]
