from edl_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_FSDP,
    AXIS_TP,
    AXIS_PP,
    AXIS_SP,
    AXIS_EP,
    MeshSpec,
    build_mesh,
    dp_mesh,
    batch_sharding,
    replicated_sharding,
)
from edl_tpu.parallel.pipeline import pipeline_apply

__all__ = [
    "AXIS_DP",
    "AXIS_FSDP",
    "AXIS_TP",
    "AXIS_PP",
    "AXIS_SP",
    "AXIS_EP",
    "MeshSpec",
    "build_mesh",
    "dp_mesh",
    "batch_sharding",
    "replicated_sharding",
    "pipeline_apply",
]
