"""Device mesh construction and sharding vocabulary.

This replaces the reference system's *entire* communication topology.
The reference synced gradients through a parameter-server ReplicaSet
over TCP (``pkg/jobparser.go:74-112``; ports plumbing ``:237-263``) and
discovered peers via env vars + etcd (``:265-313``).  On TPU none of
that exists: trainers form a ``jax.sharding.Mesh`` over the slice's ICI
links, gradient sync is the allreduce XLA inserts for batch-sharded
computation, and "resizing the pserver pool" becomes "rebuilding the
mesh at a new world size".

Axis names (the framework-wide sharding vocabulary):

- ``dp``   data parallelism — batch dimension; the *elastic* axis.
- ``fsdp`` parameter sharding over the dp axis (ZeRO-style).
- ``tp``   tensor parallelism — hidden/heads dimensions.
- ``pp``   pipeline parallelism — layer stages.
- ``sp``   sequence/context parallelism — sequence dimension
           (ring attention); shares devices with ``tp`` by default.
- ``ep``   expert parallelism — MoE experts.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_TP = "tp"
AXIS_PP = "pp"
AXIS_SP = "sp"
AXIS_EP = "ep"

#: Canonical axis order: pipeline outermost (lowest-bandwidth cuts),
#: then data, then tensor innermost (highest-bandwidth, most-frequent
#: collectives ride the fastest ICI links).
CANONICAL_ORDER = (AXIS_PP, AXIS_DP, AXIS_FSDP, AXIS_EP, AXIS_TP, AXIS_SP)


@dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape: axis name -> size.  Axes of size 1 are kept
    so PartitionSpecs referring to them stay valid at every scale."""

    axes: Tuple[Tuple[str, int], ...]

    @staticmethod
    def create(**sizes: int) -> "MeshSpec":
        unknown = set(sizes) - set(CANONICAL_ORDER)
        if unknown:
            raise ValueError(f"unknown mesh axes: {sorted(unknown)}")
        ordered = tuple(
            (name, int(sizes.get(name, 1)))
            for name in CANONICAL_ORDER
            if name in sizes
        )
        for name, size in ordered:
            if size < 1:
                raise ValueError(f"axis {name} must have size >= 1, got {size}")
        return MeshSpec(axes=ordered)

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.axes)

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(s for _, s in self.axes)

    def size(self) -> int:
        out = 1
        for _, s in self.axes:
            out *= s
        return out

    def axis_size(self, name: str) -> int:
        for n, s in self.axes:
            if n == name:
                return s
        return 1


def build_mesh(
    spec: MeshSpec, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Build a Mesh over ``devices`` (default: all local devices).

    The device list's first ``spec.size()`` entries are used; this is
    the primitive elasticity builds on — a world of size ``w`` is "the
    first ``w * chips_per_trainer`` devices of the current membership
    generation" (ordering agreed through the coordinator, replacing the
    reference's etcd registry, ref ``pkg/jobparser.go:174-191``)."""
    if devices is None:
        devices = jax.devices()
    n = spec.size()
    if len(devices) < n:
        raise ValueError(
            f"mesh {dict(spec.axes)} needs {n} devices, have {len(devices)}"
        )
    arr = np.asarray(devices[:n], dtype=object).reshape(spec.shape)
    return Mesh(arr, axis_names=spec.names)


def dp_mesh(
    num_trainers: int, devices: Optional[Sequence[jax.Device]] = None
) -> Mesh:
    """Pure data-parallel mesh — the reference's one parallelism strategy
    (SURVEY.md §2.3), elastic over ``dp``."""
    return build_mesh(MeshSpec.create(dp=num_trainers), devices)


def batch_sharding(mesh: Mesh, *, extra_axes: Sequence[Optional[str]] = ()) -> NamedSharding:
    """Sharding for a batch-major array: leading dim split over every
    data-ish mesh axis present (dp and fsdp), remaining dims per
    ``extra_axes``."""
    data_axes = tuple(a for a in (AXIS_DP, AXIS_FSDP) if a in mesh.axis_names)
    lead = data_axes if len(data_axes) > 1 else (data_axes[0] if data_axes else None)
    return NamedSharding(mesh, P(lead, *extra_axes))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def filter_partition_spec(spec: P, axis_names) -> P:
    """Drop references to axes not in ``axis_names`` so ONE rule set
    serves every mesh: a pure-DP mesh simply ignores tp/fsdp
    placements, a dp×tp serving mesh ignores fsdp/ep, and so on.
    Tuple entries filter member-wise (an empty survivor becomes None).
    This is the rule the Trainer has always applied to
    ``model.param_partition`` specs, extracted so the serving plane
    places weights by the SAME rules training shards them with."""
    names = set(axis_names)

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in names)
            return kept if kept else None
        return entry if entry in names else None

    return P(*(keep(e) for e in spec))


def partition_shardings(mesh: Mesh, spec_tree):
    """A pytree of PartitionSpec rules -> a congruent pytree of
    ``NamedSharding`` on ``mesh``, with absent axes filtered per
    ``filter_partition_spec``.  The one-call bridge from a model's
    ``param_partition`` rules to concrete placements."""
    import jax

    return jax.tree_util.tree_map(
        lambda s: NamedSharding(
            mesh, filter_partition_spec(s, mesh.axis_names)
        ),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def hint_activation(x, *entries):
    """Pin an activation's layout on the AMBIENT mesh (a no-op when
    there is none, or when none of the named axes exist on it).

    Model code calls this with full-vocabulary entries — e.g.
    ``hint_activation(h, ("dp", "fsdp"), None, "tp")`` for a
    [batch, seq, ffn] tensor — and the entries are filtered to the axes
    the current mesh actually has, so one call site serves every
    layout.  Why it exists: partition rules constrain PARAMS only;
    without activation pins GSPMD is free to pick mismatched layouts
    between the forward and its transpose, and on tp meshes it resolves
    the mismatch by replicating whole activation tensors every step
    ("Involuntary full rematerialization" — VERDICT r4 weak-2).

    Reads the ambient mesh through ``jax._src.mesh.thread_resources``
    (private API, same caveat as the launcher's heartbeat patch):
    guarded so drift degrades to no pinning, never to a trace error."""
    try:
        from jax._src import mesh as mesh_lib

        mesh = mesh_lib.thread_resources.env.physical_mesh
    except Exception:  # pragma: no cover - jax private API drift
        return x
    if mesh is None or mesh.empty:
        return x
    if mesh.devices.size == 1:
        # Single-device mesh: a constraint can only inhibit fusion,
        # never place anything.
        return x
    names = set(mesh.axis_names)

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = tuple(a for a in entry if a in names)
            return kept if kept else None
        return entry if entry in names else None

    spec = P(*(keep(e) for e in entries))
    if all(e is None for e in spec):
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def mesh_debug_string(mesh: Mesh) -> str:
    return (
        f"Mesh(shape={dict(zip(mesh.axis_names, mesh.devices.shape))}, "
        f"devices={mesh.devices.size})"
    )
