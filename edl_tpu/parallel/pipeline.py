"""Pipeline parallelism over the mesh's ``pp`` axis: GPipe and 1F1B.

The last mesh axis to become load-bearing: stages of a homogeneous
layer stack shard over ``pp`` (each device holds ONE stage's
parameters), microbatches stream through the pipeline, and activations
hop stage-to-stage with ``lax.ppermute`` — a neighbor exchange, the
cheapest collective, riding the lowest-bandwidth mesh axis by the
canonical order (``parallel/mesh.py``: pipeline cuts outermost).

Two schedules:

- ``pipeline_apply`` — plain GPipe.  ``M`` microbatches over ``S``
  stages run in ``M + S - 1`` ticks; at tick ``t`` stage ``r``
  processes microbatch ``t - r`` (when in range).  One ``lax.scan``
  inside ``shard_map``; reverse-mode AD differentiates it like any
  scan, which means the scan saves every tick's intra-stage
  activations — peak activation memory O(M).
- ``pipeline_1f1b_loss`` — one-forward-one-backward.  The schedule is
  NOT differentiated: each cycle runs a forward sub-tick and a
  backward sub-tick (explicit per-stage ``jax.vjp``), stage inputs
  live in a (2S-1)-slot ring buffer, activation cotangents hop
  backward via the reverse ppermute, and parameter gradients
  accumulate in the scan carry.  Activation memory is O(S)
  microbatches; the price is the standard recompute (each stage's
  forward runs again inside its backward sub-tick — Megatron's
  activation-recompute tradeoff).  An outer ``custom_vjp`` makes the
  whole thing a differentiable scalar loss: its fwd computes (loss,
  grads) in one pass and its bwd just scales the saved grads.

Composition: batch may additionally shard over ``dp`` (the microbatch
dim's spec), params over ``fsdp``/``tp`` within a stage — the same
GSPMD composition as every other axis here.  The reference system has
nothing remotely comparable (SURVEY.md §2.3: pipeline parallelism
explicitly absent); this exceeds the parity bar.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pp",
    batch_axis: str = "dp",
) -> jax.Array:
    """Run ``x`` through ``S`` pipeline stages sharded over ``axis``.

    ``stage_fn(stage_params, h) -> h``: one stage's computation (e.g.
    a chunk of transformer blocks).  ``stacked_params``: pytree whose
    leaves carry a leading stage dimension ``S`` (sharded over
    ``axis``).  ``x``: [B, ...] activations; ``B`` must divide into
    ``num_microbatches`` equal microbatches.  Returns [B, ...] after
    all stages, numerically identical to applying the stages
    sequentially (up to float reassociation).
    """
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    S = sizes.get(axis, 1)
    M = num_microbatches
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    bad = [
        p.shape[0]
        for p in jax.tree_util.tree_leaves(stacked_params)
        if p.shape[0] != n_stages
    ]
    if bad:
        raise ValueError(
            f"stacked_params leaves disagree on the stage dim: {bad}"
        )
    if S > 1 and n_stages != S:
        # A mismatch would silently run p[0] of each rank's multi-stage
        # slice — wrong math, no error.
        raise ValueError(
            f"stacked_params carry {n_stages} stages but the mesh's "
            f"{axis!r} axis has {S} devices; they must match (fold "
            "layers-per-stage INSIDE stage_fn)"
        )
    if S == 1:
        # No pipeline axis: sequential application, same semantics.
        h = x
        for s_i in range(n_stages):
            h = stage_fn(jax.tree.map(lambda p: p[s_i], stacked_params), h)
        return h

    mb = B // M
    xm = x.reshape(M, mb, *x.shape[1:])

    # Activations flow at the STAGE OUTPUT dtype (mixed precision: bf16
    # in, f32 stage math -> the carry is f32, like the sequential
    # stack's inter-stage dtype).
    out_aval = jax.eval_shape(
        stage_fn,
        jax.tree.map(
            lambda p: jax.ShapeDtypeStruct(p.shape[1:], p.dtype),
            stacked_params,
        ),
        jax.ShapeDtypeStruct((mb,) + x.shape[1:], x.dtype),
    )
    if out_aval.shape != (mb,) + x.shape[1:]:
        raise ValueError(
            f"stage_fn must preserve activation shape; got "
            f"{out_aval.shape} from {(mb,) + x.shape[1:]}"
        )
    act_dtype = out_aval.dtype

    # Microbatch dim may shard over dp; stage dim over pp; everything
    # else replicated at this level (fsdp/tp compose inside stage_fn
    # via GSPMD on the params' own specs).
    dp_size = sizes.get(batch_axis, 1)
    bspec = batch_axis if batch_axis in sizes and mb % dp_size == 0 else None
    if batch_axis in sizes and dp_size > 1 and bspec is None:
        import sys

        print(
            f"[edl] pipeline_apply: microbatch width {mb} not divisible "
            f"by the {batch_axis!r} axis ({dp_size}); running the "
            "pipeline REPLICATED over it (correct but wastes "
            f"{dp_size}x compute) — pick num_microbatches so the "
            f"microbatch width B/num_microbatches is a multiple of "
            f"{dp_size}",
            file=sys.stderr,
        )
    x_spec = P(None, bspec, *([None] * (x.ndim - 1)))
    p_spec = jax.tree.map(lambda _: P(axis), stacked_params)
    # Output keeps the [M, mb, ...] layout (same spec as the input) and
    # flattens OUTSIDE the shard_map: flattening per-shard would
    # interleave the dp-sharded microbatch dim into the wrong global
    # row order.
    out_spec = x_spec

    def local_fn(params, xm_blk):
        # shard_map hands each pp rank its stage slice with the stage
        # dim collapsed to 1: strip it.
        p_local = jax.tree.map(lambda p: p[0], params)
        r = lax.axis_index(axis)
        perm = [(i, i + 1) for i in range(S - 1)]

        def tick(carry, t):
            prev_y, outs = carry
            recv = lax.ppermute(prev_y, axis, perm)  # rank r <- r-1
            # rank 0 feeds microbatch t (clamped; out-of-range ticks
            # compute garbage that never lands anywhere)
            feed = xm_blk[jnp.clip(t, 0, M - 1)].astype(act_dtype)
            h = jnp.where(r == 0, feed, recv)
            y = stage_fn(p_local, h)
            # rank S-1 emits microbatch t-(S-1) when in range
            m = t - (S - 1)
            emit = jnp.logical_and(r == S - 1, jnp.logical_and(m >= 0, m < M))
            outs = outs.at[jnp.clip(m, 0, M - 1)].add(
                jnp.where(emit, y, jnp.zeros_like(y))
            )
            return (y, outs), None

        y0 = jnp.zeros(xm_blk.shape[1:], act_dtype)
        outs0 = jnp.zeros(xm_blk.shape, act_dtype)
        (_, outs), _ = lax.scan(
            tick, (y0, outs0), jnp.arange(M + S - 1)
        )
        # Only the last stage holds real outputs: replicate over pp.
        # psum is deliberate (VERDICT r4 weak-6 suggested a one-hop
        # broadcast): jax has no broadcast-from-rank primitive —
        # ppermute rejects one-src-many-dst multicast, and an
        # all_gather+select moves (S-1)x the buffer where the ring
        # all-reduce moves ~2x the optimal pipelined broadcast.  Within
        # 2x of the best any primitive offers, with XLA's chunked
        # pipelining for free.
        return lax.psum(outs, axis)

    kwargs = dict(
        mesh=mesh, in_specs=(p_spec, x_spec), out_specs=out_spec
    )
    try:  # jax >= 0.8 renamed check_rep -> check_vma
        fn = shard_map(local_fn, check_vma=False, **kwargs)
    except TypeError:  # pragma: no cover - older jax
        fn = shard_map(local_fn, check_rep=False, **kwargs)
    return fn(stacked_params, xm).reshape(B, *x.shape[1:])


def pipeline_1f1b_loss(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    head_fn: Callable[[Any, jax.Array, Any], Any],
    stacked_params: Any,
    head_params: Any,
    x: jax.Array,
    aux: Any,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pp",
    batch_axis: str = "dp",
) -> jax.Array:
    """Mean loss through the 1F1B pipeline schedule, differentiable.

    ``stage_fn(stage_params, h [mb, F]) -> h``: one stage.
    ``head_fn(head_params, h [mb, F], aux_mb) -> (loss_sum, count)``:
    the per-microbatch loss head run at the LAST stage (e.g. final
    norm + tied-vocab xent); must return the SUM of per-token losses
    and the valid-token count as f32 scalars, so the microbatch
    combination sum(loss_sums)/sum(counts) is exactly the full-batch
    mean regardless of per-microbatch valid counts.
    ``aux``: [B, ...] per-example head inputs (labels), microbatched
    alongside ``x``.

    Returns the scalar mean loss.  Gradients flow to stacked_params,
    head_params and x (the embedding upstream); the backward pass costs
    nothing beyond scaling — the schedule already computed the grads.
    The flip side: there is NO grad-free path.  The backward sub-ticks
    run inside the schedule unconditionally, so a forward-only caller
    (evaluation) pays the full backward schedule anyway — see the
    caveat at ``models/pipeline_lm``'s ``schedule`` flag (ADVICE r5).

    Memory: the schedule is ONE un-differentiated scan whose carry
    holds a (2S-1)-microbatch input ring buffer + param-sized grad
    accumulators, so peak activation memory is O(S) microbatches
    (GPipe-under-AD saves O(M) ticks of intra-stage activations).
    Compute: each stage's forward runs twice (once in the fwd sub-tick,
    once rematerialized inside its vjp) — the standard
    activation-recompute tradeoff."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    S = sizes.get(axis, 1)
    M = num_microbatches
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    if S == 1:
        # No pipeline axis: sequential forward + head, plain AD.
        n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        mb = B // M
        ls_total = jnp.float32(0)
        cnt_total = jnp.float32(0)
        for m in range(M):
            h = x[m * mb : (m + 1) * mb]
            for s_i in range(n_stages):
                h = stage_fn(
                    jax.tree.map(lambda p: p[s_i], stacked_params), h
                )
            aux_m = jax.tree.map(lambda a: a[m * mb : (m + 1) * mb], aux)
            ls, cnt = head_fn(head_params, h, aux_m)
            ls_total = ls_total + ls
            cnt_total = cnt_total + cnt
        return ls_total / jnp.maximum(cnt_total, 1.0)

    mb = B // M

    out_aval = jax.eval_shape(
        stage_fn,
        jax.tree.map(
            lambda p: jax.ShapeDtypeStruct(p.shape[1:], p.dtype),
            stacked_params,
        ),
        jax.ShapeDtypeStruct((mb,) + x.shape[1:], x.dtype),
    )
    act_dtype = out_aval.dtype
    feat_shape = (mb,) + x.shape[1:]

    dp_size = sizes.get(batch_axis, 1)
    bspec = batch_axis if batch_axis in sizes and mb % dp_size == 0 else None
    x_spec = P(None, bspec, *([None] * (x.ndim - 1)))
    aux_specs = jax.tree.map(
        lambda a: P(None, bspec, *([None] * (a.ndim - 1))), aux
    )
    p_spec = jax.tree.map(lambda _: P(axis), stacked_params)
    h_spec = jax.tree.map(lambda _: P(), head_params)

    R = 2 * S - 1  # ring slots; +1 trash slot appended below
    C = M + 2 * S - 2  # cycles

    def local_fn(params, head_p, xm_blk, aux_blk):
        p_local = jax.tree.map(lambda p: p[0], params)
        r = lax.axis_index(axis)
        fwd_perm = [(i, i + 1) for i in range(S - 1)]
        bwd_perm = [(i + 1, i) for i in range(S - 1)]
        feat = xm_blk.shape[1:]

        def head_closure(hp, y, a_mb):
            ls, cnt = head_fn(hp, y, a_mb)
            return jnp.float32(ls), jnp.float32(cnt)

        zeros_gH = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), head_p
        )
        zeros_gP = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), p_local
        )

        def cycle(carry, c):
            y_prev, dh_prev, buf, gP, gH, dxb, ls, cnt = carry

            # ---- forward sub-tick -------------------------------------
            recv = lax.ppermute(y_prev, axis, fwd_perm)
            mf = c - r
            f_valid = jnp.logical_and(mf >= 0, mf < M)
            feed = xm_blk[jnp.clip(mf, 0, M - 1)].astype(act_dtype)
            h_in = jnp.where(r == 0, feed, recv)
            # store the stage input; invalid sub-ticks write the trash
            # slot R (a live slot must never be clobbered)
            slot = jnp.where(f_valid, jnp.mod(mf, R), R)
            buf = lax.dynamic_update_index_in_dim(buf, h_in, slot, 0)
            y = stage_fn(p_local, h_in)

            # head (last rank only — lax.cond keeps the vocab-sized
            # head off every other rank's critical path)
            a_mb = jax.tree.map(
                lambda a: a[jnp.clip(mf, 0, M - 1)], aux_blk
            )

            def run_head(_):
                (ls_mb, cnt_mb), h_vjp = jax.vjp(
                    lambda hp, yy: head_closure(hp, yy, a_mb), head_p, y
                )
                dH, dY = h_vjp((jnp.float32(1), jnp.float32(0)))
                return ls_mb, cnt_mb, dH, dY

            def skip_head(_):
                return (
                    jnp.float32(0),
                    jnp.float32(0),
                    zeros_gH,
                    jnp.zeros(y.shape, y.dtype),
                )

            is_last = r == S - 1
            ls_mb, cnt_mb, dH, dY_head = lax.cond(
                jnp.logical_and(is_last, f_valid), run_head, skip_head, None
            )
            ls = ls + ls_mb
            cnt = cnt + cnt_mb
            gH = jax.tree.map(jnp.add, gH, dH)

            # ---- backward sub-tick ------------------------------------
            recv_d = lax.ppermute(dh_prev, axis, bwd_perm)
            mbk = c - (2 * S - 2 - r)
            b_valid = jnp.logical_and(mbk >= 0, mbk < M)
            dY = jnp.where(is_last, dY_head.astype(act_dtype), recv_d)
            h_saved = lax.dynamic_index_in_dim(
                buf, jnp.where(b_valid, jnp.mod(mbk, R), R), 0, keepdims=False
            )
            _, s_vjp = jax.vjp(stage_fn, p_local, h_saved)
            dp, dh = s_vjp(dY)
            gP = jax.tree.map(
                lambda g, d: g + jnp.where(b_valid, d, 0.0).astype(g.dtype),
                gP,
                dp,
            )
            emit_dx = jnp.logical_and(b_valid, r == 0)
            dxb = dxb.at[jnp.clip(mbk, 0, M - 1)].add(
                jnp.where(emit_dx, dh, jnp.zeros_like(dh)).astype(dxb.dtype)
            )
            return (y, dh, buf, gP, gH, dxb, ls, cnt), None

        buf0 = jnp.zeros((R + 1,) + feat, act_dtype)
        carry0 = (
            jnp.zeros(feat, act_dtype),              # y hop
            jnp.zeros(feat, act_dtype),              # dh hop
            buf0,
            zeros_gP,
            zeros_gH,
            jnp.zeros((M,) + feat, jnp.float32),     # dx
            jnp.float32(0),
            jnp.float32(0),
        )
        (_, _, _, gP, gH, dxb, ls, cnt), _ = lax.scan(
            cycle, carry0, jnp.arange(C)
        )

        # Reductions: loss/count/head grads sum over dp shards AND pp
        # (only rank S-1 contributed); stage grads sum over dp only
        # (each rank owns its stage); dx sums over pp only (each dp
        # shard owns its rows).
        red_axes = (axis, batch_axis) if bspec else (axis,)
        ls = lax.psum(ls, red_axes)
        cnt = lax.psum(cnt, red_axes)
        gH = jax.tree.map(lambda g: lax.psum(g, red_axes), gH)
        if bspec:
            gP = jax.tree.map(lambda g: lax.psum(g, batch_axis), gP)
        dxb = lax.psum(dxb, axis)
        denom = jnp.maximum(cnt, 1.0)
        # grads of the MEAN loss (the schedule accumulated d loss_sum)
        gP = jax.tree.map(lambda g: (g / denom)[None], gP)  # restage dim
        gH = jax.tree.map(lambda g: g / denom, gH)
        dxb = dxb / denom
        return ls / denom, gP, gH, dxb

    kwargs = dict(
        mesh=mesh,
        in_specs=(p_spec, h_spec, x_spec, aux_specs),
        out_specs=(
            P(),
            jax.tree.map(lambda _: P(axis), stacked_params),
            jax.tree.map(lambda _: P(), head_params),
            x_spec,
        ),
    )
    try:
        sharded = shard_map(local_fn, check_vma=False, **kwargs)
    except TypeError:  # pragma: no cover - older jax
        sharded = shard_map(local_fn, check_rep=False, **kwargs)

    @jax.custom_vjp
    def loss_of(sp, hp, xx, ax):
        return loss_fwd(sp, hp, xx, ax)[0]

    def loss_fwd(sp, hp, xx, ax):
        loss, gP, gH, dxb = sharded(
            sp,
            hp,
            xx.reshape(M, mb, *xx.shape[1:]),
            jax.tree.map(lambda a: a.reshape(M, mb, *a.shape[1:]), ax),
        )
        return loss, (gP, gH, dxb, jax.tree.map(jnp.shape, ax))

    def loss_bwd(res, g):
        gP, gH, dxb, _ = res
        dx_full = (g * dxb).reshape(B, *x.shape[1:]).astype(x.dtype)
        return (
            jax.tree.map(lambda t: (g * t).astype(t.dtype), gP),
            jax.tree.map(lambda t: (g * t).astype(t.dtype), gH),
            dx_full,
            jax.tree.map(lambda a: None, aux),
        )

    loss_of.defvjp(loss_fwd, loss_bwd)
    return loss_of(stacked_params, head_params, x, aux)
