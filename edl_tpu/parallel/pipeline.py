"""GPipe-style pipeline parallelism over the mesh's ``pp`` axis.

The last mesh axis to become load-bearing: stages of a homogeneous
layer stack shard over ``pp`` (each device holds ONE stage's
parameters), microbatches stream through the pipeline, and activations
hop stage-to-stage with ``lax.ppermute`` — a neighbor exchange, the
cheapest collective, riding the lowest-bandwidth mesh axis by the
canonical order (``parallel/mesh.py``: pipeline cuts outermost).

Schedule: plain GPipe.  ``M`` microbatches over ``S`` stages run in
``M + S - 1`` ticks; at tick ``t`` stage ``r`` processes microbatch
``t - r`` (when in range).  The bubble fraction is ``(S-1)/(M+S-1)``
— pick ``M >> S``.  The whole schedule is ONE ``lax.scan`` inside
``shard_map``, so reverse-mode AD differentiates it like any scan:
the transpose of ``ppermute`` is the reverse hop and the backward
schedule emerges mechanically.  Correctness first: a 1F1B interleave
(which shrinks peak activation memory from M microbatches to S) would
require taking MANUAL control of the forward/backward interleaving —
a custom_vjp over the whole schedule — rather than relying on scan
AD; that is future work, not a parameter away.

Composition: batch may additionally shard over ``dp`` (the microbatch
dim's spec), params over ``fsdp``/``tp`` within a stage — the same
GSPMD composition as every other axis here.  The reference system has
nothing remotely comparable (SURVEY.md §2.3: pipeline parallelism
explicitly absent); this exceeds the parity bar.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    num_microbatches: int,
    axis: str = "pp",
    batch_axis: str = "dp",
) -> jax.Array:
    """Run ``x`` through ``S`` pipeline stages sharded over ``axis``.

    ``stage_fn(stage_params, h) -> h``: one stage's computation (e.g.
    a chunk of transformer blocks).  ``stacked_params``: pytree whose
    leaves carry a leading stage dimension ``S`` (sharded over
    ``axis``).  ``x``: [B, ...] activations; ``B`` must divide into
    ``num_microbatches`` equal microbatches.  Returns [B, ...] after
    all stages, numerically identical to applying the stages
    sequentially (up to float reassociation).
    """
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    S = sizes.get(axis, 1)
    M = num_microbatches
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible into {M} microbatches")
    n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    bad = [
        p.shape[0]
        for p in jax.tree_util.tree_leaves(stacked_params)
        if p.shape[0] != n_stages
    ]
    if bad:
        raise ValueError(
            f"stacked_params leaves disagree on the stage dim: {bad}"
        )
    if S > 1 and n_stages != S:
        # A mismatch would silently run p[0] of each rank's multi-stage
        # slice — wrong math, no error.
        raise ValueError(
            f"stacked_params carry {n_stages} stages but the mesh's "
            f"{axis!r} axis has {S} devices; they must match (fold "
            "layers-per-stage INSIDE stage_fn)"
        )
    if S == 1:
        # No pipeline axis: sequential application, same semantics.
        h = x
        for s_i in range(n_stages):
            h = stage_fn(jax.tree.map(lambda p: p[s_i], stacked_params), h)
        return h

    mb = B // M
    xm = x.reshape(M, mb, *x.shape[1:])

    # Activations flow at the STAGE OUTPUT dtype (mixed precision: bf16
    # in, f32 stage math -> the carry is f32, like the sequential
    # stack's inter-stage dtype).
    out_aval = jax.eval_shape(
        stage_fn,
        jax.tree.map(
            lambda p: jax.ShapeDtypeStruct(p.shape[1:], p.dtype),
            stacked_params,
        ),
        jax.ShapeDtypeStruct((mb,) + x.shape[1:], x.dtype),
    )
    if out_aval.shape != (mb,) + x.shape[1:]:
        raise ValueError(
            f"stage_fn must preserve activation shape; got "
            f"{out_aval.shape} from {(mb,) + x.shape[1:]}"
        )
    act_dtype = out_aval.dtype

    # Microbatch dim may shard over dp; stage dim over pp; everything
    # else replicated at this level (fsdp/tp compose inside stage_fn
    # via GSPMD on the params' own specs).
    dp_size = sizes.get(batch_axis, 1)
    bspec = batch_axis if batch_axis in sizes and mb % dp_size == 0 else None
    if batch_axis in sizes and dp_size > 1 and bspec is None:
        import sys

        print(
            f"[edl] pipeline_apply: microbatch width {mb} not divisible "
            f"by the {batch_axis!r} axis ({dp_size}); running the "
            "pipeline REPLICATED over it (correct but wastes "
            f"{dp_size}x compute) — pick num_microbatches so the "
            f"microbatch width B/num_microbatches is a multiple of "
            f"{dp_size}",
            file=sys.stderr,
        )
    x_spec = P(None, bspec, *([None] * (x.ndim - 1)))
    p_spec = jax.tree.map(lambda _: P(axis), stacked_params)
    # Output keeps the [M, mb, ...] layout (same spec as the input) and
    # flattens OUTSIDE the shard_map: flattening per-shard would
    # interleave the dp-sharded microbatch dim into the wrong global
    # row order.
    out_spec = x_spec

    def local_fn(params, xm_blk):
        # shard_map hands each pp rank its stage slice with the stage
        # dim collapsed to 1: strip it.
        p_local = jax.tree.map(lambda p: p[0], params)
        r = lax.axis_index(axis)
        perm = [(i, i + 1) for i in range(S - 1)]

        def tick(carry, t):
            prev_y, outs = carry
            recv = lax.ppermute(prev_y, axis, perm)  # rank r <- r-1
            # rank 0 feeds microbatch t (clamped; out-of-range ticks
            # compute garbage that never lands anywhere)
            feed = xm_blk[jnp.clip(t, 0, M - 1)].astype(act_dtype)
            h = jnp.where(r == 0, feed, recv)
            y = stage_fn(p_local, h)
            # rank S-1 emits microbatch t-(S-1) when in range
            m = t - (S - 1)
            emit = jnp.logical_and(r == S - 1, jnp.logical_and(m >= 0, m < M))
            outs = outs.at[jnp.clip(m, 0, M - 1)].add(
                jnp.where(emit, y, jnp.zeros_like(y))
            )
            return (y, outs), None

        y0 = jnp.zeros(xm_blk.shape[1:], act_dtype)
        outs0 = jnp.zeros(xm_blk.shape, act_dtype)
        (_, outs), _ = lax.scan(
            tick, (y0, outs0), jnp.arange(M + S - 1)
        )
        # Only the last stage holds real outputs: replicate over pp.
        # psum is deliberate (VERDICT r4 weak-6 suggested a one-hop
        # broadcast): jax has no broadcast-from-rank primitive —
        # ppermute rejects one-src-many-dst multicast, and an
        # all_gather+select moves (S-1)x the buffer where the ring
        # all-reduce moves ~2x the optimal pipelined broadcast.  Within
        # 2x of the best any primitive offers, with XLA's chunked
        # pipelining for free.
        return lax.psum(outs, axis)

    kwargs = dict(
        mesh=mesh, in_specs=(p_spec, x_spec), out_specs=out_spec
    )
    try:  # jax >= 0.8 renamed check_rep -> check_vma
        fn = shard_map(local_fn, check_vma=False, **kwargs)
    except TypeError:  # pragma: no cover - older jax
        fn = shard_map(local_fn, check_rep=False, **kwargs)
    return fn(stacked_params, xm).reshape(B, *x.shape[1:])
