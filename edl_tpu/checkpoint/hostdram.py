"""Asynchronous host-DRAM checkpointing with resharding restore.

The reference delegates checkpoint/resume wholly to the external
PaddlePaddle master (SURVEY.md §5.4 — nothing in-repo but a design-doc
link, ``README.md:18-21``).  For the TPU rebuild this subsystem is the
heart of the <60s-resize north star (BASELINE.md): a recent checkpoint
must *always* be warm in host DRAM so a membership change never waits
on storage, and restore must place every leaf onto a mesh of a
different size/shape than the one it was saved from.

Design:

- ``save_async`` enqueues device->host copies without blocking the step
  loop: ``copy_to_host_async()`` on every leaf (pure DMA issue), then a
  background thread materializes numpy arrays and publishes the
  checkpoint atomically.
- The store keeps the last ``keep`` checkpoints in DRAM, plus optional
  disk spill (numpy ``.npz`` + a json manifest) for durability across
  host loss — the elastic fast path never touches disk.
- ``restore`` takes a target ``Mesh`` + sharding pytree and
  ``jax.device_put``s each leaf; XLA handles any source->target layout
  change, which is exactly "re-shard optimizer state across a changed
  mesh" (SURVEY.md §7.4) when param shardings are mesh-dependent.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _legacy_chained_crc(leaves: List[np.ndarray]) -> int:
    """The pre-digest_v2 checkpoint fingerprint (crc32 chained over
    raw leaf bytes) — kept ONLY to verify durable spills written by
    older revisions at cold start."""
    import zlib

    crc = 0
    for leaf in leaves:
        arr = np.ascontiguousarray(leaf).reshape(-1).view(np.uint8)
        crc = zlib.crc32(arr, crc)
    return crc


def _pack_leaf_digests(leaf_digests: List[int]) -> int:
    """Whole-checkpoint fingerprint from per-leaf crc32s: crc32 over
    the packed digest vector.  Deriving the checkpoint digest from the
    leaf digests (instead of chaining a second pass over the raw bytes)
    means one memory pass yields BOTH granularities — the per-leaf
    vector the delta-aware restore agreement trades, and the single
    int the whole-checkpoint agreement and spill manifests record."""
    import zlib

    return zlib.crc32(np.asarray(leaf_digests, np.uint32).tobytes())


def _spill_shard_layout(ckpt):
    """Fabric shard layout at the DEPLOYMENT boundary settings for a
    checkpoint's leaves — boundaries are world-independent, so the
    world size is immaterial (1).  Shared by the flush fingerprint and
    the spill manifest so both hit the same ``shard_digests`` cache."""
    from edl_tpu.checkpoint.fabric import (
        ShardLayout,
        deployment_shard_bytes,
        leaf_rows,
    )

    return ShardLayout.build(
        [l.nbytes for l in ckpt.leaves],
        1,
        shard_bytes=deployment_shard_bytes(),
        rows=leaf_rows(ckpt.leaves),
    )


#: shard-only durable spill naming: each fabric rank writes ONLY its
#: owned shards (``ckpt-<step>.shard-r<rank>.{json,npz}``); the union
#: across ranks is the durable full state.  Kept distinct from the
#: full-copy ``ckpt-<step>.{json,npz}`` family so mixed dirs (rolling
#: upgrade to shard-only) load either.
_SHARD_SPILL_RE = re.compile(r"^ckpt-(\d{12})\.shard-r(\d+)\.json$")


def scan_shard_spills(spill_dir: str) -> Dict[int, Dict[int, str]]:
    """step -> {fabric rank -> manifest filename} for every shard-only
    spill in ``spill_dir``."""
    out: Dict[int, Dict[int, str]] = {}
    try:
        names = os.listdir(spill_dir)
    except OSError:
        return out
    for f in names:
        m = _SHARD_SPILL_RE.match(f)
        if m:
            out.setdefault(int(m.group(1)), {})[int(m.group(2))] = f
    return out


def newest_covered_shard_step(spill_dir: str) -> Optional[tuple]:
    """``(step, {rank: (name, manifest)})`` for the NEWEST step whose
    rank manifests together cover every shard index — the shard-only
    analogue of "the newest intact full spill".  Coverage is judged
    from the manifests alone (each records its indices and the total
    shard count), so no template or byte read is needed to pick the
    step.  None when no complete set exists (e.g. a rank's spill was
    torn mid-write: that step is skipped, an older covered one
    loads)."""
    by_step = scan_shard_spills(spill_dir)
    for step in sorted(by_step, reverse=True):
        mans: Dict[int, tuple] = {}
        covered: set = set()
        total = None
        ok = True
        for rank, name in by_step[step].items():
            try:
                with open(os.path.join(spill_dir, name)) as f:
                    man = json.load(f)
            except (OSError, ValueError):
                ok = False
                break
            mans[rank] = (name, man)
            covered.update(int(i) for i in man.get("indices", ()))
            n = int(man.get("n_shards", -1))
            if total is None:
                total = n
            elif total != n:
                ok = False  # mixed shard granularities: not one set
                break
        if ok and total is not None and covered >= set(range(total)):
            return step, mans
    return None


def load_shard_spill_bytes(
    spill_dir: str,
    mans: Dict[int, tuple],
    want: Optional[set] = None,
) -> tuple:
    """``({shard index: uint8 array}, {shard index: crc})`` read from a
    shard-spill manifest set.  ``want`` restricts to those indices (a
    shard-only member loads just its own slice + K buddy shards — the
    cold-start memory contract); None loads all.  Every shard read is
    CRC-checked against its manifest digest, so a torn/bit-rotted
    spill localizes to ONE shard and raises rather than restoring."""
    import zlib

    out: Dict[int, np.ndarray] = {}
    crcs: Dict[int, int] = {}
    for rank in sorted(mans):
        name, man = mans[rank]
        idxs = [int(i) for i in man.get("indices", ())]
        need = [
            i for i in idxs if (want is None or i in want) and i not in out
        ]
        if not need:
            continue
        digs = {
            int(i): int(d) for i, d in zip(idxs, man.get("digests", ()))
        }
        npz_path = os.path.join(spill_dir, name[: -len(".json")] + ".npz")
        with np.load(npz_path) as z:
            for i in need:
                arr = np.asarray(z[f"s_{i}"], np.uint8)
                if zlib.crc32(arr) != digs.get(i):
                    raise RuntimeError(
                        f"shard {i} in {npz_path} failed CRC "
                        "verification (torn/bit-rotted shard spill)"
                    )
                out[i] = arr
                crcs[i] = digs[i]
    return out, crcs


def leaf_placer(mesh: Mesh):
    """Per-leaf device placement onto ``mesh``: plain device_put on a
    fully-addressable mesh; shard-sliced ``make_array_from_callback``
    when the mesh spans processes this one cannot address.  Shared by
    ``HostDRAMStore.restore`` and the streaming restore transfer
    (``checkpoint/transfer.py``), which places leaves one at a time so
    placement overlaps the remaining network transfer."""
    multiproc = any(
        d.process_index != jax.process_index() for d in mesh.devices.flat
    )
    cpu = all(d.platform == "cpu" for d in mesh.devices.flat)

    def place(x, s):
        if not multiproc:
            if (
                cpu
                and isinstance(x, np.ndarray)
                and not s.is_fully_replicated
            ):
                # Sharded target (the tp serving mesh): stage each
                # device's SLICE through jnp.array instead of the whole
                # leaf — swap/restore staging traffic per device is the
                # shard's bytes (1/tp for a tp-sharded kernel), and the
                # owned-buffer discipline is the multiproc branch's
                # (a raw numpy slice would be zero-copied by this
                # jaxlib without keeping the temp alive — dangling
                # buffers; see below).  Slice boundaries are jax's own
                # ceil-chunk rule — the same one
                # ``checkpoint.fabric.gspmd_chunk`` encodes for the
                # shard fabric, so the two accountings agree.
                return jax.make_array_from_callback(
                    x.shape, s, lambda idx: jnp.array(x[idx])
                )
            if cpu and isinstance(x, np.ndarray):
                # CPU backend: device_put ZERO-COPIES aligned numpy — a
                # replicated target then backs every per-device
                # "buffer" with the checkpoint's own bytes.  The train
                # step donates its state input, and a persistent-cache
                # DESERIALIZED executable performs that donation as a
                # true in-place write (the freshly-compiled path copies
                # external zero-copy buffers): each replica increments
                # the ONE shared buffer, so a restored step counter
                # advances by world_size per step and the checkpoint's
                # host bytes silently follow the live state.  Staging
                # an owned device array first makes device_put produce
                # per-device owned buffers (host memory either way on
                # CPU; real accelerators always DMA a copy).  The
                # staging lowers through pjit — one tiny XLA compile
                # per distinct leaf shape/dtype, paid OUTSIDE the
                # resize window by ``warm_leaf_conversions`` (a fresh
                # per-shard numpy copy via make_array_from_callback
                # would avoid the compile but is zero-copied by this
                # jaxlib without keeping the temp alive — dangling
                # buffers).
                x = jnp.array(x)
            return jax.device_put(x, s)
        arr = np.asarray(x)
        if cpu:
            # Same zero-copy hazard as above, per local device: two
            # local replicas handed the same host slice would share one
            # buffer.  Staging each shard through jnp.array hands the
            # callback machinery a jax-OWNED buffer, so every device
            # gets a distinct owned copy.  (A fresh numpy temp per
            # callback would also be distinct but this jaxlib
            # zero-copies it without keeping the temp alive — the
            # buffers dangle once the temp is collected, and workers
            # die with SIGSEGV/SIGABRT under memory pressure.)
            return jax.make_array_from_callback(
                arr.shape, s, lambda idx: jnp.array(arr[idx])
            )
        return jax.make_array_from_callback(
            arr.shape, s, lambda idx: arr[idx]
        )

    return place


#: (shape, dtype) pairs whose CPU staging conversion is already
#: compiled in this process (the jnp.array jit cache is keyed the same
#: way and shared across meshes/world sizes).
_warmed_leaf_conversions: set = set()


def warm_leaf_conversions(abstract_leaves) -> int:
    """Pre-compile the tiny ``jnp.array`` staging programs the CPU
    branch of ``leaf_placer`` dispatches — one per distinct leaf
    shape/dtype — so a trainer's FIRST restore doesn't pay them inside
    the resize window (they are mesh-independent, so one pass covers
    every world size).  No-op off the CPU backend, where ``device_put``
    stages via DMA and never compiles.  Returns how many conversions
    were warmed (transient host allocation of one leaf at a time; the
    staged device arrays are dropped immediately)."""
    if jax.default_backend() != "cpu":
        return 0
    warmed = 0
    for l in abstract_leaves:
        key = (tuple(l.shape), np.dtype(l.dtype).str)
        if key in _warmed_leaf_conversions:
            continue
        jnp.array(np.zeros(l.shape, l.dtype))
        # Memoized only on success — and invalidated wholesale when
        # the launcher clears backends (multi-pod world teardown
        # drops the compiled executables this set claims exist).
        _warmed_leaf_conversions.add(key)
        warmed += 1
    return warmed


def reset_leaf_conversion_warmth() -> None:
    """Forget which staging conversions are compiled.  Must accompany
    ``jax.extend.backend.clear_backends()`` (the launcher's world
    teardown): the executables die with the backend, and a stale memo
    would silently push those compiles back inside the next resize
    window's restore phase."""
    _warmed_leaf_conversions.clear()


def _cover_regions(l) -> Optional[List[Any]]:
    """Unique addressable-shard regions of ``l`` when they cover the
    FULL array; None when local shards leave gaps (truly cross-process
    sharded state).

    The case this unlocks: params sharded only over intra-pod mesh axes
    (tp/fsdp within a multi-chip pod) and replicated over the cross-pod
    dp axis — not ``fully_addressable``, yet every index is present
    locally, so a host-side assembly needs NO collectives.  That is
    what lets a graceful resize flush model-sharded state even when a
    peer pod is already gone (VERDICT r4 weak-3)."""
    regions: Dict[tuple, Any] = {}
    for sh in l.addressable_shards:
        key = []
        for s, dim in zip(sh.index, l.shape):
            if not isinstance(s, slice) or (s.step not in (None, 1)):
                return None
            key.append((s.start or 0, dim if s.stop is None else s.stop))
        regions.setdefault(tuple(key), sh)
    covered = 0
    for key in regions:
        vol = 1
        for lo, hi in key:
            vol *= hi - lo
        covered += vol
    if covered != l.size:
        return None
    return list(regions.items())


class _ShardAssembly:
    """Deferred host-side assembly of a leaf from owned per-shard device
    copies (regions from ``_cover_regions``).  The device copies are
    donation-safe snapshots; ``assemble`` runs on the checkpoint
    store's background thread."""

    def __init__(self, shape, dtype, parts):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.parts = parts  # [(region_key, owned device array)]

    def assemble(self) -> np.ndarray:
        out = np.empty(self.shape, self.dtype)
        for key, data in self.parts:
            out[tuple(slice(lo, hi) for lo, hi in key)] = np.asarray(
                jax.device_get(data)
            )
        return out


@dataclass
class HostCheckpoint:
    """One materialized checkpoint: host numpy leaves + tree structure."""

    step: int
    generation: int
    leaves: List[np.ndarray]
    treedef: Any
    created_at: float = field(default_factory=lambda: 0.0)
    save_seconds: float = 0.0

    def unflatten(self):
        return jax.tree_util.tree_unflatten(self.treedef, self.leaves)

    def nbytes(self) -> int:
        return sum(x.nbytes for x in self.leaves)

    def _leaf_crcs(self) -> List[int]:
        """Fresh per-leaf crc32 pass (no cache)."""
        import zlib

        return [
            zlib.crc32(
                np.ascontiguousarray(leaf).reshape(-1).view(np.uint8)
            )
            for leaf in self.leaves
        ]

    def _crc(self) -> int:
        """Fresh whole-checkpoint fingerprint (no cache)."""
        return _pack_leaf_digests(self._leaf_crcs())

    def leaf_digests(self) -> List[int]:
        """Per-leaf crc32 fingerprints, cached.

        The currency of the delta-aware restore agreement
        (``checkpoint/transfer.py``): members all-gather these so a
        joiner receives ONLY the leaves whose bytes it lacks, and a
        receiver can verify each transferred leaf against the source's
        advertised digest.  One host memory pass on first call.

        Thread-safe: the resize window now fingerprints checkpoints
        concurrently (the flush's background hash/spill thread vs the
        restore agreement on the resize thread) — the lock makes one
        pass compute and the other reuse, instead of both paying the
        full memory pass."""
        with self._hash_lock:
            if self._leaf_digests is None:
                self._leaf_digests = self._leaf_crcs()
            return self._leaf_digests

    def digest(self) -> int:
        """Content fingerprint (crc32 over the per-leaf crc vector),
        cached.

        Lets multi-pod members agree that they hold the *identical*
        checkpoint — same step AND same bytes — so a graceful resize can
        skip moving any state (joiner-only restore).  One host memory
        pass on first call (shared with ``leaf_digests``); O(1) after."""
        with self._hash_lock:
            if self._digest is None:
                self._digest = _pack_leaf_digests(self.leaf_digests())
            return self._digest

    def verify(self) -> bool:
        """Whether the leaves still hash to the digest recorded when it
        was first computed (at save/adoption time) — the restore-side
        check that turns silent corruption into a detected, recoverable
        fault.  Full memory pass; runs only on the (rare) restore path.
        With no recorded digest there is nothing to check against:
        record one now and report clean."""
        with self._hash_lock:
            if self._digest is None:
                self.digest()
                return True
            fresh = self._leaf_crcs()
            if _pack_leaf_digests(fresh) != self._digest:
                return False
            self._leaf_digests = fresh
            return True

    def adopt_digests(self, leaf_digests: List[int]) -> None:
        """Install externally verified per-leaf digests (the streaming
        restore transfer chunk-CRC-verified every received leaf and
        digest-matched every skipped one against the source's
        advertisement, so no re-hash pass is needed — the zero-copy
        adoption half of the transfer engine)."""
        with self._hash_lock:
            self._leaf_digests = [int(d) for d in leaf_digests]
            self._digest = _pack_leaf_digests(self._leaf_digests)

    def shard_digests(self, layout) -> List[int]:
        """Per-SHARD crc32 vector under ``layout`` (a
        ``checkpoint.fabric.ShardLayout``), cached by the layout's
        world-independent boundary key — the refinement of
        ``leaf_digests`` the peer-to-peer fabric trades in its
        agreement.  The single memory pass also fills the per-leaf
        vector (the leaf crc is the chain of its shards' regions), so
        flush stage B hashing once serves BOTH granularities."""
        key = layout.key()
        with self._hash_lock:
            cached = self._shard_digests
            if cached is not None and cached[0] == key:
                return cached[1]
            from edl_tpu.checkpoint.fabric import compute_shard_digests

            shard_crcs, leaf_crcs = compute_shard_digests(
                self.leaves, layout
            )
            self._shard_digests = (key, shard_crcs)
            if self._leaf_digests is None:
                self._leaf_digests = leaf_crcs
                if self._digest is None:
                    self._digest = _pack_leaf_digests(leaf_crcs)
            return shard_crcs

    _digest: Optional[int] = field(default=None, repr=False, compare=False)
    _leaf_digests: Optional[List[int]] = field(
        default=None, repr=False, compare=False
    )
    #: (layout boundary key, per-shard crc vector) — one layout cached
    #: (the fabric uses one shard granularity per deployment)
    _shard_digests: Optional[tuple] = field(
        default=None, repr=False, compare=False
    )
    #: serializes fingerprint computation across threads (reentrant:
    #: digest() computes via leaf_digests() under the same lock)
    _hash_lock: Any = field(
        default_factory=threading.RLock, repr=False, compare=False
    )


class HostDRAMStore:
    """Always-warm checkpoint store in host DRAM.

    Thread model: each ``save_async`` runs on its own daemon thread (the
    device->host DMA is issued on the caller thread, so saves never
    block the step loop).  Saves of a step already stored or in flight
    are deduped — e.g. an interval save and a resize flush landing on
    the same step — and disk spills use unique tmp names with an atomic
    rename, so concurrent saves can never corrupt or race each other.
    """

    def __init__(
        self,
        keep: int = 2,
        spill_dir: Optional[str] = None,
        chaos=None,
        shard_only: bool = False,
    ):
        """``chaos``: optional ``edl_tpu.chaos.FaultSchedule``; when set
        the save worker and the spill path consult their named
        injection points (``checkpoint.save_thread``,
        ``checkpoint.spill``).  None in production — one branch per
        save, no other cost.

        ``shard_only``: cluster-memory residency (EDL_SHARD_ONLY).  Once
        ``bind_fabric`` supplies the fabric topology, this member keeps
        only its own GSPMD slice plus its K ring-buddy shards resident
        (in the fabric's ``ShardReplicaStore``) instead of full
        checkpoints: flushes trim to shards after stage B, spills write
        only owned shards, and cold starts seed the resident store from
        the shard-spill union — host DRAM per member is (1+K)/world of
        state, so aggregate cluster memory, not one host, caps model
        size.  Until bound it behaves exactly like the full store
        (single-process/test runs never lose the fast path)."""
        self.keep = keep
        self.spill_dir = spill_dir
        self.chaos = chaos
        self.shard_only = bool(shard_only)
        #: fabric topology (rank/world/k/shard_bytes) + the resident
        #: ShardReplicaStore — set by bind_fabric(); rebound on every
        #: resize (boundaries are world-independent, ownership is not)
        self._fab: Optional[dict] = None
        self._resident = None
        # Default-on telemetry (edl_tpu.telemetry): saves/flushes land
        # in the metrics registry and the flight recorder.  The journal
        # entry is written on the CALLER thread at submission so a
        # seeded soak's event stream stays deterministic regardless of
        # how save worker threads interleave.
        from edl_tpu import telemetry

        self.recorder = telemetry.get_recorder()
        reg = telemetry.get_registry()
        self._m_saves = reg.counter("edl_checkpoint_saves_total")
        self._m_save_bytes = reg.counter("edl_checkpoint_bytes_total")
        self._m_save_seconds = reg.histogram("edl_checkpoint_save_seconds")
        self._lock = threading.Lock()
        self._checkpoints: Dict[int, HostCheckpoint] = {}  # step -> ckpt
        self._pending: List[threading.Thread] = []
        self._inflight_steps: set = set()
        #: (save_id, error): tagging errors with the save that raised
        #: them lets wait() discard errors from ABANDONED saves — a
        #: leaked dead-world save thread failing long after the world
        #: was buried must not spuriously degrade the NEXT graceful
        #: resize to the replay path (ADVICE r5).
        self._save_errors: List[tuple] = []
        self._save_seq = 0
        self._abandoned_saves: set = set()
        self._tmp_counter = 0

    # -- save ---------------------------------------------------------------
    def _snapshot_leaves(self, leaves: List[Any]) -> List[Any]:
        """Device-side snapshot of ``leaves`` with the d2h DMA issued.

        The step loop donates its state buffers into the next step
        (``Trainer`` uses donate_argnums to keep HBM footprint flat), so
        the original leaves may be invalidated while the host copy is
        still in flight.  jnp.copy dispatches asynchronously; the
        snapshot buffers are owned here and immune to donation.

        Leaves spanning processes (multi-pod world) can't be fetched
        by device_get unless fully replicated; replicate them with an
        XLA allgather first.  That is a collective: every member of
        the world must dispatch the same saves in the same order —
        which holds, because interval saves fire at identical steps
        on every member and resize flushes run once per generation on
        every old-world member."""
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        def snapshot(l):
            if not isinstance(l, jax.Array):
                return l
            if not l.is_fully_addressable:
                if l.is_fully_replicated:
                    # Owned copy under the leaf's own sharding: returning
                    # ``l`` itself races the step loop, which donates the
                    # buffer into the next step while the background
                    # device_get is still in flight (the copy is a fresh
                    # buffer XLA cannot alias — no donation was declared).
                    return jax.jit(lambda a: a, out_shardings=l.sharding)(l)
                regions = _cover_regions(l)
                if regions is not None:
                    # Local shards cover every index (sharded only over
                    # intra-pod axes): owned per-shard copies, assembled
                    # host-side later — NO collective.
                    return _ShardAssembly(
                        l.shape,
                        l.dtype,
                        [(key, jnp.copy(sh.data)) for key, sh in regions],
                    )
                # Truly cross-process sharded: replicate via an XLA
                # allgather.  A collective — every member of the world
                # must dispatch this save in the same order (interval
                # saves at identical steps; resize flushes gated on
                # every old-world member being alive, elastic._can_flush).
                mesh = l.sharding.mesh
                return jax.jit(
                    lambda a: a,
                    out_shardings=NamedSharding(mesh, PartitionSpec()),
                )(l)
            return jnp.copy(l)

        leaves = [snapshot(l) for l in leaves]
        for leaf in leaves:
            if isinstance(leaf, _ShardAssembly):
                for _, data in leaf.parts:
                    try:
                        data.copy_to_host_async()
                    except Exception:
                        pass
            elif isinstance(leaf, jax.Array):
                try:
                    leaf.copy_to_host_async()
                except Exception:  # non-addressable or already host
                    pass
        return leaves

    @staticmethod
    def _materialize(leaves: List[Any]) -> List[np.ndarray]:
        """Complete the device->host copies into owned numpy arrays."""
        return [
            l.assemble()
            if isinstance(l, _ShardAssembly)
            else np.asarray(jax.device_get(l))
            for l in leaves
        ]

    @staticmethod
    def _materialize_inline(leaves: List[Any]) -> List[np.ndarray]:
        """Flush-path materialization: d2h straight from the LIVE
        buffers, no owned device copies.

        The interval-save path snapshots with ``jnp.copy`` because the
        step loop keeps running and donates the state buffers into the
        next step while the background device_get is in flight.  Inside
        the resize barrier no further step can dispatch until restore
        replaces the state, so the live buffers cannot be donated out
        from under a synchronous read — which also means the flush
        never compiles the per-(shape, sharding) snapshot-copy jits
        inside the window (a cold world size's first flush used to pay
        one XLA compile per leaf right in the ordered phase)."""
        from jax.sharding import NamedSharding, PartitionSpec

        # Issue every d2h DMA first, then collect: transfers overlap.
        staged: List[Any] = []
        for l in leaves:
            if isinstance(l, jax.Array) and not (
                l.is_fully_addressable or l.is_fully_replicated
            ):
                regions = _cover_regions(l)
                if regions is not None:
                    staged.append(
                        _ShardAssembly(
                            l.shape,
                            l.dtype,
                            [(key, sh.data) for key, sh in regions],
                        )
                    )
                    continue
                # Truly cross-process sharded: replicate via an XLA
                # allgather (a collective — same ordering contract as
                # the save path's, see _snapshot_leaves).
                staged.append(
                    jax.jit(
                        lambda a: a,
                        out_shardings=NamedSharding(
                            l.sharding.mesh, PartitionSpec()
                        ),
                    )(l)
                )
                continue
            staged.append(l)
        for l in staged:
            if isinstance(l, _ShardAssembly):
                for _, data in l.parts:
                    try:
                        data.copy_to_host_async()
                    except Exception:
                        pass
            elif isinstance(l, jax.Array):
                try:
                    l.copy_to_host_async()
                except Exception:
                    pass
        return HostDRAMStore._materialize(staged)

    def _publish(self, ckpt: HostCheckpoint) -> None:
        """Install a materialized checkpoint and prune to ``keep``."""
        with self._lock:
            self._checkpoints[ckpt.step] = ckpt
            extra = sorted(self._checkpoints)[: -self.keep]
            for s in extra:
                del self._checkpoints[s]

    # -- shard-only residency (cluster-memory checkpoints) -------------------
    def bind_fabric(self, rank: int, world: int, *, k: int, shard_bytes: int, resident) -> None:
        """Bind the fabric topology that defines WHICH shard ranges
        this member keeps resident.  ``resident`` is the fabric's
        ``ShardReplicaStore`` — the SAME one the member's FabricServer
        serves pulls from, so trimming a full checkpoint down to
        resident shards keeps this member a first-class fabric source
        (peers, joiners, and the serving swap poll all read it through
        the one lookup path)."""
        self._fab = {
            "rank": int(rank),
            "world": int(world),
            "k": int(k),
            "shard_bytes": int(shard_bytes),
        }
        self._resident = resident

    def shard_only_active(self) -> bool:
        return (
            self.shard_only
            and self._fab is not None
            and self._resident is not None
        )

    def resident_nbytes(self) -> int:
        """Bytes held in the shard-resident store — the number the
        (1+K)/world memory contract bounds."""
        return int(self._resident.nbytes()) if self._resident is not None else 0

    def _fab_layout(self, leaves):
        """The bound deployment's shard table over ``leaves`` (abstract
        or materialized — only shapes/nbytes are read)."""
        from edl_tpu.checkpoint.fabric import (
            ShardLayout,
            leaf_nbytes,
            leaf_rows,
        )

        fab = self._fab
        return ShardLayout.build(
            [leaf_nbytes(l) for l in leaves],
            max(1, fab["world"]),
            k=fab["k"],
            shard_bytes=fab["shard_bytes"],
            rows=leaf_rows(leaves),
        )

    def trim_to_shards(self, step: int) -> int:
        """Drop a full checkpoint down to this member's resident shard
        ranges (own GSPMD slice + K ring-buddy shards) and evict the
        full copy from the store.  The shard copies are real (not
        views), so the full leaves free as soon as in-flight references
        drop — a restore window holding the returned flush checkpoint
        keeps it alive exactly as long as it is used.  Returns bytes
        adopted (0 when not shard-only bound or the step is absent).
        Every member of a collective flush self-adopts its OWN wanted
        ranges from its transient full copy, so K-replication of a
        healthy flush costs zero wire — the buddy offer round then
        declines everything."""
        if not self.shard_only_active():
            return 0
        with self._lock:
            ckpt = self._checkpoints.get(step)
        if ckpt is None:
            return 0
        from edl_tpu.checkpoint.fabric import adopt_resident

        layout = self._fab_layout(ckpt.leaves)
        crcs = None
        cached = ckpt._shard_digests
        if cached is not None and cached[0] == layout.key():
            crcs = cached[1]
        adopted = adopt_resident(
            self._resident,
            ckpt.leaves,
            layout,
            self._fab["rank"],
            int(step),
            crcs=crcs,
        )
        with self._lock:
            if self._checkpoints.get(step) is ckpt:
                del self._checkpoints[step]
        from edl_tpu import telemetry

        telemetry.get_registry().gauge("edl_fabric_resident_bytes").set(
            self._resident.nbytes()
        )
        return adopted

    def load_shards_from_disk(self, template_state) -> Optional[dict]:
        """Shard-only cold start: seed the RESIDENT store with this
        member's wanted shard ranges from the newest fully-covered
        shard-spill set — no process materializes full state.  Returns
        ``{step, generation, bytes, shards}`` or None when the durable
        dir holds no complete shard set.  The member then enters the
        fabric agreement as a replica-only holder; the restore engine
        assembles device slices from resident shards."""
        if not self.spill_dir or not self.shard_only_active():
            return None
        found = newest_covered_shard_step(self.spill_dir)
        if found is None:
            return None
        step, mans = found
        leaves_abs, _ = jax.tree_util.tree_flatten(template_state)
        layout = self._fab_layout(leaves_abs)
        any_man = next(iter(mans.values()))[1]
        from edl_tpu.checkpoint.fabric import leaf_nbytes

        if int(any_man.get("n_shards", -1)) != len(layout.shards) or [
            int(b) for b in any_man.get("leaf_nbytes", ())
        ] != [leaf_nbytes(l) for l in leaves_abs]:
            raise RuntimeError(
                f"durable shard spills in {self.spill_dir} do not match "
                "the model's leaf schema (different model or shard "
                "granularity?); refusing to silently restart from step 0"
            )
        want = set(layout.wanted(self._fab["rank"]))
        blobs, crcs = load_shard_spill_bytes(self.spill_dir, mans, want=want)
        adopted = 0
        for i, arr in blobs.items():
            s = layout.shards[i]
            if self._resident.put(
                int(step), s.leaf, s.offset, s.length, arr, crcs[i]
            ):
                adopted += int(arr.nbytes)
        return {
            "step": int(step),
            "generation": int(any_man.get("generation", 0)),
            "bytes": adopted,
            "shards": len(blobs),
        }

    def save_async(self, state, generation: int = 0) -> threading.Thread:
        """Snapshot ``state`` (a pytree of jax Arrays) into host DRAM.

        Returns the worker thread (join it, or call ``wait()``, to
        ensure completion).  The device buffers are captured by
        reference and DMA'd; the step loop may immediately donate/mutate
        its own handle because XLA arrays are immutable."""
        t0 = time.perf_counter()
        leaves, treedef = jax.tree_util.tree_flatten(state)
        step_val = _extract_step(state)

        with self._lock:
            if step_val in self._checkpoints or step_val in self._inflight_steps:
                th = threading.Thread(target=lambda: None, daemon=True)
                th.start()
                return th
            self._inflight_steps.add(step_val)
            self._save_seq += 1
            save_id = self._save_seq

        leaves = self._snapshot_leaves(leaves)
        # Journal at submission (caller thread) so the event order is
        # deterministic; duration/bytes land in the metrics instead.
        self.recorder.record(
            "checkpoint.save",
            {"step": step_val, "kind": "async"},
            step=step_val,
            generation=generation,
        )

        def work():
            try:
                if self.chaos is not None:
                    # chaos[checkpoint.save_thread]: the async save
                    # worker dies (OOM-kill, host fault) — lands in
                    # _save_errors; the next wait() must surface it and
                    # the resize path must degrade to replay.
                    self.chaos.maybe_raise("checkpoint.save_thread")
                ckpt = HostCheckpoint(
                    step=step_val,
                    generation=generation,
                    leaves=self._materialize(leaves),
                    treedef=treedef,
                    created_at=time.time(),
                    save_seconds=time.perf_counter() - t0,
                )
                # Fingerprint here, on the background thread: the
                # multi-pod resize agreement reads digest() inside its
                # all-gather, and a full-DRAM crc pass there would sit
                # on the <60s critical path the digest exists to cut.
                # Shard-first ordering, same as flush_sync's finish():
                # one memory pass serves both granularities — digest()
                # first would make _spill's shard_digests a second
                # full pass.  Gated on the spill actually consuming
                # the shard vector: without a spill_dir nothing reads
                # it, and the prewarm costs an extra crc over every
                # region.
                if self.spill_dir:
                    try:
                        ckpt.shard_digests(_spill_shard_layout(ckpt))
                    except Exception:  # pragma: no cover - defensive
                        pass
                ckpt.digest()
                self._publish(ckpt)
                self._m_saves.inc(kind="async")
                self._m_save_bytes.inc(ckpt.nbytes(), kind="async")
                self._m_save_seconds.observe(
                    ckpt.save_seconds, kind="async"
                )
                if self.spill_dir:
                    self._spill(ckpt)
                if self.shard_only_active():
                    # Interval saves honor the memory contract too: a
                    # collective save lands the same step on EVERY
                    # member, so each self-adopting its wanted ranges
                    # K-covers the ring with zero wire — then the full
                    # copy drops.
                    self.trim_to_shards(ckpt.step)
            except BaseException as e:  # pragma: no cover - defensive
                with self._lock:
                    self._save_errors.append((save_id, e))
            finally:
                with self._lock:
                    self._inflight_steps.discard(step_val)

        th = threading.Thread(target=work, daemon=True, name=f"ckpt-save-{step_val}")
        th.edl_save_id = save_id
        self._track(th)
        th.start()
        return th

    def _track(self, th: threading.Thread) -> None:
        with self._lock:
            # Prune finished workers so a long run between wait() calls
            # doesn't retain one Thread object per interval save.  A
            # thread with ident None was created but not yet started
            # (the append below races th.start()) — keep it.
            self._pending = [
                p for p in self._pending if p.ident is None or p.is_alive()
            ]
            self._pending.append(th)

    def flush_sync(self, state, generation: int = 0, on_background=None):
        """The resize-window flush: device->host ORDERED, fingerprint +
        spill OVERLAPPED.

        ``on_background(ckpt)``: optional stage-B hook invoked on the
        background thread after fingerprint + spill — the checkpoint
        fabric hangs shard-digest prewarming and buddy replication
        here (never in the resize window; the hook must spawn its own
        thread for anything long-running, because the caller joins
        this background thread before the resize returns).  Hook
        errors are printed, never recorded on ``edl_error``: a failed
        replication must not read as a failed flush.

        Returns ``(ckpt, background_thread_or_None)``.  Only the
        device-to-host materialization runs on the caller thread —
        that part alone must precede world teardown (the device buffers
        die with the old process group).  The crc fingerprint and the
        durable-dir spill move to a background thread that overlaps
        world formation / compile / restore; the caller joins it before
        the resize returns (``elastic._resize``), so the graceful
        guarantee — flushed state durable and fingerprinted before the
        next step runs — is unchanged, it just stops serializing the
        resize window.  A background failure is recorded on the
        returned thread (``edl_error``), NOT in ``_save_errors``: the
        caller joins and handles it, and a handled error lingering in
        the store would spuriously degrade a LATER unrelated resize to
        the replay path (the ADVICE r5 class of bug).

        Dedup mirrors ``save_async``: a step already stored returns its
        checkpoint with no work; a save of the same step in flight is
        waited out (its d2h must land before teardown either way)."""
        t0 = time.perf_counter()
        step_val = _extract_step(state)
        for _ in range(2):
            with self._lock:
                ckpt = self._checkpoints.get(step_val)
                inflight = step_val in self._inflight_steps
            if ckpt is not None:
                return ckpt, None
            if not inflight:
                break
            # An interval save of this very step is mid-materialization:
            # join it (wait() re-raises its errors exactly like the old
            # monolithic flush did) and re-check; if it errored, fall
            # through to a fresh flush attempt.
            self.wait()
        leaves, treedef = jax.tree_util.tree_flatten(state)
        with self._lock:
            self._inflight_steps.add(step_val)
            self._save_seq += 1
            save_id = self._save_seq
        try:
            if self.chaos is not None:
                # chaos[checkpoint.save_thread]: the thread doing the
                # host materialization dies mid-flush (same fault the
                # async worker path injects) — raises synchronously
                # here, and the resize degrades to interval-checkpoint
                # + replay.
                self.chaos.maybe_raise("checkpoint.save_thread")
            # Stage A (ordered before teardown): host copy straight off
            # the live buffers — the resize barrier guarantees no step
            # can donate them mid-read (see _materialize_inline).
            host_leaves = self._materialize_inline(leaves)
        except BaseException:
            with self._lock:
                self._inflight_steps.discard(step_val)
            raise
        ckpt = HostCheckpoint(
            step=step_val,
            generation=generation,
            leaves=host_leaves,
            treedef=treedef,
            created_at=time.time(),
            save_seconds=time.perf_counter() - t0,
        )
        self._publish(ckpt)
        self._m_saves.inc(kind="flush")
        self._m_save_bytes.inc(ckpt.nbytes(), kind="flush")
        self._m_save_seconds.observe(ckpt.save_seconds, kind="flush")
        self.recorder.record(
            "checkpoint.save",
            {"step": step_val, "kind": "flush"},
            step=step_val,
            generation=generation,
        )

        def finish():
            t1 = time.perf_counter()
            try:
                if self.chaos is not None:
                    # chaos[flush.spill.slow]: the background hash/spill
                    # thread stalls (cold page cache, contended durable
                    # volume) — the resize must overlap it, and its join
                    # at the end of the window must stay bounded.
                    for ev in self.chaos.due("flush.spill.slow"):
                        time.sleep(float(ev.arg or 0.05))
                if self.spill_dir or on_background is not None:
                    # One memory pass serves BOTH granularities: the
                    # shard pass fills the leaf vector and the
                    # whole-checkpoint digest as it goes, making the
                    # digest() below (and _spill's shard_digests) cache
                    # hits — ordering digest() first would pay a second
                    # full pass for the shard crcs.  Gated on an actual
                    # consumer (spill manifest or the fabric's stage-B
                    # hook): otherwise the shard crcs cost an extra
                    # hash over every region for nobody.
                    try:
                        ckpt.shard_digests(_spill_shard_layout(ckpt))
                    except Exception:  # pragma: no cover - defensive
                        pass
                ckpt.digest()
                if self.spill_dir:
                    self._spill(ckpt)
            except BaseException as e:
                th.edl_error = e
            finally:
                if on_background is not None:
                    try:
                        on_background(ckpt)
                    except Exception:
                        import traceback

                        traceback.print_exc()
                if self.shard_only_active():
                    # Trim AFTER stage B: the fabric hook joins its
                    # buddy replication in shard-only mode, so the full
                    # copy is never dropped before K buddies ack (an
                    # under-replicated flush keeps its resident shards
                    # either way — the journal + counter make the K gap
                    # loud instead of silent).  The resize window's
                    # reference to the returned checkpoint keeps the
                    # leaves alive exactly as long as the restore uses
                    # them.
                    try:
                        self.trim_to_shards(ckpt.step)
                    except Exception:  # pragma: no cover - defensive
                        import traceback

                        traceback.print_exc()
                th.edl_seconds = time.perf_counter() - t1
                with self._lock:
                    self._inflight_steps.discard(step_val)

        th = threading.Thread(
            target=finish, daemon=True, name=f"ckpt-flush-{step_val}"
        )
        th.edl_save_id = save_id
        th.edl_error = None
        th.edl_seconds = 0.0
        self._track(th)
        th.start()
        return ckpt, th

    def wait(self, timeout: Optional[float] = None):
        """Block until all in-flight saves have landed; re-raise errors.

        ``timeout``: optional TOTAL seconds to wait across all pending
        saves.  On expiry the still-running threads are re-tracked (a
        later wait can finish the join) and MARKED ABANDONED: the
        broken-world path uses the timeout so a save blocked on a dead
        peer's collective cannot hang recovery — it proceeds and leaks
        the thread — and whenever that leaked thread finally dies, its
        error is tagged with a save id already in the abandoned set and
        silently discarded here.  Without the tag, the stale error
        would linger until the NEXT healthy flush's wait() re-raised it
        and spuriously degraded an unrelated graceful resize to the
        replay path (ADVICE r5)."""
        with self._lock:
            pending = list(self._pending)
            self._pending.clear()
        deadline = None if timeout is None else time.monotonic() + timeout
        still_alive = []
        for th in pending:
            if deadline is None:
                th.join()
            else:
                th.join(max(0.0, deadline - time.monotonic()))
                if th.is_alive():
                    still_alive.append(th)
        with self._lock:
            if still_alive:
                self._pending.extend(still_alive)
                for th in still_alive:
                    sid = getattr(th, "edl_save_id", None)
                    if sid is not None:
                        self._abandoned_saves.add(sid)
            live = [
                (sid, e)
                for sid, e in self._save_errors
                if sid not in self._abandoned_saves
            ]
            self._save_errors.clear()
            if live:
                raise RuntimeError("async checkpoint save failed") from live[0][1]

    def put(self, ckpt: HostCheckpoint) -> None:
        """Adopt an externally produced checkpoint (e.g. one received by
        broadcast when joining a multi-pod world)."""
        # Fingerprint now (we are already on the slow broadcast path)
        # so the NEXT resize's agreement round reads a cached digest.
        ckpt.digest()
        with self._lock:
            self._checkpoints[ckpt.step] = ckpt
            extra = sorted(self._checkpoints)[: -self.keep]
            for s in extra:
                del self._checkpoints[s]

    # -- query --------------------------------------------------------------
    def latest(self) -> Optional[HostCheckpoint]:
        with self._lock:
            if not self._checkpoints:
                return None
            return self._checkpoints[max(self._checkpoints)]

    def get(self, step: int) -> Optional[HostCheckpoint]:
        with self._lock:
            return self._checkpoints.get(step)

    def latest_verified(self) -> Optional[HostCheckpoint]:
        """Newest checkpoint whose bytes still match the digest
        recorded at save time; a corrupted snapshot is dropped (with a
        stderr note) and the next-oldest is tried.  The restore paths
        use this instead of ``latest()`` so silent DRAM/storage
        corruption becomes a detected fault with a bounded cost (one
        extra replay interval), not a poisoned training run.  The crc
        pass per candidate runs only on the rare restore path."""
        import sys

        while True:
            with self._lock:
                if not self._checkpoints:
                    return None
                step = max(self._checkpoints)
                ckpt = self._checkpoints[step]
            if ckpt.verify():
                return ckpt
            print(
                f"[edl] checkpoint step {step} failed CRC verification "
                "(corrupted in store); discarding and falling back to "
                "the next-oldest snapshot",
                file=sys.stderr,
            )
            with self._lock:
                if self._checkpoints.get(step) is ckpt:
                    del self._checkpoints[step]

    def steps(self) -> List[int]:
        with self._lock:
            return sorted(self._checkpoints)

    # -- restore ------------------------------------------------------------
    def restore(
        self,
        ckpt: HostCheckpoint,
        mesh: Mesh,
        sharding_tree: Any = None,
    ):
        """Place a checkpoint onto ``mesh``.

        ``sharding_tree``: a pytree of NamedSharding (or a single one)
        congruent with the state; default replicates everything — the
        correct layout for pure-DP TrainState.  This is the re-sharding
        moment: the checkpoint may have been written from any previous
        mesh."""
        state_host = ckpt.unflatten()
        if sharding_tree is None:
            sharding_tree = NamedSharding(mesh, P())

        # A mesh spanning multiple processes has devices this process
        # cannot address; device_put can't target those, so build each
        # global array from the local shards only (every process holds
        # the full host value — make_array_from_callback slices it).
        place = leaf_placer(mesh)

        if isinstance(sharding_tree, (NamedSharding,)):
            single = sharding_tree
            return jax.tree_util.tree_map(lambda x: place(x, single), state_host)
        return jax.tree_util.tree_map(place, state_host, sharding_tree)

    # -- disk spill (durability; not on the resize fast path) ---------------
    def _spill(self, ckpt: HostCheckpoint):
        if self.shard_only_active():
            # Cluster-memory durability: this rank writes ONLY its
            # owned shards; the union across ranks is the durable full
            # state, so spill I/O per member is 1/world of state
            # instead of world identical full copies.
            return self._spill_shards(ckpt)
        if self.chaos is not None:
            # chaos[checkpoint.spill]: durable-volume I/O error (full
            # disk, detached PD) — surfaces through _save_errors while
            # the DRAM copy stays warm and restorable.
            self.chaos.maybe_raise("checkpoint.spill", OSError)
        os.makedirs(self.spill_dir, exist_ok=True)
        with self._lock:
            self._tmp_counter += 1
            tag = f"{os.getpid()}-{self._tmp_counter}"
        path = os.path.join(self.spill_dir, f"ckpt-{ckpt.step:012d}")
        arrays = {f"leaf_{i}": a for i, a in enumerate(ckpt.leaves)}
        tmp_npz = f"{path}.{tag}.tmp.npz"
        np.savez(tmp_npz, **arrays)
        os.replace(tmp_npz, path + ".npz")
        manifest = {
            "step": ckpt.step,
            "generation": ckpt.generation,
            "created_at": ckpt.created_at,
            "n_leaves": len(ckpt.leaves),
            # Content fingerprints (already cached by the save worker):
            # load_from_disk re-hashes the loaded bytes against the
            # digest so a torn/bit-rotted spill is detected, not
            # restored; the per-leaf vector re-seeds the delta-restore
            # agreement cache so a cold start pays no extra hash pass.
            # digest_v 2 = crc32 over the leaf-digest vector; absent =
            # the pre-delta chained-crc algorithm (load_from_disk
            # verifies those with the legacy formula rather than
            # classifying every old spill as corrupt).
            "digest": ckpt.digest(),
            "digest_v": 2,
            "leaf_digests": ckpt.leaf_digests(),
        }
        # Per-SHARD digests (checkpoint fabric granularity) ride the
        # manifest too: shard boundaries are world-independent, so a
        # cold start can re-seed the fabric agreement's shard vector —
        # and a torn spill localizes to a shard, not a whole leaf.
        try:
            layout = _spill_shard_layout(ckpt)
            manifest["shard_bytes"] = layout.shard_bytes
            manifest["shard_digests"] = ckpt.shard_digests(layout)
        except Exception:  # pragma: no cover - defensive
            pass
        tmp_json = f"{path}.{tag}.tmp.json"
        with open(tmp_json, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp_json, path + ".json")
        # Bound the durable dir: keep the newest ``keep`` spills (same
        # retention as DRAM).  Best-effort — several pods share the dir
        # and may prune concurrently (identical bytes, atomic renames),
        # so a racing unlink is benign.
        try:
            names = sorted(
                f
                for f in os.listdir(self.spill_dir)
                if f.endswith(".json")
                and ".tmp." not in f
                and ".shard-r" not in f
            )
            for name in names[: -self.keep]:
                base = os.path.join(self.spill_dir, name[: -len(".json")])
                for suffix in (".json", ".npz"):
                    try:
                        os.unlink(base + suffix)
                    except OSError:
                        pass
        except OSError:  # pragma: no cover - listdir race
            pass

    def _spill_shards(self, ckpt: HostCheckpoint) -> None:
        """Shard-only durable spill: ``ckpt-<step>.shard-r<rank>.npz``
        holds this rank's OWNED shard bytes (one ``s_<index>`` uint8
        entry per shard), the manifest records indices, per-shard
        digests, and the full shard-digest vector (a cold start
        re-seeds the fabric agreement without a hash pass).  Writes are
        tmp + atomic rename, same discipline as the full spill."""
        from edl_tpu.checkpoint.fabric import byte_view

        if self.chaos is not None:
            # chaos[checkpoint.spill]: same injection point as the full
            # spill — a durable-volume fault surfaces identically.
            self.chaos.maybe_raise("checkpoint.spill", OSError)
        os.makedirs(self.spill_dir, exist_ok=True)
        fab = dict(self._fab)
        layout = self._fab_layout(ckpt.leaves)
        digs = ckpt.shard_digests(layout)
        owned = layout.owned_by(fab["rank"])
        with self._lock:
            self._tmp_counter += 1
            tag = f"{os.getpid()}-{self._tmp_counter}"
        path = os.path.join(
            self.spill_dir,
            f"ckpt-{ckpt.step:012d}.shard-r{fab['rank']:04d}",
        )
        arrays = {}
        for s in owned:
            view = byte_view(ckpt.leaves[s.leaf])[
                s.offset : s.offset + s.length
            ]
            arrays[f"s_{s.index}"] = np.frombuffer(view, np.uint8)
        tmp_npz = f"{path}.{tag}.tmp.npz"
        np.savez(tmp_npz, **arrays)
        os.replace(tmp_npz, path + ".npz")
        manifest = {
            "shard_only": True,
            "step": ckpt.step,
            "generation": ckpt.generation,
            "created_at": ckpt.created_at,
            "rank": fab["rank"],
            "world": fab["world"],
            "k": fab["k"],
            "shard_bytes": layout.shard_bytes,
            "n_leaves": len(ckpt.leaves),
            "leaf_nbytes": [int(l.nbytes) for l in ckpt.leaves],
            "n_shards": len(layout.shards),
            "indices": [int(s.index) for s in owned],
            "digests": [int(digs[s.index]) for s in owned],
            "shard_digests": [int(d) for d in digs],
        }
        tmp_json = f"{path}.{tag}.tmp.json"
        with open(tmp_json, "w") as f:
            json.dump(manifest, f)
        os.replace(tmp_json, path + ".json")
        # Retention by STEP across the whole shard family (each step
        # has one file pair per rank); best-effort under concurrent
        # pruning peers, like the full spill's.
        try:
            by_step = scan_shard_spills(self.spill_dir)
            for s in sorted(by_step)[: -self.keep]:
                for name in by_step[s].values():
                    base = os.path.join(
                        self.spill_dir, name[: -len(".json")]
                    )
                    for suffix in (".json", ".npz"):
                        try:
                            os.unlink(base + suffix)
                        except OSError:
                            pass
        except OSError:  # pragma: no cover - listdir race
            pass

    def load_from_disk(self, template_state, step: Optional[int] = None) -> HostCheckpoint:
        """Rehydrate a spilled checkpoint.  ``template_state`` supplies
        the treedef (the caller knows the model; leaves are positional)."""
        if not self.spill_dir:
            raise ValueError("store has no spill_dir")
        import sys

        _, treedef = jax.tree_util.tree_flatten(template_state)
        # FileNotFoundError means exactly "nothing spilled" (callers
        # treat it as a fresh job).  A manifest whose .npz is missing is
        # NOT that: it is either a concurrent prune by a peer pod
        # (retry the scan — a newer checkpoint replaced it) or real
        # corruption, which must raise loudly rather than silently
        # restart training at step 0.  A manifest whose bytes load but
        # fail the recorded CRC digest is corruption too: fall back to
        # the next-oldest spill; only when EVERY spill is corrupt (or a
        # specific requested step is) does the load raise.
        corrupt: set = set()
        race_retries = 0
        while True:
            names = sorted(
                f
                for f in os.listdir(self.spill_dir)
                if f.endswith(".json")
                and ".tmp." not in f
                and ".shard-r" not in f
            )
            if step is None:
                intact = [n for n in names if n not in corrupt]
                if not intact:
                    if corrupt:
                        raise RuntimeError(
                            f"all {len(corrupt)} durable checkpoint(s) in "
                            f"{self.spill_dir} failed CRC verification "
                            "(corrupt volume?); refusing to silently "
                            "restart from step 0"
                        )
                    # No full spill — a shard-only deployment's durable
                    # dir holds per-rank shard spills instead: assemble
                    # the union (full-copy consumers of a shard-only
                    # dir, e.g. a non-shard-only member or the serving
                    # engine's compat path).
                    ckpt = self._load_full_from_shard_spills(
                        template_state, treedef
                    )
                    if ckpt is not None:
                        return ckpt
                    raise FileNotFoundError(
                        f"no checkpoints in {self.spill_dir}"
                    )
                name = intact[-1]
            else:
                name = f"ckpt-{step:012d}.json"
                if name not in names:
                    raise FileNotFoundError(f"no checkpoint for step {step}")
            try:
                with open(os.path.join(self.spill_dir, name)) as f:
                    manifest = json.load(f)
                with np.load(
                    os.path.join(self.spill_dir, name[: -len(".json")] + ".npz")
                ) as z:
                    leaves = [
                        z[f"leaf_{i}"] for i in range(manifest["n_leaves"])
                    ]
            except (FileNotFoundError, OSError):
                race_retries += 1
                if race_retries >= 3:
                    raise RuntimeError(
                        f"durable checkpoint {name} in {self.spill_dir} has "
                        "a manifest but unreadable bytes (corrupt volume?); "
                        "refusing to silently restart from step 0"
                    ) from None
                time.sleep(0.2)
                continue
            if treedef.num_leaves != len(leaves):
                raise ValueError(
                    f"template has {treedef.num_leaves} leaves, "
                    f"checkpoint has {len(leaves)}"
                )
            ckpt = HostCheckpoint(
                step=manifest["step"],
                generation=manifest["generation"],
                leaves=leaves,
                treedef=treedef,
                created_at=manifest["created_at"],
            )
            # Older manifests carry no digest: nothing to verify
            # against (verify() then records a fresh one and passes).
            # Manifests from before digest_v 2 recorded a CHAINED
            # crc32 over the raw leaf bytes — verify those with the
            # legacy formula (then cache fresh v2 digests), instead of
            # letting the algorithm change classify every pre-existing
            # durable checkpoint as corrupt on a healthy volume.
            if manifest.get("digest_v") == 2:
                ckpt._digest = manifest.get("digest")
                if manifest.get("leaf_digests") is not None:
                    ckpt._leaf_digests = [
                        int(d) for d in manifest["leaf_digests"]
                    ]
                ok = ckpt.verify()
            elif manifest.get("digest") is not None:
                ok = _legacy_chained_crc(leaves) == manifest["digest"]
                if ok:
                    ckpt.digest()  # cache fresh v2 fingerprints
            else:
                ok = ckpt.verify()  # records a fresh digest, passes
            if ok:
                if manifest.get("shard_digests") is not None:
                    # Re-seed the fabric's per-shard vector from the
                    # manifest so a cold start pays no extra hash pass
                    # before its first shard agreement.
                    try:
                        from edl_tpu.checkpoint.fabric import (
                            ShardLayout,
                            leaf_rows,
                        )

                        layout = ShardLayout.build(
                            [l.nbytes for l in leaves],
                            1,
                            shard_bytes=int(manifest["shard_bytes"]),
                            rows=leaf_rows(leaves),
                        )
                        if len(layout.shards) == len(
                            manifest["shard_digests"]
                        ):
                            ckpt._shard_digests = (
                                layout.key(),
                                [
                                    int(d)
                                    for d in manifest["shard_digests"]
                                ],
                            )
                    except Exception:  # pragma: no cover - defensive
                        pass
                break
            if step is not None:
                raise RuntimeError(
                    f"durable checkpoint {name} in {self.spill_dir} "
                    "failed CRC verification (corrupt volume?)"
                )
            print(
                f"[edl] durable checkpoint {name} failed CRC "
                "verification; falling back to the next-oldest spill",
                file=sys.stderr,
            )
            corrupt.add(name)
        with self._lock:
            self._checkpoints[ckpt.step] = ckpt
        return ckpt

    def _load_full_from_shard_spills(
        self, template_state, treedef
    ) -> Optional[HostCheckpoint]:
        """Assemble a FULL checkpoint from a shard-spill union — the
        compatibility path for consumers that need whole leaves from a
        shard-only durable dir (each shard read is CRC-gated, so a torn
        rank spill fails loudly and localized).  Shard-only members
        never take this path; they seed residency via
        ``load_shards_from_disk`` instead."""
        found = newest_covered_shard_step(self.spill_dir)
        if found is None:
            return None
        step, mans = found
        from edl_tpu.checkpoint.fabric import (
            ShardLayout,
            byte_view,
            leaf_nbytes,
            leaf_rows,
        )

        leaves_abs, _ = jax.tree_util.tree_flatten(template_state)
        any_man = next(iter(mans.values()))[1]
        if int(any_man.get("n_leaves", -1)) != len(leaves_abs) or [
            int(b) for b in any_man.get("leaf_nbytes", ())
        ] != [leaf_nbytes(l) for l in leaves_abs]:
            raise RuntimeError(
                f"durable shard spills in {self.spill_dir} do not match "
                "the template's leaf schema (wrong model?); refusing to "
                "silently restart from step 0"
            )
        layout = ShardLayout.build(
            [leaf_nbytes(l) for l in leaves_abs],
            max(1, int(any_man.get("world", 1))),
            k=int(any_man.get("k", 1)),
            shard_bytes=int(any_man["shard_bytes"]),
            rows=leaf_rows(leaves_abs),
        )
        blobs, _crcs = load_shard_spill_bytes(self.spill_dir, mans)
        leaves = [
            np.empty(tuple(l.shape), np.dtype(l.dtype)) for l in leaves_abs
        ]
        for i, arr in blobs.items():
            s = layout.shards[i]
            byte_view(leaves[s.leaf])[
                s.offset : s.offset + s.length
            ] = memoryview(arr)
        ckpt = HostCheckpoint(
            step=int(step),
            generation=int(any_man.get("generation", 0)),
            leaves=leaves,
            treedef=treedef,
            created_at=float(any_man.get("created_at", 0.0)),
        )
        sd = any_man.get("shard_digests")
        if sd is not None and len(sd) == len(layout.shards):
            ckpt._shard_digests = (layout.key(), [int(d) for d in sd])
        ckpt.digest()
        with self._lock:
            self._checkpoints[ckpt.step] = ckpt
        import sys

        print(
            f"[edl] assembled full checkpoint step {step} from "
            f"{len(mans)} shard spill(s) in {self.spill_dir}",
            file=sys.stderr,
        )
        return ckpt


def _extract_step(state) -> int:
    step = getattr(state, "step", None)
    if step is None:
        return 0
    return int(jax.device_get(step))
