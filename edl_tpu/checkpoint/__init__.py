from edl_tpu.checkpoint.hostdram import HostDRAMStore, HostCheckpoint
from edl_tpu.checkpoint.transfer import (
    TornTransferError,
    TransferError,
    TransferStats,
    stream_restore,
)
from edl_tpu.checkpoint.fabric import (
    FabricServer,
    ShardLayout,
    ShardReplicaStore,
    fabric_restore,
    replicate_to_buddies,
)

__all__ = [
    "HostDRAMStore",
    "HostCheckpoint",
    "TornTransferError",
    "TransferError",
    "TransferStats",
    "stream_restore",
    "FabricServer",
    "ShardLayout",
    "ShardReplicaStore",
    "fabric_restore",
    "replicate_to_buddies",
]
