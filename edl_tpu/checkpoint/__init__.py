from edl_tpu.checkpoint.hostdram import HostDRAMStore, HostCheckpoint

__all__ = ["HostDRAMStore", "HostCheckpoint"]
