from edl_tpu.checkpoint.hostdram import HostDRAMStore, HostCheckpoint
from edl_tpu.checkpoint.transfer import (
    TornTransferError,
    TransferError,
    TransferStats,
    stream_restore,
)

__all__ = [
    "HostDRAMStore",
    "HostCheckpoint",
    "TornTransferError",
    "TransferError",
    "TransferStats",
    "stream_restore",
]
