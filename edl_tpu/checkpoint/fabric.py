"""Sharded peer-to-peer checkpoint fabric — restore at world bandwidth.

PR 2's streaming delta transfer (``checkpoint/transfer.py``) retired
the monolithic broadcast, but its topology is still a star: ONE source
process fans every receiver's missing leaves out of one NIC, so a
joiner's restore time scales with state_size / single-NIC bandwidth.
Gemini (SOSP'23, PAPERS.md) argues checkpoints should live replicated
in cluster host memory with recovery traffic moving peer-to-peer in
parallel; GSPMD already gives us the shard map for free — each member
holds exactly its slice of every sharded leaf.  This module is that
fabric:

1. **Shards, not leaves.**  ``ShardLayout`` cuts every leaf into
   contiguous byte-range shards, row-aligned to the leaf's leading
   (GSPMD-partitioned) axis.  Boundaries depend only on the state
   template — NOT the world size — so shard identities (and their
   digests, and any replicas) survive resizes.  Ownership is
   world-dependent: a row-aligned shard is owned by the member whose
   ceil-chunked GSPMD slice contains its START row — a serving
   preference that tracks "each member already holds exactly its
   shards" (exact when the world's chunk aligns with shard rows; a
   border shard may straddle two slices, and correctness never
   depends on it: who can serve WHAT is always the digest-verified
   coverage map from the agreement); each shard also names K buddy
   replicas
   (ring successors) — the deterministic replica map every member
   computes identically from the membership alone.
2. **Per-shard digests.**  PR 2's per-leaf crc32 vector refines to a
   per-shard vector (``HostCheckpoint.shard_digests`` — one memory
   pass yields leaf AND shard granularity).  The restore agreement
   all-gathers both: the per-shard vector is simultaneously the
   need-matrix (member r needs shard s iff its crc differs from the
   reference) and the coverage map (any member advertising the
   reference crc can serve it).
3. **Parallel multi-peer pull.**  A joiner pulls only the shards it
   lacks, from MANY peers concurrently — one chunked-TCP stream per
   source (PR 2's wire discipline per stream: per-chunk crc32,
   ``recv_into`` straight into the preallocated leaf buffer, completed
   leaves handed to ``on_leaf`` while later chunks are still on the
   wire).  Restore time scales with state / world-bandwidth, not
   state / one NIC.  A peer that dies or serves torn bytes mid-pull
   costs only its unfinished shards: they fall back per-shard to the
   next replica holder.  When the world offers no multi-peer coverage
   (2-member worlds, a lone survivor) the engine hands the ENTIRE
   restore to PR 2's single-source stream — the decision is derived
   from the shared gather, so every member takes the same branch and
   the collectives stay paired.
4. **Replication off the critical path.**  ``replicate_to_buddies``
   pushes a member's owned shards to its K buddies with an
   offer/accept handshake (buddies decline shards they already hold,
   so the common collective-flush case moves ZERO bytes); it runs
   from the flush's stage-B background hook — never in the resize
   window.  A consensus-clean scale-down victim pushes its shard
   inheritance (owned + buddy-held shards) the same way before
   parking, so planned shrinks keep the newest state K-replicated
   among survivors without a durable-dir round trip.

The verdict stays world-consistent: a post-transfer confirmation
all-gather (same shape as the agreement, different tag) fails the
resize on EVERY member when anyone's pull was unrecoverable — exactly
PR 2's ``TornTransferError`` discipline.

Chaos: ``fabric.replica.torn`` (a serving peer's stored shard rotted
after it was advertised — the receiver's reference-digest check must
catch it and fall back), ``fabric.peer.lost`` (a source dies
mid-pull), ``fabric.replica.lost`` (a stage-B replica push never
reaches its buddy), ``fabric.pull.slow`` (a serving peer stalls
before one chunk send).

Like ``transfer.py``, the collective fabric is abstracted (the tiny
agreement rides ``JaxProcessFabric`` in production, ``LoopbackWorld``
threads in tests) while the TCP data plane is REAL in both — tests
count actual bytes on the wire, per peer.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from edl_tpu.checkpoint.hostdram import HostCheckpoint
from edl_tpu.checkpoint.transfer import (
    _CHUNK_HDR,
    _DONE_LEAF,
    _MAGIC,
    _NO_LEAF,
    _gather,
    _int_to_ip,
    _ip_to_int,
    _leaf_sizes,
    _recv_exact,
    _tune,
    TornTransferError,
    TransferError,
    TransferResult,
    TransferStats,
    stream_restore,
)

#: default shard granularity: small enough that a handful of members
#: splits even a single giant fused leaf, large enough that per-shard
#: header/crc/agreement overhead is noise.
DEFAULT_SHARD_BYTES = 32 << 20


def deployment_shard_bytes() -> int:
    """The deployment's configured shard granularity
    (``EDL_FABRIC_SHARD_BYTES``).  Everything that derives shard
    boundaries — the restore agreement, spill manifests, digest
    caches — must read the SAME size or their layout keys diverge and
    the cached/persisted digest vectors silently never hit."""
    import os

    return int(
        os.environ.get("EDL_FABRIC_SHARD_BYTES", str(DEFAULT_SHARD_BYTES))
    )


def gspmd_chunk(extent: int, world: int) -> int:
    """Rows per member under GSPMD's ceil-chunked equal split of an
    axis — THE chunk rule.  One definition on purpose: jax's
    ``NamedSharding.shard_shape``, ``ShardLayout.owner`` and the
    serving engine's per-device swap-staging accounting must all agree
    on where a tp/fsdp slice boundary falls, or the fabric's "each
    member already holds its shards" serving preference (and the
    engine's 1/tp swap-traffic claim) silently drifts."""
    return -(-int(extent) // max(1, int(world)))


def gspmd_owner(start_row: int, extent: int, world: int) -> int:
    """The member whose ceil-chunked axis-0 slice contains
    ``start_row`` (clamped: tail rows past the last full chunk belong
    to the last member)."""
    if world <= 1:
        return 0
    return min(int(start_row) // gspmd_chunk(extent, world), world - 1)


def leaf_rows(leaves) -> List[int]:
    """Per-leaf axis-0 extent (0 for 0-d leaves) — the row rule shard
    boundaries align to.  ONE definition on purpose: it is
    load-bearing for shard identity across save / spill / restore, so
    every call site must agree."""
    return [
        int(l.shape[0]) if getattr(l, "ndim", 0) else 0 for l in leaves
    ]


def leaf_nbytes(x) -> int:
    """Byte size of a leaf, concrete OR abstract — ShapeDtypeStruct has
    no ``.nbytes``, and the shard plane sizes layouts from abstract
    templates (eval_shape) precisely so no full state gets allocated."""
    nb = getattr(x, "nbytes", None)
    if nb is not None:
        return int(nb)
    return int(
        np.dtype(x.dtype).itemsize
        * np.prod(tuple(x.shape), dtype=np.int64)
    )


def byte_view(buf) -> memoryview:
    """Flat byte view of an array/buffer.  ``memoryview(x).cast("B")``
    raises on zero-size multi-dim arrays ("zeros in shape or
    strides"), so every wire path routes through the flatten-first
    spelling instead."""
    arr = np.ascontiguousarray(buf)
    return memoryview(arr.reshape(-1).view(np.uint8))


#: fabric wire magic (request headers); distinct from transfer.py's so
#: a stray cross-protocol connect fails loudly at the first header.
_FAB_MAGIC = 0xED15FAB0

#: request header: magic u32, kind u32, rank u32, count u32,
#: step i64, generation i64, chunk_bytes u32.
_REQ_HDR = struct.Struct("<IIIIqqI")
_KIND_PULL = 1
_KIND_OFFER = 2
#: per-shard range record: leaf u32, offset u64, length u64, crc u32.
_RANGE = struct.Struct("<IQQI")
#: chunk-length sentinel: "I no longer hold this range" (the server's
#: checkpoint was pruned between agreement and pull).
_MISS_LEN = (1 << 64) - 1
#: final ack of an OFFER session: accepted count u32.
_ACK = struct.Struct("<I")

#: agreement message tags (transfer.py uses 101/102; the shapes differ
#: too, so a desync across protocols fails the length check first).
_MSG_FABRIC_AGREE = 103
_MSG_FABRIC_CONFIRM = 104
_SUMMARY_HDR = 6


# ---------------------------------------------------------------------------
# the shard layout: world-independent boundaries, world-dependent owners
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Shard:
    """One contiguous byte range of one leaf."""

    index: int  # position in ShardLayout.shards (the agreement slot)
    leaf: int
    offset: int  # byte offset into the leaf's flat byte view
    length: int
    #: first axis-0 row covered; -1 when the leaf is not row-aligned
    #: (0-d leaves, leaves smaller than one shard)
    start_row: int = -1


class ShardLayout:
    """Deterministic shard table over a state template.

    Boundaries are a pure function of (leaf byte sizes, row sizes,
    shard_bytes) — every member of every world computes the same
    table, and the table survives resizes, so per-shard digests cache
    across generations and replicas pushed under one world remain
    addressable in the next.  Ownership and the buddy replica map are
    pure functions of (table, world, k) — recomputed per membership,
    never negotiated."""

    def __init__(
        self,
        shards: List[Shard],
        sizes: List[int],
        rows: List[int],
        world: int,
        k: int,
        shard_bytes: int,
    ):
        self.shards = shards
        self.sizes = list(sizes)
        self.rows = list(rows)
        self.world = max(1, int(world))
        self.k = max(0, int(k))
        self.shard_bytes = int(shard_bytes)
        self.by_leaf: Dict[int, List[Shard]] = {}
        for s in shards:
            self.by_leaf.setdefault(s.leaf, []).append(s)

    @staticmethod
    def build(
        sizes: Sequence[int],
        world: int,
        *,
        k: int = 1,
        shard_bytes: int = DEFAULT_SHARD_BYTES,
        rows: Optional[Sequence[int]] = None,
    ) -> "ShardLayout":
        """``sizes``: per-leaf byte sizes (the model template).
        ``rows``: per-leaf axis-0 extent (0 = not row-alignable); when
        given, shard boundaries land on whole rows so they track the
        GSPMD ceil-chunked axis-0 partition (nesting is exact when a
        world's chunk is a multiple of the shard's row stride;
        otherwise a border shard straddles two slices and ownership
        is just a serving preference — see the module docstring)."""
        rows = list(rows) if rows is not None else [0] * len(sizes)
        shard_bytes = max(1, int(shard_bytes))
        shards: List[Shard] = []
        for i, nbytes in enumerate(sizes):
            if nbytes <= shard_bytes:
                # Whole-leaf shard: no GSPMD slice to pin it to (every
                # member holds all of it), so ownership spreads
                # round-robin (start_row=-1 routes owner() there).
                shards.append(
                    Shard(
                        index=len(shards),
                        leaf=i,
                        offset=0,
                        length=int(nbytes),
                        start_row=-1,
                    )
                )
                continue
            row_b = nbytes // rows[i] if rows[i] > 0 else 0
            if row_b > 0:
                # Row-aligned: whole-row shards of ~shard_bytes.
                rows_per = max(1, shard_bytes // row_b)
                r = 0
                while r < rows[i]:
                    take = min(rows_per, rows[i] - r)
                    length = take * row_b
                    if r + take == rows[i]:
                        # Tail rounding (nbytes not divisible by rows,
                        # e.g. a trailing remainder) rides the last
                        # shard so coverage is exact.
                        length = nbytes - r * row_b
                    shards.append(
                        Shard(
                            index=len(shards),
                            leaf=i,
                            offset=r * row_b,
                            length=int(length),
                            start_row=r,
                        )
                    )
                    r += take
            else:
                off = 0
                while off < nbytes:
                    length = min(shard_bytes, nbytes - off)
                    shards.append(
                        Shard(
                            index=len(shards),
                            leaf=i,
                            offset=off,
                            length=int(length),
                            start_row=-1,
                        )
                    )
                    off += length
        return ShardLayout(shards, list(sizes), rows, world, k, shard_bytes)

    def key(self) -> tuple:
        """Boundary signature — deliberately world-independent so a
        checkpoint's cached shard digests survive resizes."""
        return (self.shard_bytes, tuple(self.sizes), tuple(self.rows))

    def owner(self, s: Shard) -> int:
        """The member whose GSPMD ceil-chunked axis-0 slice contains
        this shard's start row at this world size (a serving
        preference, not a correctness claim — see the module
        docstring); non-row shards spread round-robin."""
        if self.world <= 1:
            return 0
        if s.start_row >= 0 and self.rows[s.leaf] > 0:
            return gspmd_owner(s.start_row, self.rows[s.leaf], self.world)
        return (s.leaf + s.index) % self.world

    def holders(self, s: Shard) -> Tuple[int, ...]:
        """Owner first, then the K buddy replicas (ring successors) —
        the deterministic replica map."""
        owner = self.owner(s)
        out = [owner]
        for j in range(1, min(self.k, self.world - 1) + 1):
            out.append((owner + j) % self.world)
        return tuple(dict.fromkeys(out))

    def owned_by(self, rank: int) -> List[Shard]:
        return [s for s in self.shards if self.owner(s) == rank]

    def replica_map(self) -> Dict[int, Tuple[int, ...]]:
        """shard index -> (owner, replicas...) for the whole table —
        what every member computes identically from the membership."""
        return {s.index: self.holders(s) for s in self.shards}

    def wanted(self, rank: int) -> List[int]:
        """Shard indices member ``rank`` is responsible for holding in
        shard-only residency: its own GSPMD slice plus the K buddy
        shards the ring assigns it.  This is THE per-member memory
        contract — (1+K)/world of state instead of 1.0 — and every
        member computes it identically from the membership."""
        return [s.index for s in self.shards if rank in self.holders(s)]

    def row_span(self, s: Shard) -> Tuple[int, int]:
        """[row_lo, row_hi) of a row-aligned shard (the tail shard's
        rounding means length//row_bytes can undercount: the span runs
        to the NEXT shard's start row, or the leaf's end)."""
        if s.start_row < 0 or self.rows[s.leaf] <= 0:
            return (0, self.rows[s.leaf]) if self.rows[s.leaf] > 0 else (0, 0)
        peers = self.by_leaf[s.leaf]
        pos = peers.index(s)
        hi = (
            peers[pos + 1].start_row
            if pos + 1 < len(peers)
            else self.rows[s.leaf]
        )
        return (s.start_row, hi)

    def shards_for_rows(self, leaf: int, lo: int, hi: int) -> List[Shard]:
        """The shards of ``leaf`` whose row spans intersect [lo, hi) —
        what a device slice must fetch to stage rows [lo, hi) without
        materializing the whole leaf.  Non-row leaves (whole-leaf or
        plain byte-range shards) return every shard: their bytes carry
        no row structure, so any consumer needs all of them."""
        shs = self.by_leaf.get(leaf, [])
        if not shs or shs[0].start_row < 0:
            return list(shs)
        out = []
        for s in shs:
            s_lo, s_hi = self.row_span(s)
            if s_lo < hi and s_hi > lo:
                out.append(s)
        return out


def compute_shard_digests(
    leaves: Sequence[np.ndarray], layout: ShardLayout
) -> Tuple[List[int], List[int]]:
    """One memory pass over ``leaves`` yielding BOTH granularities:
    (per-shard crc32 vector, per-leaf crc32 vector).  The leaf crc is
    chained over its shards in offset order, which is exactly
    ``zlib.crc32`` over the whole leaf — so the fabric's refinement
    agrees bit-for-bit with PR 2's leaf digests."""
    shard_crcs = [0] * len(layout.shards)
    leaf_crcs = [0] * len(leaves)
    for i, leaf in enumerate(leaves):
        view = byte_view(leaf)
        crc = 0
        for s in layout.by_leaf.get(i, []):
            region = view[s.offset : s.offset + s.length]
            shard_crcs[s.index] = zlib.crc32(region)
            crc = zlib.crc32(region, crc)
        leaf_crcs[i] = crc
    return shard_crcs, leaf_crcs


# ---------------------------------------------------------------------------
# the replica store: buddy shards a member holds WITHOUT the checkpoint
# ---------------------------------------------------------------------------


class ShardReplicaStore:
    """Byte-range shards this member holds on behalf of buddies,
    keyed (step, leaf, offset, length) — the "host copy keyed by the
    shards it actually owns" half of the fabric for members that do
    NOT hold the full checkpoint (a parked victim's survivors, a
    partial holder after a degraded flush).  Bounded to the newest
    ``keep_steps`` distinct steps; stale pushes are declined."""

    def __init__(self, keep_steps: int = 1):
        self.keep_steps = max(1, keep_steps)
        self._lock = threading.Lock()
        self._shards: Dict[tuple, Tuple[np.ndarray, int]] = {}

    def newest_step(self) -> int:
        with self._lock:
            return max((k[0] for k in self._shards), default=-1)

    def wants(self, step: int, leaf: int, offset: int, length: int) -> bool:
        """Offer/accept gate: decline shards already held and shards
        older than the newest step in the store (replication must
        never roll a buddy's coverage backwards)."""
        key = (step, leaf, offset, length)
        with self._lock:
            newest = max((k[0] for k in self._shards), default=-1)
            return key not in self._shards and step >= newest

    def put(
        self,
        step: int,
        leaf: int,
        offset: int,
        length: int,
        data: np.ndarray,
        crc: int,
    ) -> bool:
        if zlib.crc32(data) != crc:
            return False
        with self._lock:
            self._shards[(step, leaf, offset, length)] = (data, int(crc))
            steps = sorted({k[0] for k in self._shards})
            for old in steps[: -self.keep_steps]:
                for k in [k for k in self._shards if k[0] == old]:
                    del self._shards[k]
        return True

    def get(
        self, step: int, leaf: int, offset: int, length: int
    ) -> Optional[np.ndarray]:
        with self._lock:
            hit = self._shards.get((step, leaf, offset, length))
            return hit[0] if hit is not None else None

    def crc(
        self, step: int, leaf: int, offset: int, length: int
    ) -> Optional[int]:
        with self._lock:
            hit = self._shards.get((step, leaf, offset, length))
            return hit[1] if hit is not None else None

    def drop_step(self, step: int) -> int:
        """Discard every shard held at ``step``.  The world-consistent
        degrade when an agreement proves the step unrestorable (no
        full holder anywhere, coverage gaps): every member decodes the
        same gather matrix and drops the same step together, so the
        RETRIED agreement advertises the newest FULL checkpoint step
        instead of livelocking on identical partial inputs — PR 2's
        degrade-to-next-oldest discipline at fabric granularity."""
        with self._lock:
            keys = [k for k in self._shards if k[0] == step]
            for k in keys:
                del self._shards[k]
            return len(keys)

    def shards_at(self, step: int) -> List[tuple]:
        """[(leaf, offset, length, crc)] held at ``step`` — what an
        inheritance push re-offers downstream."""
        with self._lock:
            return [
                (k[1], k[2], k[3], v[1])
                for k, v in self._shards.items()
                if k[0] == step
            ]

    def nbytes(self) -> int:
        with self._lock:
            return sum(k[3] for k in self._shards)


def adopt_resident(
    resident: ShardReplicaStore,
    leaves: Sequence[Any],
    layout: ShardLayout,
    rank: int,
    step: int,
    *,
    want: Optional[Sequence[int]] = None,
    crcs: Optional[Sequence[int]] = None,
) -> int:
    """Trim full leaves down to shard residency: copy the byte ranges
    of the shards ``rank`` must hold (``ShardLayout.wanted`` unless
    ``want`` overrides) into the resident store and return the bytes
    adopted.  The copies are real (not views) so the caller can DROP
    the full leaves afterwards — that drop is the whole point: host
    memory falls from 1.0x state to (1+K)/world.  ``crcs``: the
    layout-ordered shard digest vector when the caller already has one
    (flush stage B computed it); absent entries are hashed here."""
    idxs = layout.wanted(rank) if want is None else [int(s) for s in want]
    adopted = 0
    for s_idx in idxs:
        sh = layout.shards[s_idx]
        leaf = leaves[sh.leaf]
        if leaf is None or getattr(leaf, "nbytes", 0) < sh.offset + sh.length:
            continue
        region = byte_view(leaf)[sh.offset : sh.offset + sh.length]
        data = np.empty(sh.length, np.uint8)
        memoryview(data)[:] = region
        crc = (
            int(crcs[s_idx])
            if crcs is not None and s_idx < len(crcs)
            else zlib.crc32(data)
        )
        if resident.put(step, sh.leaf, sh.offset, sh.length, data, crc):
            adopted += sh.length
    return adopted


def assemble_from_resident(
    resident: ShardReplicaStore,
    layout: ShardLayout,
    step: int,
    leaf: int,
    template_leaf: Any,
) -> np.ndarray:
    """One full leaf rebuilt from resident shard bytes (cold start /
    verification paths).  Raises ``TransferError`` when coverage is
    incomplete — shard-only residency plus this assembler is the
    cluster-memory replacement for a full host copy."""
    buf = np.empty(template_leaf.shape, np.dtype(template_leaf.dtype))
    view = byte_view(buf)
    for sh in layout.by_leaf.get(leaf, []):
        src = resident.get(step, sh.leaf, sh.offset, sh.length)
        if src is None:
            raise TransferError(
                f"shard-only assembly: leaf {leaf} missing shard "
                f"{sh.index} at step {step}"
            )
        view[sh.offset : sh.offset + sh.length] = byte_view(src)
    return buf


def stage_slice_from_shards(
    layout: ShardLayout,
    leaf: int,
    template_leaf: Any,
    index: Any,
    shard_src: Callable[[Shard], Any],
) -> np.ndarray:
    """The device slice ``template_leaf[index]`` assembled straight
    from shard byte ranges — the staging primitive behind serving hot
    swap and tp restore, with NO full-leaf materialization.

    ``index`` is a jax device index (tuple of step-1 slices).  Row
    leaves copy only the covering shards' overlapping rows, applying
    the trailing-axis slices per shard block so a tp-sharded kernel
    stages exactly its columns; whole-leaf / byte-range shards (≤ one
    shard_bytes) assemble the small leaf then slice.  ``shard_src``
    maps a ``Shard`` to its bytes — a view into a full host leaf (the
    DRAM hot-swap path, zero extra copies), an npz entry of a
    shard-only durable spill, or a resident-store hit — so every
    consumer shares ONE offset arithmetic.  Bytes are bit-identical to
    ``np.asarray(template[index])`` by construction."""
    shape = tuple(template_leaf.shape)
    dtype = np.dtype(template_leaf.dtype)
    idx = tuple(index) if index is not None else ()
    idx = idx + (slice(None),) * (len(shape) - len(idx))
    shs = layout.by_leaf.get(leaf, [])
    if not shs:
        if not shape or int(np.prod(shape, dtype=np.int64)) == 0:
            return np.empty(shape, dtype)[idx if shape else ()]
        raise TransferError(f"no shards cover leaf {leaf}")
    rows = layout.rows[leaf]
    if not shape or rows <= 0 or shs[0].start_row < 0:
        buf = np.empty(shape, dtype)
        view = byte_view(buf)
        for sh in shs:
            view[sh.offset : sh.offset + sh.length] = byte_view(
                shard_src(sh)
            )[: sh.length]
        return buf[idx] if shape else buf
    s0 = idx[0]
    lo = 0 if s0.start is None else int(s0.start)
    hi = shape[0] if s0.stop is None else int(s0.stop)
    rest = tuple(idx[1:])
    tail = int(np.prod(shape[1:], dtype=np.int64))
    out: Optional[np.ndarray] = None
    for sh in layout.shards_for_rows(leaf, lo, hi):
        s_lo, s_hi = layout.row_span(sh)
        a, b = max(lo, s_lo), min(hi, s_hi)
        if a >= b:
            continue
        src = np.frombuffer(
            byte_view(shard_src(sh)), dtype, count=(s_hi - s_lo) * tail
        ).reshape((s_hi - s_lo,) + shape[1:])
        block = src[a - s_lo : b - s_lo]
        if rest:
            block = block[(slice(None),) + rest]
        if out is None:
            out = np.empty((hi - lo,) + block.shape[1:], dtype)
        out[a - lo : b - lo] = block
    if out is None:
        raise TransferError(
            f"no shards cover rows [{lo}, {hi}) of leaf {leaf}"
        )
    return out


class ReplicaIngest:
    """OFFER gate for a member's ``FabricServer``: declines shards
    whose bytes the member already holds in a full checkpoint at that
    step — this is what makes the collective-flush replication round
    byte-free — and delegates genuinely novel shards to the replica
    store.  ``has_bytes(step, leaf, offset, length)`` answers the
    full-checkpoint question (the store owner knows)."""

    def __init__(
        self,
        replicas: ShardReplicaStore,
        has_bytes: Callable[[int, int, int, int], bool],
    ):
        self.replicas = replicas
        self.has_bytes = has_bytes

    def wants(self, step: int, leaf: int, offset: int, length: int) -> bool:
        if self.has_bytes(step, leaf, offset, length):
            return False
        return self.replicas.wants(step, leaf, offset, length)

    def put(self, *args) -> bool:
        return self.replicas.put(*args)


# ---------------------------------------------------------------------------
# the fabric server: serves pulls, ingests replica pushes
# ---------------------------------------------------------------------------


class FabricServer:
    """Persistent per-member shard endpoint.

    ``lookup(step, leaf, offset, length)``: a buffer exposing exactly
    those bytes, or None — backed by the member's checkpoint store
    and/or its ``ShardReplicaStore``.  ``ingest``: a replica store
    (``wants``/``put``) accepting OFFER pushes; None declines all.
    One daemon thread accepts; each connection is handled on its own
    thread, so concurrent receivers aggregate the member's NIC."""

    def __init__(
        self,
        lookup: Callable[[int, int, int, int], Any],
        ingest: Optional[ShardReplicaStore] = None,
        *,
        timeout: float = 120.0,
        chaos=None,
    ):
        self.lookup = lookup
        self.ingest = ingest
        self.timeout = timeout
        self.chaos = chaos
        self.port = 0
        self._srv: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()
        #: pull-path payload bytes served, total and per requester rank
        self.pull_bytes_sent = 0
        self.pull_bytes_by_rank: Dict[int, int] = {}
        #: replica shards / bytes accepted over OFFER sessions
        self.replicas_accepted = 0
        self.replica_bytes = 0
        #: chaos[fabric.replica.torn] budget: each scheduled event
        #: buys ONE torn served range (due() pops every due event at
        #: once, so the budget spreads them across ranges/connections)
        self._torn_budget = 0

    def start(self) -> "FabricServer":
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.bind(("0.0.0.0", 0))
        srv.listen(64)
        srv.settimeout(0.5)
        self._srv = srv
        self.port = srv.getsockname()[1]
        threading.Thread(
            target=self._accept_loop, daemon=True, name="edl-fabric-serve"
        ).start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._handle,
                args=(conn,),
                daemon=True,
                name="edl-fabric-conn",
            ).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            with conn:
                conn.settimeout(self.timeout)
                _tune(conn)
                hdr = bytearray(_REQ_HDR.size)
                _recv_exact(conn, memoryview(hdr))
                magic, kind, rank, count, step, gen, chunk = _REQ_HDR.unpack(
                    bytes(hdr)
                )
                if magic != _FAB_MAGIC or count > 1_000_000:
                    return
                ranges = []
                raw = bytearray(_RANGE.size * count)
                _recv_exact(conn, memoryview(raw))
                for j in range(count):
                    ranges.append(
                        _RANGE.unpack_from(raw, j * _RANGE.size)
                    )
                if kind == _KIND_PULL:
                    self._serve_pull(conn, rank, step, ranges, chunk)
                elif kind == _KIND_OFFER:
                    self._serve_offer(conn, step, ranges)
        except (TransferError, OSError, struct.error):
            # A receiver that died mid-pull (or a garbled request) is
            # ITS problem; the server must not care.
            pass

    def _serve_pull(
        self,
        conn: socket.socket,
        rank: int,
        step: int,
        ranges: List[tuple],
        chunk_bytes: int,
    ) -> None:
        chunk_bytes = max(1, chunk_bytes)
        for leaf, offset, length, _crc in ranges:
            buf = self.lookup(step, leaf, offset, length)
            if buf is None:
                conn.sendall(
                    _CHUNK_HDR.pack(_MAGIC, leaf, offset, _MISS_LEN, 0)
                )
                continue
            mv = byte_view(buf)
            tear = False
            if self.chaos is not None:
                with self._lock:
                    self._torn_budget += len(
                        self.chaos.due("fabric.replica.torn")
                    )
                    if self._torn_budget > 0:
                        self._torn_budget -= 1
                        tear = True
            off = 0
            while off < length or (length == 0 and off == 0):
                part = mv[off : off + chunk_bytes]
                if self.chaos is not None:
                    # chaos[fabric.pull.slow]: a stalled serving peer —
                    # the parallel pull must keep draining the OTHER
                    # streams while this one crawls.
                    for ev in self.chaos.due("fabric.pull.slow"):
                        time.sleep(float(ev.arg or 0.05))
                if tear and len(part):
                    # chaos[fabric.replica.torn]: the stored shard
                    # rotted AFTER its crc was advertised in the
                    # agreement — the per-chunk crc below is computed
                    # over the torn bytes (self-consistent, as real rot
                    # would be), so only the receiver's check against
                    # the ADVERTISED reference digest can catch it.
                    part = bytearray(part)
                    part[0] ^= 0xFF
                    tear = False
                conn.sendall(
                    _CHUNK_HDR.pack(
                        _MAGIC,
                        leaf,
                        offset + off,
                        len(part),
                        zlib.crc32(part),
                    )
                )
                conn.sendall(part)
                with self._lock:
                    self.pull_bytes_sent += len(part)
                    self.pull_bytes_by_rank[rank] = (
                        self.pull_bytes_by_rank.get(rank, 0) + len(part)
                    )
                off += len(part)
                if length == 0:
                    break
        conn.sendall(_CHUNK_HDR.pack(_MAGIC, _DONE_LEAF, 0, 0, 0))

    def _serve_offer(
        self, conn: socket.socket, step: int, ranges: List[tuple]
    ) -> None:
        want = bytearray(len(ranges))
        for j, (leaf, offset, length, _crc) in enumerate(ranges):
            if self.ingest is not None and self.ingest.wants(
                step, leaf, offset, length
            ):
                want[j] = 1
        conn.sendall(bytes(want))
        accepted = 0
        hdr = bytearray(_CHUNK_HDR.size)
        from edl_tpu import telemetry

        reg = telemetry.get_registry()
        m_replicas = reg.counter("edl_fabric_replicas_total")
        m_replica_bytes = reg.counter("edl_fabric_replica_bytes_total")
        for j, (leaf, offset, length, crc) in enumerate(ranges):
            if not want[j]:
                continue
            # Payload arrives as in-order chunks covering the range.
            data = np.empty(length, np.uint8)
            got = 0
            ok = True
            while got < length:
                _recv_exact(conn, memoryview(hdr))
                magic, c_leaf, c_off, c_len, c_crc = _CHUNK_HDR.unpack(
                    bytes(hdr)
                )
                if (
                    magic != _MAGIC
                    or c_leaf != leaf
                    or c_off != offset + got
                    or c_off + c_len > offset + length
                ):
                    return  # garbled push: drop the session
                region = memoryview(data)[got : got + c_len]
                _recv_exact(conn, region)
                if zlib.crc32(region) != c_crc:
                    ok = False
                got += c_len
            if ok and self.ingest.put(step, leaf, offset, length, data, crc):
                accepted += 1
                with self._lock:
                    self.replicas_accepted += 1
                    self.replica_bytes += length
                m_replicas.inc()
                m_replica_bytes.inc(length)
        conn.sendall(_ACK.pack(accepted))


# ---------------------------------------------------------------------------
# pull client (one stream = one peer; the engine runs many at once)
# ---------------------------------------------------------------------------


def _pull_from_peer(
    addr: Tuple[str, int],
    my_rank: int,
    peer_rank: int,
    step: int,
    shards: List[Shard],
    bufs: Optional[Dict[int, np.ndarray]],
    reference: Dict[int, int],
    *,
    chunk_bytes: int,
    timeout: float,
    chaos,
    regions: Optional[Callable[[Shard, int, int], memoryview]] = None,
) -> Tuple[List[Shard], List[Shard], int, int]:
    """Pull ``shards`` from one peer.  Returns (ok, failed,
    bytes_received, chunks).  Never raises: a dead/slow/torn peer
    costs only its unfinished shards — they go back to the pool and
    the engine reassigns them to the next holder.

    Received bytes land in ``bufs`` (full-leaf buffers, indexed by
    absolute leaf offset) or — when ``regions`` is given — wherever
    ``regions(shard, rel_offset, length)`` points, which is what lets
    a shard-only member pull into per-shard buffers without ever
    allocating a full leaf."""
    ok: List[Shard] = []
    failed: List[Shard] = []
    received = 0
    chunks = 0
    by_key = {(s.leaf, s.offset): s for s in shards}
    done: Dict[tuple, int] = {}  # (leaf, offset) -> bytes landed
    crc_chain: Dict[tuple, int] = {}
    # O(1) chunk->shard routing: chunks arrive in-order per shard, so
    # a shard's next chunk always starts at offset + landed bytes —
    # key each incomplete shard by that moving edge (a linear scan
    # here is O(shards) PER CHUNK, quadratic at small shard sizes).
    expected: Dict[tuple, tuple] = {k: k for k in by_key}
    failed_keys: set = set()
    remaining = len(shards)
    try:
        conn = socket.create_connection(addr, timeout=timeout)
    except OSError:
        return ok, list(shards), received, chunks
    try:
        with conn:
            conn.settimeout(timeout)
            _tune(conn)
            conn.sendall(
                _REQ_HDR.pack(
                    _FAB_MAGIC,
                    _KIND_PULL,
                    my_rank,
                    len(shards),
                    step,
                    0,
                    chunk_bytes,
                )
            )
            conn.sendall(
                b"".join(
                    _RANGE.pack(s.leaf, s.offset, s.length, 0)
                    for s in shards
                )
            )
            hdr = bytearray(_CHUNK_HDR.size)
            lost_due = False
            while remaining > 0:
                _recv_exact(conn, memoryview(hdr))
                magic, leaf, off, length, crc = _CHUNK_HDR.unpack(bytes(hdr))
                if magic != _MAGIC:
                    raise TransferError("fabric pull: bad chunk magic")
                if leaf == _DONE_LEAF:
                    break
                if length == _MISS_LEN:
                    # The peer no longer holds this range.
                    key = (leaf, off)
                    s = by_key.get(key)
                    if s is not None and done.get(key, 0) < s.length:
                        expected.pop((leaf, off + done.get(key, 0)), None)
                        done[key] = s.length
                        failed.append(s)
                        failed_keys.add(key)
                        remaining -= 1
                    continue
                key = expected.pop((leaf, off), None)
                if key is None:
                    raise TransferError(
                        f"fabric pull: out-of-order chunk leaf={leaf} "
                        f"off={off}"
                    )
                s = by_key[key]
                if off + length > key[1] + s.length:
                    raise TransferError(
                        f"fabric pull: chunk overruns shard leaf={leaf} "
                        f"off={off} len={length}"
                    )
                if regions is not None:
                    region = regions(s, off - s.offset, length)
                else:
                    region = byte_view(bufs[leaf])[off : off + length]
                _recv_exact(conn, region)
                if chaos is not None and not lost_due:
                    # chaos[fabric.peer.lost]: the peer dies mid-pull
                    # (from this receiver's point of view) — remaining
                    # shards must fall back to another replica holder.
                    if list(chaos.due("fabric.peer.lost")):
                        lost_due = True
                        raise OSError("fabric peer lost (chaos)")
                chunks += 1
                received += length
                if zlib.crc32(region) != crc:
                    # Torn on the wire: the shard is unusable from
                    # this peer; keep draining (tearing the stream
                    # down would poison the peer's other streams).
                    crc_chain[key] = None
                else:
                    prev = crc_chain.get(key, 0)
                    if prev is not None:
                        crc_chain[key] = zlib.crc32(region, prev)
                done[key] = done.get(key, 0) + length
                if done[key] < s.length:
                    expected[(leaf, off + length)] = key
                elif key not in failed_keys:
                    remaining -= 1
                    chained = crc_chain.get(key, 0)
                    if chained is not None and chained == reference.get(
                        s.index
                    ):
                        ok.append(s)
                    else:
                        # Chunk-crc-consistent but reference-digest
                        # mismatched = the peer's copy rotted after it
                        # was advertised (fabric.replica.torn).
                        failed.append(s)
                        failed_keys.add(key)
    except (TransferError, OSError, struct.error):
        got = {(s.leaf, s.offset) for s in ok} | {
            (s.leaf, s.offset) for s in failed
        }
        failed.extend(s for s in shards if (s.leaf, s.offset) not in got)
    else:
        got = {(s.leaf, s.offset) for s in ok} | {
            (s.leaf, s.offset) for s in failed
        }
        failed.extend(s for s in shards if (s.leaf, s.offset) not in got)
    return ok, failed, received, chunks


# ---------------------------------------------------------------------------
# replication: offer/accept pushes to the deterministic buddies
# ---------------------------------------------------------------------------


def push_shards(
    addr: Tuple[str, int],
    my_rank: int,
    step: int,
    generation: int,
    shards: List[Tuple[int, int, int, int, Any]],
    *,
    chunk_bytes: int = DEFAULT_SHARD_BYTES,
    timeout: float = 30.0,
) -> Tuple[int, int]:
    """OFFER ``shards`` [(leaf, offset, length, crc, buffer)] to one
    peer; payload moves only for the ranges the peer accepts.
    Returns (accepted, payload_bytes)."""
    conn = socket.create_connection(addr, timeout=timeout)
    with conn:
        conn.settimeout(timeout)
        _tune(conn)
        conn.sendall(
            _REQ_HDR.pack(
                _FAB_MAGIC,
                _KIND_OFFER,
                my_rank,
                len(shards),
                step,
                generation,
                chunk_bytes,
            )
        )
        conn.sendall(
            b"".join(
                _RANGE.pack(leaf, off, length, crc)
                for leaf, off, length, crc, _ in shards
            )
        )
        want = bytearray(len(shards))
        _recv_exact(conn, memoryview(want))
        sent = 0
        for j, (leaf, off, length, _crc, buf) in enumerate(shards):
            if not want[j] or length == 0:
                # Zero-length shards carry no payload chunks — the
                # server's per-range loop reads exactly ``length``
                # bytes, so an empty chunk here would desync the
                # session (it stores the empty range from the offer's
                # crc alone).
                continue
            mv = byte_view(buf)
            pos = 0
            while pos < length:
                part = mv[pos : pos + chunk_bytes]
                conn.sendall(
                    _CHUNK_HDR.pack(
                        _MAGIC, leaf, off + pos, len(part), zlib.crc32(part)
                    )
                )
                conn.sendall(part)
                sent += len(part)
                pos += len(part)
        ack = bytearray(_ACK.size)
        _recv_exact(conn, memoryview(ack))
        return _ACK.unpack(bytes(ack))[0], sent


def replicate_to_buddies(
    layout: ShardLayout,
    my_rank: int,
    step: int,
    generation: int,
    peer_addrs: Dict[int, Tuple[str, int]],
    shard_source: Callable[[Shard], Optional[Tuple[Any, int]]],
    *,
    chunk_bytes: int = DEFAULT_SHARD_BYTES,
    timeout: float = 30.0,
    chaos=None,
) -> dict:
    """Offer this member's owned shards to their buddy replicas.
    Buddies decline shards they already hold, so the common
    collective-flush case moves zero payload bytes.  An unreachable
    buddy is skipped wire-wise (the next flush re-offers), but the
    summary now ACCOUNTS for it: ``underreplicated`` counts owned
    shards that did not reach every ring buddy (a declined offer IS an
    ack — the buddy already holds the bytes), which is what lets the
    flush path enforce ``EDL_FABRIC_K`` instead of treating it as
    advisory.  Returns a summary dict for the ``fabric.replicate``
    journal entry."""
    offers: Dict[int, List[Tuple[int, int, int, int, Any]]] = {}
    owned = layout.owned_by(my_rank)
    #: per-shard ring-buddy targets (K enforced against these; a buddy
    #: with no known address can never ack, so it counts as expected
    #: and missing — losing a peer's address IS under-replication)
    expected: Dict[int, int] = {}
    acks: Dict[int, int] = {}
    for s in owned:
        src = shard_source(s)
        if src is None:
            continue
        buf, crc = src
        buddies = [b for b in layout.holders(s)[1:] if b != my_rank]
        expected[s.index] = len(buddies)
        acks[s.index] = 0
        for buddy in buddies:
            if buddy not in peer_addrs:
                continue
            offers.setdefault(buddy, []).append(
                (s.leaf, s.offset, s.length, crc, buf)
            )
    summary = {
        "step": step,
        "offered": sum(len(v) for v in offers.values()),
        "accepted": 0,
        "bytes": 0,
        "peers": sorted(offers),
        "dropped": 0,
        "underreplicated": 0,
    }
    for buddy, items in offers.items():
        if chaos is not None and list(chaos.due("fabric.replica.lost")):
            # chaos[fabric.replica.lost]: the push never reaches the
            # buddy (network partition, buddy OOM) — the next flush
            # re-offers, and the ack accounting below reports the
            # window where K is not met.
            summary["dropped"] += len(items)
            continue
        try:
            accepted, sent = push_shards(
                peer_addrs[buddy],
                my_rank,
                step,
                generation,
                items,
                chunk_bytes=chunk_bytes,
                timeout=timeout,
            )
            summary["accepted"] += accepted
            summary["bytes"] += sent
            # A completed OFFER session acks every item in it: the
            # buddy either stored the shard or declined it because it
            # already holds those bytes — both leave the ring covered.
            by_range = {
                (s.leaf, s.offset, s.length): s.index for s in owned
            }
            for leaf, off, length, _crc, _buf in items:
                idx = by_range.get((leaf, off, length))
                if idx is not None:
                    acks[idx] = acks.get(idx, 0) + 1
        except (OSError, TransferError, struct.error):
            # An unreachable buddy — or one that closed the connection
            # mid-offer (e.g. parking for a scale-down) — is skipped;
            # the next flush re-offers.
            summary["dropped"] += len(items)
    summary["underreplicated"] = sum(
        1 for idx, want in expected.items() if acks.get(idx, 0) < want
    )
    return summary


def _record_degrade(step: int, dropped: int, reason: str) -> None:
    """Journal a world-consistent coverage degrade LOUDLY: the
    agreement proved ``step`` unrestorable (coverage below what the
    ring promised) and every member is dropping it together so the
    retry lands on the newest fully-covered step instead of
    livelocking.  Silence here is how an advisory K rots into data
    loss nobody noticed."""
    from edl_tpu import telemetry

    telemetry.get_recorder().record(
        "fabric.degrade",
        {"dropped_shards": int(dropped), "reason": reason},
        step=int(step),
    )


# ---------------------------------------------------------------------------
# shard-only residency: agree + pull ONLY the shards a member must hold
# ---------------------------------------------------------------------------


def shard_restore(
    fabric,
    template_leaves: Sequence[Any],
    resident: ShardReplicaStore,
    *,
    rows: Optional[Sequence[int]] = None,
    k: int = 1,
    shard_bytes: int = DEFAULT_SHARD_BYTES,
    want: Optional[Sequence[int]] = None,
    server: Optional[FabricServer] = None,
    chunk_bytes: int = DEFAULT_SHARD_BYTES,
    timeout: float = 120.0,
    chaos=None,
    max_streams: int = 8,
) -> TransferResult:
    """Shard-RESIDENT restore: every member ends holding exactly its
    ``want`` shards (default: own GSPMD slice + K ring-buddy shards,
    ``ShardLayout.wanted``) — never a full leaf, never a full state.

    This is the cluster-memory restore the shard-only host plane runs
    on: the collective agreement is the same gather shape as
    ``fabric_restore`` (every member advertises the per-shard crc
    vector of its resident bytes), the reference digests come from the
    union of advertisements (no full-checkpoint authority exists
    anywhere by design), and the pull lands in PER-SHARD buffers via
    ``_pull_from_peer``'s region hook, so a joiner's peak host bytes
    are own-slice + K-buddy + the in-flight shard — not the state.

    Coverage below the ring's promise degrades loudly and
    world-consistently: any shard with NO advertiser at the agreed
    step makes every member drop that step from its resident store and
    raise ``TransferError`` — the caller's hold-and-retry re-agrees at
    the newest fully-covered step (the killed-buddy discipline; a
    livelock on identical partial inputs is the failure mode this
    buys out of).  Every member of the world must call this in the
    same window (two collectives: agree + confirm)."""
    t0 = time.perf_counter()
    sizes = _leaf_sizes(template_leaves)
    n = len(sizes)
    layout = ShardLayout.build(
        sizes, fabric.world, k=k, shard_bytes=shard_bytes, rows=rows
    )
    m = len(layout.shards)
    me = fabric.rank
    want_idx = sorted(
        set(layout.wanted(me)) if want is None else {int(s) for s in want}
    )

    adv_step = resident.newest_step()
    have = adv_step >= 0

    vec = np.full(_SUMMARY_HDR + n + m, _NO_LEAF, np.int64)
    vec[0] = _MSG_FABRIC_AGREE
    vec[1] = 1 if have else 0
    vec[2] = adv_step if have else -1
    vec[3] = -1  # shard-only members never hold a full-state digest
    vec[4] = _ip_to_int(getattr(fabric, "advertise_host", "127.0.0.1"))

    ephemeral = None
    if server is None:

        def lookup(step, leaf, offset, length):
            return resident.get(step, leaf, offset, length)

        ephemeral = FabricServer(
            lookup,
            ingest=ReplicaIngest(resident, lambda *a: False),
            timeout=timeout,
            chaos=chaos,
        ).start()
        server = ephemeral
    vec[5] = server.port if server is not None else 0

    by_range = {
        (s.leaf, s.offset, s.length): s.index for s in layout.shards
    }
    if have:
        for leaf, off, length, crc in resident.shards_at(adv_step):
            idx = by_range.get((leaf, off, length))
            if idx is not None:
                vec[_SUMMARY_HDR + n + idx] = int(crc)

    pull_sent0 = server.pull_bytes_sent if server is not None else 0

    def cleanup():
        if ephemeral is not None:
            ephemeral.stop()

    try:
        world = _gather(fabric, vec, _MSG_FABRIC_AGREE)
    except TransferError:
        cleanup()
        raise
    W = world.shape[0]
    haves, steps = world[:, 1], world[:, 2]
    peer_addrs = {
        r: (_int_to_ip(world[r, 4]), int(world[r, 5]))
        for r in range(W)
        if int(world[r, 5]) > 0
    }

    if not haves.any():
        cleanup()
        return TransferResult(
            stats=TransferStats(mode="init"), peer_addrs=peer_addrs
        )

    agreed = int(steps.max())
    at_step = [r for r in range(W) if haves[r] and int(steps[r]) == agreed]
    shard_adv = world[:, _SUMMARY_HDR + n :]
    order = sorted(at_step)
    reference: List[int] = []
    for s in range(m):
        # Owner-first reference: the rank whose GSPMD slice the shard
        # belongs to is the natural authority when it advertised; any
        # other advertiser otherwise (deterministic: lowest rank).
        own = layout.owner(layout.shards[s])
        ranked = [own] + [r for r in order if r != own]
        reference.append(
            next(
                (
                    int(shard_adv[r, s])
                    for r in ranked
                    if r in at_step and int(shard_adv[r, s]) != _NO_LEAF
                ),
                _NO_LEAF,
            )
        )
    gap = [s for s in range(m) if reference[s] == _NO_LEAF]
    if gap:
        cleanup()
        dropped = resident.drop_step(agreed) if adv_step == agreed else 0
        _record_degrade(
            agreed, dropped, f"{len(gap)} shard(s) with no holder"
        )
        raise TransferError(
            f"fabric shard restore: {len(gap)} shard(s) have no holder "
            f"at the agreed step {agreed} (first: shard {min(gap)}); "
            "coverage below the replication promise — degrading to the "
            "newest fully-covered step"
        )
    holders: List[List[int]] = [
        [r for r in at_step if int(shard_adv[r, s]) == reference[s]]
        for s in range(m)
    ]

    stats = TransferStats(mode="fabric", source_rank=min(at_step), step=agreed)
    #: shards I must hold but whose resident bytes are absent or
    #: mismatch the agreed reference
    mine: List[int] = []
    for s in want_idx:
        sh = layout.shards[s]
        crc = resident.crc(agreed, sh.leaf, sh.offset, sh.length)
        if crc is None or crc != reference[s]:
            mine.append(s)
    stats.bytes_scheduled = sum(layout.shards[s].length for s in mine)
    stats.leaves_skipped = len(want_idx) - len(mine)

    my_ok = True
    fail_reason = ""
    per_peer: Dict[str, int] = {}
    if mine:
        #: per-shard destination buffers — the ONLY assembly memory
        #: this path ever allocates (never a leaf, never the state)
        shard_bufs: Dict[int, np.ndarray] = {
            s: np.empty(layout.shards[s].length, np.uint8) for s in mine
        }

        def regions(sh: Shard, rel: int, length: int) -> memoryview:
            return memoryview(shard_bufs[sh.index])[rel : rel + length]

        pending: Dict[int, Shard] = {s: layout.shards[s] for s in mine}
        tried: Dict[int, set] = {s: set() for s in mine}
        dead_peers: set = set()

        def eligible(s_idx: int) -> List[int]:
            sh = layout.shards[s_idx]
            ladder = [r for r in layout.holders(sh) if r in holders[s_idx]]
            ladder += [r for r in holders[s_idx] if r not in ladder]
            return [
                r
                for r in ladder
                if r != me
                and r not in tried[s_idx]
                and r not in dead_peers
                and r in peer_addrs
            ]

        first_round = True
        while pending and my_ok:
            groups: Dict[int, List[Shard]] = {}
            load: Dict[int, int] = {}
            stuck = False
            for s_idx in sorted(pending):
                cands = eligible(s_idx)
                if not cands:
                    stuck = True
                    break
                sh = pending[s_idx]
                owner = layout.owner(sh)
                peer = min(
                    cands,
                    key=lambda r: (
                        load.get(r, 0),
                        0 if r == owner else 1,
                        r,
                    ),
                )
                load[peer] = load.get(peer, 0) + sh.length
                groups.setdefault(peer, []).append(sh)
            if stuck:
                my_ok = False
                fail_reason = "a wanted shard exhausted every holder"
                break
            if not first_round:
                stats.shard_fallbacks += sum(len(v) for v in groups.values())
            first_round = False
            results: List[tuple] = []
            res_lock = threading.Lock()

            def pull(peer, shards_for_peer):
                out = _pull_from_peer(
                    peer_addrs[peer],
                    me,
                    peer,
                    agreed,
                    shards_for_peer,
                    None,
                    {s: reference[s] for s in mine},
                    chunk_bytes=chunk_bytes,
                    timeout=timeout,
                    chaos=chaos,
                    regions=regions,
                )
                with res_lock:
                    results.append((peer, out))

            peers_now = sorted(groups)
            for wave_at in range(0, len(peers_now), max(1, max_streams)):
                if not my_ok:
                    break
                wave = peers_now[wave_at : wave_at + max(1, max_streams)]
                threads = [
                    threading.Thread(
                        target=pull,
                        args=(p, groups[p]),
                        daemon=True,
                        name=f"edl-fabric-pull-r{p}",
                    )
                    for p in wave
                ]
                for t in threads:
                    t.start()
                deadline = time.monotonic() + timeout + 30
                for t in threads:
                    t.join(max(0.0, deadline - time.monotonic()))
                    if t.is_alive():
                        my_ok = False
                        fail_reason = "a pull stream hung past timeout"
            for peer, (ok_shs, failed_shs, rec, chs) in results:
                stats.bytes_received += rec
                stats.chunks_received += chs
                if rec:
                    per_peer[str(peer)] = per_peer.get(str(peer), 0) + rec
                for sh in ok_shs:
                    if sh.index not in pending:
                        continue
                    del pending[sh.index]
                    # Adoption is immediate and crc-gated: the pulled
                    # buffer becomes resident the moment its chained
                    # crc matched the reference.
                    resident.put(
                        agreed,
                        sh.leaf,
                        sh.offset,
                        sh.length,
                        shard_bufs.pop(sh.index),
                        reference[sh.index],
                    )
                    stats.leaves_received += 1
                for sh in failed_shs:
                    tried[sh.index].add(peer)
                if failed_shs and not ok_shs and rec == 0:
                    dead_peers.add(peer)
    stats.per_peer = per_peer

    # -- world-consistent verdict -------------------------------------------
    vec2 = np.zeros(_SUMMARY_HDR + n + m, np.int64)
    vec2[0] = _MSG_FABRIC_CONFIRM
    vec2[1] = 1 if my_ok else 0
    try:
        ok_col = _gather(fabric, vec2, _MSG_FABRIC_CONFIRM)[:, 1]
    finally:
        if server is not None:
            stats.bytes_sent = server.pull_bytes_sent - pull_sent0
        cleanup()
    if not ok_col.all():
        bad = [r for r in range(len(ok_col)) if not ok_col[r]]
        mine_msg = f" (this member: {fail_reason})" if fail_reason else ""
        raise TornTransferError(
            f"fabric shard restore: member(s) {bad} could not reach "
            f"their resident coverage{mine_msg}: no member adopts; "
            "resize retries"
        )
    stats.seconds = time.perf_counter() - t0

    from edl_tpu import telemetry

    reg = telemetry.get_registry()
    if stats.bytes_sent:
        reg.counter("edl_fabric_bytes_sent_total").inc(stats.bytes_sent)
    if stats.bytes_received:
        reg.counter("edl_fabric_bytes_received_total").inc(
            stats.bytes_received
        )
    if stats.per_peer:
        reg.gauge("edl_fabric_pull_peers").set(len(stats.per_peer))
    if stats.shard_fallbacks:
        reg.counter("edl_fabric_shard_fallbacks_total").inc(
            stats.shard_fallbacks
        )
    reg.gauge("edl_fabric_resident_bytes").set(resident.nbytes())
    reg.histogram("edl_fabric_pull_seconds").observe(stats.seconds)
    telemetry.get_recorder().record(
        "fabric.pull",
        {
            "mode": "shard_only",
            "step": stats.step,
            "bytes_received": stats.bytes_received,
            "bytes_sent": stats.bytes_sent,
            "peers": sorted(stats.per_peer or ()),
            "shard_fallbacks": stats.shard_fallbacks,
            "wanted": len(want_idx),
            "pulled": len(mine),
            "resident_bytes": resident.nbytes(),
        },
        step=stats.step,
        timing={"seconds": round(stats.seconds, 6)},
    )
    return TransferResult(stats=stats, peer_addrs=peer_addrs)


# ---------------------------------------------------------------------------
# the engine: agree on shards, pull in parallel, confirm world-wide
# ---------------------------------------------------------------------------


def fabric_restore(
    fabric,
    template_leaves: Sequence[Any],
    ckpt: Optional[HostCheckpoint],
    *,
    rows: Optional[Sequence[int]] = None,
    k: int = 1,
    shard_bytes: int = DEFAULT_SHARD_BYTES,
    replica_store: Optional[ShardReplicaStore] = None,
    server: Optional[FabricServer] = None,
    chunk_bytes: int = DEFAULT_SHARD_BYTES,
    timeout: float = 120.0,
    chaos=None,
    on_leaf: Optional[Callable[[int, np.ndarray], None]] = None,
    max_streams: int = 8,
) -> TransferResult:
    """``_fabric_restore`` + telemetry publication (mirrors
    ``transfer.stream_restore``'s split).  When the shared gather
    routes the restore to PR 2's single-source stream instead, that
    engine publishes its own stats and this wrapper stays silent."""
    result = _fabric_restore(
        fabric,
        template_leaves,
        ckpt,
        rows=rows,
        k=k,
        shard_bytes=shard_bytes,
        replica_store=replica_store,
        server=server,
        chunk_bytes=chunk_bytes,
        timeout=timeout,
        chaos=chaos,
        on_leaf=on_leaf,
        max_streams=max_streams,
    )
    s = result.stats
    if s.mode != "fabric":
        return result  # init/local, or the PR 2 stream published already
    from edl_tpu import telemetry

    reg = telemetry.get_registry()
    if s.bytes_sent:
        reg.counter("edl_fabric_bytes_sent_total").inc(s.bytes_sent)
    if s.bytes_received:
        reg.counter("edl_fabric_bytes_received_total").inc(s.bytes_received)
    if s.per_peer:
        reg.gauge("edl_fabric_pull_peers").set(len(s.per_peer))
    if s.shard_fallbacks:
        reg.counter("edl_fabric_shard_fallbacks_total").inc(
            s.shard_fallbacks
        )
    reg.histogram("edl_fabric_pull_seconds").observe(s.seconds)
    telemetry.get_recorder().record(
        "fabric.pull",
        {
            "mode": s.mode,
            "step": s.step,
            "bytes_received": s.bytes_received,
            "bytes_sent": s.bytes_sent,
            "peers": sorted(s.per_peer or ()),
            "shard_fallbacks": s.shard_fallbacks,
            "leaves_received": s.leaves_received,
            "leaves_skipped": s.leaves_skipped,
        },
        step=s.step,
        timing={"seconds": round(s.seconds, 6)},
    )
    return result


def _fabric_restore(
    fabric,
    template_leaves: Sequence[Any],
    ckpt: Optional[HostCheckpoint],
    *,
    rows: Optional[Sequence[int]] = None,
    k: int = 1,
    shard_bytes: int = DEFAULT_SHARD_BYTES,
    replica_store: Optional[ShardReplicaStore] = None,
    server: Optional[FabricServer] = None,
    chunk_bytes: int = DEFAULT_SHARD_BYTES,
    timeout: float = 120.0,
    chaos=None,
    on_leaf: Optional[Callable[[int, np.ndarray], None]] = None,
    max_streams: int = 8,
) -> TransferResult:
    """Agree on one state at shard granularity; move the deltas from
    MANY peers in parallel.

    Every member of the world must call this in the same resize (the
    agreement is an all-gather, exactly like ``stream_restore`` — and
    when the shared gather shows no multi-peer coverage, every member
    deterministically hands the restore to ``stream_restore``, so the
    collectives stay paired in both branches)."""
    t0 = time.perf_counter()
    sizes = _leaf_sizes(template_leaves)
    n = len(sizes)
    layout = ShardLayout.build(
        sizes, fabric.world, k=k, shard_bytes=shard_bytes, rows=rows
    )
    m = len(layout.shards)

    # -- what do I hold? -----------------------------------------------------
    usable = []  # leaves of my ckpt that structurally match the template
    if ckpt is not None and len(ckpt.leaves) == n:
        usable = [
            i for i in range(n) if ckpt.leaves[i].nbytes == sizes[i]
        ]
    full = ckpt is not None and len(usable) == n
    rep_step = replica_store.newest_step() if replica_store is not None else -1
    ck_step = int(ckpt.step) if ckpt is not None and usable else -1
    adv_step = max(ck_step, rep_step)
    have = adv_step >= 0
    full_at_adv = full and ck_step == adv_step

    vec = np.full(_SUMMARY_HDR + n + m, _NO_LEAF, np.int64)
    vec[0] = _MSG_FABRIC_AGREE
    vec[1] = 1 if have else 0
    vec[2] = adv_step if have else -1
    vec[3] = int(ckpt.digest()) if full_at_adv else -1
    vec[4] = _ip_to_int(getattr(fabric, "advertise_host", "127.0.0.1"))

    ephemeral = None
    if have and server is None:
        # Ephemeral endpoint for this restore only (tests, callers
        # without a persistent server); closed after the confirm.
        my_ck = ckpt if ck_step == adv_step else None

        def lookup(step, leaf, offset, length):
            if (
                my_ck is not None
                and step == adv_step
                and leaf < len(my_ck.leaves)
                and my_ck.leaves[leaf].nbytes >= offset + length
            ):
                return byte_view(my_ck.leaves[leaf])[
                    offset : offset + length
                ]
            if replica_store is not None:
                return replica_store.get(step, leaf, offset, length)
            return None

        ingest = None
        if replica_store is not None:

            def has_bytes(step, leaf, offset, length):
                return (
                    my_ck is not None
                    and step == adv_step
                    and leaf < len(my_ck.leaves)
                    and my_ck.leaves[leaf].nbytes >= offset + length
                )

            ingest = ReplicaIngest(replica_store, has_bytes)
        ephemeral = FabricServer(
            lookup, ingest=ingest, timeout=timeout, chaos=chaos
        ).start()
        server = ephemeral
    # Advertise the endpoint even with nothing to serve yet: buddies
    # push replicas to a fresh joiner long before its first flush.
    vec[5] = server.port if server is not None else 0

    shard_crcs_mine: Dict[int, int] = {}
    if ckpt is not None and usable and ck_step == adv_step:
        digs = ckpt.shard_digests(layout)
        usable_set = set(usable)
        for s in layout.shards:
            if s.leaf in usable_set:
                shard_crcs_mine[s.index] = digs[s.index]
        if full_at_adv:
            for i, d in enumerate(ckpt.leaf_digests()):
                vec[_SUMMARY_HDR + i] = int(d)
    if replica_store is not None and rep_step == adv_step:
        by_range = {
            (s.leaf, s.offset, s.length): s.index for s in layout.shards
        }
        for leaf, off, length, crc in replica_store.shards_at(adv_step):
            idx = by_range.get((leaf, off, length))
            if idx is not None and idx not in shard_crcs_mine:
                shard_crcs_mine[idx] = crc
    for idx, crc in shard_crcs_mine.items():
        vec[_SUMMARY_HDR + n + idx] = int(crc)

    pull_sent0 = server.pull_bytes_sent if server is not None else 0

    def cleanup():
        if ephemeral is not None:
            ephemeral.stop()

    try:
        world = _gather(fabric, vec, _MSG_FABRIC_AGREE)
    except TransferError:
        cleanup()
        raise
    W = world.shape[0]
    haves, steps = world[:, 1], world[:, 2]
    peer_addrs = {
        r: (_int_to_ip(world[r, 4]), int(world[r, 5]))
        for r in range(W)
        if int(world[r, 5]) > 0
    }

    if not haves.any():
        cleanup()
        return TransferResult(
            stats=TransferStats(mode="init"), peer_addrs=peer_addrs
        )

    agreed = int(steps.max())
    at_step = [r for r in range(W) if haves[r] and int(steps[r]) == agreed]
    leaf_adv = world[:, _SUMMARY_HDR : _SUMMARY_HDR + n]
    shard_adv = world[:, _SUMMARY_HDR + n :]
    full_ranks = [r for r in at_step if int(world[r, 3]) != _NO_LEAF]
    auth = min(full_ranks) if full_ranks else min(at_step)
    order = [auth] + [r for r in at_step if r != auth]
    reference: List[int] = []
    for s in range(m):
        reference.append(
            next(
                (
                    int(shard_adv[r, s])
                    for r in order
                    if int(shard_adv[r, s]) != _NO_LEAF
                ),
                _NO_LEAF,
            )
        )
    holders: List[List[int]] = [
        [r for r in at_step if int(shard_adv[r, s]) == reference[s]]
        if reference[s] != _NO_LEAF
        else []
        for s in range(m)
    ]
    needs: Dict[int, List[int]] = {}
    for r in range(W):
        miss = [s for s in range(m) if int(shard_adv[r, s]) != reference[s]]
        if miss:
            needs[r] = miss

    me = fabric.rank
    stats = TransferStats(
        mode="fabric",
        source_rank=auth,
        step=agreed,
        bytes_scheduled=sum(
            layout.shards[s].length
            for miss in needs.values()
            for s in miss
        ),
    )
    usable_set = set(usable)
    my_digs: Optional[List[int]] = None
    if ckpt is not None and usable:
        my_digs = ckpt.shard_digests(layout)

    def local_shard_ok(sh: Shard) -> bool:
        """My checkpoint's bytes for ``sh`` provably equal the agreed
        reference: same step, or — PR 2's step-agnostic delta keep at
        shard granularity — the shard crc matches the reference crc
        (the SAME trust basis the needs matrix was built on; without
        this, a member one step behind re-pulls bytes the agreement
        just proved identical)."""
        if ck_step == agreed:
            return True
        return (
            my_digs is not None
            and sh.index < len(reference)
            and reference[sh.index] != _NO_LEAF
            and my_digs[sh.index] == reference[sh.index]
        )

    def local_bytes(leaf: int, sh: Shard):
        """Bytes this member holds for ``sh`` at the agreed step —
        from its full checkpoint copy or the buddy-replica store."""
        if ckpt is not None and leaf in usable_set and local_shard_ok(sh):
            return byte_view(ckpt.leaves[leaf])[
                sh.offset : sh.offset + sh.length
            ]
        if replica_store is not None:
            hit = replica_store.get(agreed, leaf, sh.offset, sh.length)
            if hit is not None:
                return byte_view(hit)
        return None

    def assemble_leaf(leaf: int) -> np.ndarray:
        """A full leaf rebuilt from locally held shards (a partial /
        replica-only holder has the bytes but not the numpy leaf)."""
        t = template_leaves[leaf]
        buf = np.empty(t.shape, np.dtype(t.dtype))
        view = byte_view(buf)
        for sh in layout.by_leaf.get(leaf, []):
            src = local_bytes(leaf, sh)
            if src is None:
                raise TransferError(
                    f"fabric restore: advertised shard of leaf {leaf} "
                    "vanished before assembly (pruned store?); holding"
                )
            view[sh.offset : sh.offset + sh.length] = src
        return buf

    def degrade_unrestorable():
        """The agreed step has no full coverage anywhere — retrying
        the identical agreement can never succeed.  Drop this
        member's replica bytes at that step (every member reaches
        this from the same matrix, so all drop together) and the
        retry degrades to the newest FULL checkpoint step."""
        dropped = 0
        if replica_store is not None and rep_step == agreed:
            dropped = replica_store.drop_step(agreed)
        _record_degrade(agreed, dropped, "coverage gap at agreed step")

    if not needs:
        cleanup()
        if any(r == _NO_LEAF for r in reference) and m > 0:
            # Everyone advertises the identical PARTIAL coverage:
            # nothing to move, but nobody can assemble a full state
            # either — degrade and hold for the retry.
            degrade_unrestorable()
            raise TransferError(
                "fabric restore: identical partial coverage on every "
                "member (no holder for some shards); holding"
            )
        stats.mode = "local"
        stats.leaves_skipped = n
        if full_at_adv or n == 0:
            leaves_out = None if ckpt is None else list(ckpt.leaves)
        else:
            # Partial / replica-only holder whose coverage matches the
            # reference completely: rebuild full leaves locally —
            # returning the (absent) checkpoint's leaves here handed
            # the caller Nones AFTER a clean agreement.
            leaves_out = [
                ckpt.leaves[i]
                if ckpt is not None
                and i in usable_set
                and ck_step == agreed
                else assemble_leaf(i)
                for i in range(n)
            ]
        stats.seconds = time.perf_counter() - t0
        auth_leaves = [int(d) for d in leaf_adv[auth]]
        return TransferResult(
            stats=stats,
            leaves=leaves_out,
            leaf_digests=auth_leaves if full_ranks else None,
            peer_addrs=peer_addrs,
        )

    all_needed = sorted({s for miss in needs.values() for s in miss})
    gap = {s for s in all_needed if not holders[s]}
    if not full_ranks and m > 0:
        # Without a full holder EVERY member must assemble every
        # shard, so one that NOBODY advertised is a gap even though
        # it appears in no needs row (holding nothing matches the
        # _NO_LEAF reference) — missing this here would defer the
        # failure to the exhausted-holder pull path, which retries
        # without degrading and livelocks on the unrestorable step.
        gap.update(s for s in range(m) if reference[s] == _NO_LEAF)
    if gap:
        cleanup()
        degrade_unrestorable()
        raise TransferError(
            f"fabric restore: {len(gap)} needed shard(s) have no holder "
            f"at the agreed step {agreed} (first: shard {min(gap)}); "
            "holding for the coordinator to re-plan"
        )
    serving_union = {r for s in all_needed for r in holders[s]}
    if len(serving_union) < 2:
        # No multi-peer coverage (2-member worlds, one lone survivor):
        # the whole restore belongs to PR 2's single-source stream.
        # Derived from the shared gather — every member takes this
        # branch together, so the stream's own agreement pairs.
        cleanup()
        if not full_ranks:
            degrade_unrestorable()
            raise TransferError(
                "fabric restore: single-holder world without a full "
                "checkpoint holder; cannot fall back to the "
                "single-source stream"
            )
        res = stream_restore(
            fabric,
            template_leaves,
            ckpt,
            chunk_bytes=chunk_bytes,
            timeout=timeout,
            chaos=chaos,
            on_leaf=on_leaf,
        )
        # The stream knows nothing of fabric endpoints: keep THIS
        # gather's addresses so small worlds still replicate/inherit.
        if res.peer_addrs is None:
            res.peer_addrs = peer_addrs
        return res

    # -- the parallel pull ---------------------------------------------------
    import queue

    mine = needs.get(me, [])
    my_ok = True
    fail_reason = ""
    bufs: Dict[int, np.ndarray] = {}
    leaf_pending: Dict[int, int] = {}
    place_q: "queue.Queue" = queue.Queue()
    place_errors: List[BaseException] = []
    placed_lock = threading.Lock()

    def placer():
        while True:
            item = place_q.get()
            if item is None:
                return
            if place_errors:
                continue
            try:
                on_leaf(item, bufs[item])
            except BaseException as e:  # noqa: BLE001 - re-raised below
                place_errors.append(e)

    #: full holders at the agreed step pull nothing and reuse their
    #: checkpoint leaves verbatim; EVERY other member — receivers AND
    #: partial/replica-only holders with nothing to pull — must
    #: assemble real leaf buffers (returning an absent checkpoint's
    #: leaves would hand the caller Nones after a clean confirm)
    assembling = bool(mine) or not full_at_adv
    place_thread = None
    if on_leaf is not None and assembling:
        place_thread = threading.Thread(
            target=placer, daemon=True, name="edl-fabric-place"
        )
        place_thread.start()

    if assembling:
        mine_set = set(mine)
        pull_by_leaf: Dict[int, List[Shard]] = {}
        for s in mine:
            sh = layout.shards[s]
            pull_by_leaf.setdefault(sh.leaf, []).append(sh)
        reused: List[int] = []
        for leaf in range(n):
            shs = pull_by_leaf.get(leaf, [])
            if (
                not shs
                and ckpt is not None
                and leaf in usable_set
                and all(
                    local_shard_ok(sh2)
                    for sh2 in layout.by_leaf.get(leaf, [])
                )
            ):
                # Every shard of this leaf matched from my own full
                # checkpoint copy (same step, or crc-proven identical
                # across steps): zero-copy reuse, like PR 2.
                reused.append(leaf)
                continue
            t = template_leaves[leaf]
            buf = np.empty(t.shape, np.dtype(t.dtype))
            needed_ranges = {(sh.offset, sh.length) for sh in shs}
            # Kept regions (shards whose bytes I already hold and that
            # matched the reference) are copied in from my checkpoint
            # or the buddy-replica store.
            view = byte_view(buf)
            for sh in layout.by_leaf.get(leaf, []):
                if (sh.offset, sh.length) in needed_ranges:
                    continue
                src = local_bytes(leaf, sh)
                if src is None:
                    # Advertised it, can't find it (pruned between
                    # gather and now): re-pull it like a missing shard.
                    shs.append(sh)
                    needed_ranges.add((sh.offset, sh.length))
                    if sh.index not in mine_set:
                        mine.append(sh.index)
                        mine_set.add(sh.index)
                    continue
                view[sh.offset : sh.offset + sh.length] = src
            bufs[leaf] = buf
            leaf_pending[leaf] = len({sh.index for sh in shs})
        stats.leaves_received = len(bufs)
        stats.leaves_skipped = len(reused)
        if on_leaf is not None:
            # Reused leaves first: their device placement dispatches
            # before (and overlaps) the parallel network pull.
            for i in reused:
                on_leaf(i, ckpt.leaves[i])
        for leaf, cnt in list(leaf_pending.items()):
            if cnt == 0 and place_thread is not None:
                # Assembled purely from kept local/replica shards —
                # complete before any pull.
                place_q.put(leaf)

        ref_by_idx = {s: reference[s] for s in mine}
        pending: Dict[int, Shard] = {s: layout.shards[s] for s in mine}
        tried: Dict[int, set] = {s: set() for s in mine}
        dead_peers: set = set()
        per_peer: Dict[str, int] = {}

        def eligible(s_idx: int) -> List[int]:
            sh = layout.shards[s_idx]
            ladder = [r for r in layout.holders(sh) if r in holders[s_idx]]
            ladder += [r for r in holders[s_idx] if r not in ladder]
            return [
                r
                for r in ladder
                if r != me
                and r not in tried[s_idx]
                and r not in dead_peers
                and r in peer_addrs
            ]

        first_round = True
        while pending and my_ok:
            groups: Dict[int, List[Shard]] = {}
            load: Dict[int, int] = {}
            stuck = False
            for s_idx in sorted(pending):
                cands = eligible(s_idx)
                if not cands:
                    stuck = True
                    break
                sh = pending[s_idx]
                # Least-loaded eligible holder, owner preferred on
                # ties: wall-clock tracks state / (peers x per-NIC)
                # only when the streams stay balanced — a strict
                # owner-first rule concentrates on the few owners
                # whenever shards-per-leaf < world and wastes the
                # other holders' NICs.
                owner = layout.owner(sh)
                peer = min(
                    cands,
                    key=lambda r: (
                        load.get(r, 0),
                        0 if r == owner else 1,
                        r,
                    ),
                )
                load[peer] = load.get(peer, 0) + sh.length
                groups.setdefault(peer, []).append(sh)
            if stuck:
                my_ok = False
                fail_reason = "a needed shard exhausted every holder"
                break
            if not first_round:
                stats.shard_fallbacks += sum(
                    len(v) for v in groups.values()
                )
            first_round = False
            results: List[tuple] = []
            res_lock = threading.Lock()

            def pull(peer, shards_for_peer):
                out = _pull_from_peer(
                    peer_addrs[peer],
                    me,
                    peer,
                    agreed,
                    shards_for_peer,
                    bufs,
                    ref_by_idx,
                    chunk_bytes=chunk_bytes,
                    timeout=timeout,
                    chaos=chaos,
                )
                with res_lock:
                    results.append((peer, out))

            peers_now = sorted(groups)
            for wave_at in range(0, len(peers_now), max(1, max_streams)):
                if not my_ok:
                    # A hung stream already failed this restore's
                    # verdict: launching more waves only pulls bytes
                    # the confirm will discard while every other
                    # member waits in the confirm gather.
                    break
                wave = peers_now[wave_at : wave_at + max(1, max_streams)]
                threads = [
                    threading.Thread(
                        target=pull,
                        args=(p, groups[p]),
                        daemon=True,
                        name=f"edl-fabric-pull-r{p}",
                    )
                    for p in wave
                ]
                for t in threads:
                    t.start()
                # One SHARED deadline for the wave: the streams run
                # concurrently, so serial full-timeout joins would
                # multiply a multi-stream hang by the wave width.
                deadline = time.monotonic() + timeout + 30
                for t in threads:
                    t.join(max(0.0, deadline - time.monotonic()))
                    if t.is_alive():
                        my_ok = False
                        fail_reason = "a pull stream hung past timeout"
            for peer, (ok_shs, failed_shs, rec, chs) in results:
                stats.bytes_received += rec
                stats.chunks_received += chs
                if rec:
                    per_peer[str(peer)] = per_peer.get(str(peer), 0) + rec
                for sh in ok_shs:
                    if sh.index not in pending:
                        continue
                    del pending[sh.index]
                    with placed_lock:
                        leaf_pending[sh.leaf] -= 1
                        leaf_done = leaf_pending[sh.leaf] == 0
                    if leaf_done and place_thread is not None:
                        place_q.put(sh.leaf)
                for sh in failed_shs:
                    tried[sh.index].add(peer)
                if failed_shs and not ok_shs and rec == 0:
                    # Connection-level failure (refused / died before
                    # any payload) marks the peer dead for THIS
                    # restore; a torn shard from an otherwise healthy
                    # peer only burns that shard's tried-set.
                    dead_peers.add(peer)
        stats.per_peer = per_peer
    else:
        # Nothing to pull: serve (the server thread is already doing
        # that) and hand local leaves to placement like PR 2's source.
        stats.leaves_skipped = n
        if on_leaf is not None and ckpt is not None and full_at_adv:
            for i, leaf in enumerate(ckpt.leaves):
                on_leaf(i, leaf)

    if place_thread is not None:
        place_q.put(None)
        place_thread.join(timeout)
        if place_thread.is_alive():
            my_ok = False
            fail_reason = "leaf placement still running after timeout"
    if place_errors:
        cleanup()
        raise place_errors[0]

    # -- world-consistent verdict -------------------------------------------
    vec2 = np.zeros(_SUMMARY_HDR + n + m, np.int64)
    vec2[0] = _MSG_FABRIC_CONFIRM
    vec2[1] = 1 if my_ok else 0
    try:
        ok_col = _gather(fabric, vec2, _MSG_FABRIC_CONFIRM)[:, 1]
    finally:
        if server is not None:
            stats.bytes_sent = server.pull_bytes_sent - pull_sent0
        cleanup()
    if not ok_col.all():
        bad = [r for r in range(len(ok_col)) if not ok_col[r]]
        mine_msg = f" (this member: {fail_reason})" if fail_reason else ""
        raise TornTransferError(
            f"fabric restore: member(s) {bad} could not assemble a "
            f"verified state{mine_msg}: no member adopts; resize retries"
        )

    leaves = [
        bufs[i]
        if i in bufs
        else (ckpt.leaves[i] if ckpt is not None else None)
        for i in range(n)
    ]
    auth_leaf_digests = (
        [int(d) for d in leaf_adv[auth]] if full_ranks else None
    )
    stats.seconds = time.perf_counter() - t0
    return TransferResult(
        stats=stats,
        leaves=leaves,
        leaf_digests=auth_leaf_digests,
        peer_addrs=peer_addrs,
    )
