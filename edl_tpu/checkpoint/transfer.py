"""Streaming delta-aware restore transfer — the joiner recovery path.

Retires the monolithic ``broadcast_one_to_all`` restore (BENCH_r05:
25.5s for 728MB across 2 processes, vs 0.066s local restore).  That
path moved EVERY leaf to EVERY member through an XLA psum — each side
paying a zeros template, device staging copies, and a full-state
``np.asarray`` — even when the receivers already held most of the
bytes.  In-memory checkpointing systems (Gemini SOSP'23, CheckFreq
FAST'21) structure peer recovery traffic the opposite way: chunked,
overlapped, and minimized to what the joiner actually lacks.  So:

1. **Delta-aware agreement.**  Members all-gather a tiny int64 vector:
   (msg-tag, have, step, digest, ip, port) + one crc32 PER LEAF
   (``HostCheckpoint.leaf_digests``).  Everyone derives the same
   source (newest checkpoint, ties to lowest rank) and the same
   need-matrix: member r needs leaf i iff its leaf digest differs from
   the source's.  A graceful resize with one fresh joiner therefore
   moves only the joiner's missing leaves; a partially-diverged store
   moves only the diverged leaves; identical stores move nothing.
2. **Chunked pipelined transfer.**  State bytes move over plain TCP
   between hosts (recovery traffic belongs on DCN, not inside an XLA
   collective), in fixed-size chunks (default 64MB).  The source
   serves each receiver from a background thread and sends only that
   receiver's missing leaves; the receiver ``recv_into``s straight
   into the destination leaf buffer and hands each completed leaf to
   ``on_leaf`` immediately, so device placement of received leaves
   overlaps the remaining network transfer.  Peak host memory is ~1x
   state + socket buffers (the old path peaked near 3x).
3. **Zero-copy adoption.**  No zeros template, no post-transfer
   ``np.asarray`` pass, no re-hash: every chunk carries a crc32 the
   receiver verifies on arrival, so the assembled checkpoint adopts
   the source's advertised digests directly
   (``HostCheckpoint.adopt_digests``) and feeds PR 1's
   corruption-fallback machinery (``verify``/``latest_verified``)
   unchanged.  A torn chunk surfaces as ``TornTransferError`` after
   the stream drains (collective-safe: the socket is consumed either
   way) and the caller degrades to the next-oldest verified snapshot
   instead of poisoning the joiner.

Chaos: ``transfer.chunk.torn`` flips a byte in a received chunk before
its CRC check; ``transfer.chunk.slow`` stalls the source before one
chunk send (``chaos/schedule.KNOWN_POINTS``).

The collective fabric is abstracted (``JaxProcessFabric`` over
``multihost_utils.process_allgather`` in production;
``LoopbackWorld`` barriers N threads in-process for tests) but the
TCP data plane is the REAL one in both — unit tests count actual
bytes on the wire.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from edl_tpu.checkpoint.hostdram import HostCheckpoint

#: default transfer chunk: large enough that header/CRC overhead is
#: noise, small enough that placement overlap is fine-grained and the
#: staging cost stays "one chunk", not "one state".
DEFAULT_CHUNK_BYTES = 64 << 20

#: wire protocol magic (hello + chunk headers); bump on layout change.
_MAGIC = 0xED15_7EA3

#: chunk header: magic u32, leaf u32, offset u64, length u64, crc u32.
_CHUNK_HDR = struct.Struct("<IIQQI")
#: receiver hello: magic u32, rank u32.
_HELLO = struct.Struct("<II")
#: leaf sentinel marking end-of-stream.
_DONE_LEAF = 0xFFFF_FFFF

#: agreement vector layout: [msg, have, step, digest, ip, port,
#: crc_0..n-1].  The confirmation round gathers the SAME-SHAPE vector
#: with a different msg tag: collectives pair positionally, so if one
#: member fails early and retries a fresh agreement while a peer still
#: sits in the previous round's confirmation, the rows pair up instead
#: of shape-exploding — and the tag check turns the desync into a
#: typed, retryable TransferError on every member that sees it.
_SUMMARY_HDR = 6
_MSG_AGREE = 101
_MSG_CONFIRM = 102
#: leaf-digest slot for "I cannot supply/skip this leaf" (no
#: checkpoint, leaf count/size mismatch): never equals a real crc32.
_NO_LEAF = -1


class TransferError(RuntimeError):
    """Restore transfer failed (peer unreachable, protocol violation).
    The caller's normal broken-world machinery handles it: the resize
    fails, the coordinator re-plans, the transfer re-runs."""


class TornTransferError(TransferError):
    """Some member's received chunks failed their CRC.  Raised on
    EVERY member (a post-transfer confirmation all-gather makes the
    verdict world-consistent): nobody adopts the assembled state, the
    resize attempt fails as one unit, and the caller holds-and-retries
    — a fresh agreement re-runs ``latest_verified`` on the source, so
    genuine source-side corruption degrades the WHOLE world to the
    next-oldest verified snapshot together, while a transient wire
    flip simply re-transfers.  A lone member quietly restoring an
    older step instead would diverge the step counter across a live
    world and hang the next collective."""


@dataclass
class TransferStats:
    """What the restore agreement decided and what actually moved."""

    #: "init" (nobody has state), "local" (identical bytes everywhere,
    #: nothing moves), "delta" (the streaming transfer ran), "fabric"
    #: (the sharded multi-peer fabric ran — checkpoint/fabric.py)
    mode: str
    source_rank: int = -1
    step: int = -1
    #: total payload the agreement scheduled across ALL receivers
    bytes_scheduled: int = 0
    #: payload bytes THIS member pushed onto / pulled off the wire
    bytes_sent: int = 0
    bytes_received: int = 0
    leaves_received: int = 0
    #: leaves this member already held with source-matching bytes
    leaves_skipped: int = 0
    chunks_received: int = 0
    seconds: float = 0.0
    #: fabric pulls: payload bytes received per SOURCE rank (str keys
    #: so the dict JSON-serializes straight into ResizeEvent.transfer
    #: — the per-peer wire accounting the "no single peer sends full
    #: state" claim is asserted on)
    per_peer: Optional[Dict[str, int]] = None
    #: fabric pulls: shards re-pulled from another replica holder
    #: after their preferred peer died or served torn bytes
    shard_fallbacks: int = 0


@dataclass
class TransferResult:
    stats: TransferStats
    #: assembled leaves (local where digests matched, received
    #: elsewhere); None for mode "init"
    leaves: Optional[List[np.ndarray]] = None
    #: the source's advertised per-leaf digests (for zero-copy
    #: adoption); None for mode "init"
    leaf_digests: Optional[List[int]] = None
    #: fabric agreements: every member's advertised fabric-server
    #: address, rank -> (ip, port) — cached by the caller so the
    #: post-flush background replication can reach its buddies without
    #: another gather
    peer_addrs: Optional[Dict[int, tuple]] = None


# ---------------------------------------------------------------------------
# collective fabrics (the tiny agreement round; bulk data never rides these)
# ---------------------------------------------------------------------------


class JaxProcessFabric:
    """Agreement fabric over the live ``jax.distributed`` world."""

    def __init__(self, advertise_host: str = "127.0.0.1"):
        import jax

        self.rank = jax.process_index()
        self.world = jax.process_count()
        self.advertise_host = advertise_host or "127.0.0.1"

    def allgather(self, vec: np.ndarray) -> np.ndarray:
        from jax.experimental import multihost_utils

        # The gather rides a jitted identity, and without x64 JAX
        # canonicalizes int64 inputs to int32 — which would truncate
        # crc32/ip values above 2^31 (observed: adopt_digests blowing
        # up on a negative "crc").  uint8 bytes round-trip exactly.
        raw = np.ascontiguousarray(vec, np.int64).view(np.uint8)
        out = np.asarray(multihost_utils.process_allgather(raw))
        return np.ascontiguousarray(out).view(np.int64)


class LoopbackWorld:
    """N in-process "members" sharing a barrier-based allgather — the
    test fabric.  The TCP data plane stays real (127.0.0.1), so wire
    accounting in tests measures the production transport."""

    def __init__(self, world: int):
        self.world = world
        self._barrier = threading.Barrier(world)
        self._slots: List[Optional[np.ndarray]] = [None] * world
        self._lock = threading.Lock()

    def fabric(self, rank: int) -> "LoopbackFabric":
        return LoopbackFabric(self, rank)


class LoopbackFabric:
    def __init__(self, world: LoopbackWorld, rank: int):
        self._world = world
        self.rank = rank
        self.world = world.world
        self.advertise_host = "127.0.0.1"

    def allgather(self, vec: np.ndarray) -> np.ndarray:
        w = self._world
        with w._lock:
            w._slots[self.rank] = np.asarray(vec)
        w._barrier.wait(timeout=120)
        with w._lock:
            out = np.stack([np.asarray(s) for s in w._slots])
        # Second barrier: nobody may reuse the slots for a subsequent
        # gather until everyone has read this one.
        w._barrier.wait(timeout=120)
        return out


# ---------------------------------------------------------------------------
# agreement
# ---------------------------------------------------------------------------


def _ip_to_int(host: str) -> int:
    """IPv4 (dotted or resolvable name) -> u32 for the int64 agreement
    vector; unresolvable names degrade to loopback (single-host runs —
    the only place an unresolvable advertise host can work anyway)."""
    try:
        return struct.unpack("!I", socket.inet_aton(host))[0]
    except OSError:
        try:
            return struct.unpack(
                "!I", socket.inet_aton(socket.gethostbyname(host))
            )[0]
        except OSError:
            return struct.unpack("!I", socket.inet_aton("127.0.0.1"))[0]


def _int_to_ip(ip: int) -> str:
    return socket.inet_ntoa(struct.pack("!I", int(ip)))


def _leaf_sizes(template_leaves: Sequence[Any]) -> List[int]:
    out = []
    for t in template_leaves:
        n = 1
        for s in t.shape:
            n *= int(s)
        out.append(n * np.dtype(t.dtype).itemsize)
    return out


def _summary(
    ckpt: Optional[HostCheckpoint],
    sizes: List[int],
    ip: int,
    port: int,
) -> np.ndarray:
    """This member's agreement vector.  A leaf digest is advertised
    only when the local leaf's byte size matches the model template —
    a structurally incompatible checkpoint can neither skip nor source
    a leaf."""
    n = len(sizes)
    vec = np.full(_SUMMARY_HDR + n, _NO_LEAF, np.int64)
    vec[0] = _MSG_AGREE
    vec[1] = 0 if ckpt is None else 1
    vec[2] = -1 if ckpt is None else int(ckpt.step)
    vec[3] = -1 if ckpt is None else int(ckpt.digest())
    vec[4] = ip
    vec[5] = port
    if ckpt is not None and len(ckpt.leaves) == n:
        digs = ckpt.leaf_digests()
        for i, (leaf, dig) in enumerate(zip(ckpt.leaves, digs)):
            if leaf.nbytes == sizes[i]:
                vec[_SUMMARY_HDR + i] = int(dig)
    return vec


def _gather(fabric, vec: np.ndarray, expect_msg: int) -> np.ndarray:
    """One agreement-fabric all-gather, hardened: any collective
    failure (world torn down mid-gather, peer process death) and any
    round desync (a row tagged with the WRONG message type — a peer
    retrying a fresh agreement while we sit in the previous round's
    confirmation, or vice versa) surfaces as a typed TransferError the
    caller holds-and-retries on, never a raw collective exception or
    silently mispaired data."""
    try:
        world = fabric.allgather(vec)
    except TransferError:
        raise
    except Exception as e:  # noqa: BLE001 - typed boundary
        raise TransferError(
            f"restore transfer: agreement gather failed: {e}"
        ) from e
    if world.ndim != 2 or world.shape[1] != len(vec) or not (
        world[:, 0] == expect_msg
    ).all():
        raise TransferError(
            "restore transfer: agreement round desync (a member "
            "restarted the protocol mid-round); retrying the resize"
        )
    return world


# ---------------------------------------------------------------------------
# TCP data plane
# ---------------------------------------------------------------------------


def tune_socket(sock: socket.socket) -> None:
    """Bulk-transfer socket tuning: no Nagle (chunk headers must not
    wait behind payload), generous kernel buffers (64MB application
    chunks over default ~200KB buffers thrash context switches).
    Shared with the live KV migration stream (serving/migrate.py),
    which moves filled cache blocks over the same chunked wire."""
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 4 << 20)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 4 << 20)
    except OSError:  # pragma: no cover - platform-dependent caps
        pass


_tune = tune_socket


def _recv_exact(sock: socket.socket, view: memoryview) -> None:
    got = 0
    while got < len(view):
        n = sock.recv_into(view[got:], len(view) - got)
        if n == 0:
            raise TransferError("restore transfer peer closed mid-stream")
        got += n


def _serve_receiver(
    conn: socket.socket,
    ckpt: HostCheckpoint,
    need: List[int],
    chunk_bytes: int,
    chaos,
    stats: TransferStats,
    stats_lock: threading.Lock,
) -> None:
    """Stream one receiver's missing leaves over ``conn``.  Runs on a
    daemon thread so all receivers are served concurrently; the
    checkpoint leaves are immutable numpy.  ``stats.bytes_sent``
    counts bytes actually handed to the socket (under the lock —
    several receiver threads share the counter), so the source's
    telemetry reports real traffic, not the schedule."""
    try:
        with conn:
            for i in need:
                buf = np.ascontiguousarray(ckpt.leaves[i])
                mv = memoryview(buf).cast("B")
                nbytes = len(mv)
                off = 0
                while off < nbytes or (nbytes == 0 and off == 0):
                    part = mv[off : off + chunk_bytes]
                    if chaos is not None:
                        # chaos[transfer.chunk.slow]: a stalled DCN
                        # link — one chunk send delayed by arg seconds
                        # (restore must survive slow peers, not just
                        # dead ones).
                        for ev in chaos.due("transfer.chunk.slow"):
                            time.sleep(float(ev.arg or 0.05))
                    conn.sendall(
                        _CHUNK_HDR.pack(
                            _MAGIC, i, off, len(part), zlib.crc32(part)
                        )
                    )
                    conn.sendall(part)
                    with stats_lock:
                        stats.bytes_sent += len(part)
                    off += len(part)
                    if nbytes == 0:
                        break
            conn.sendall(_CHUNK_HDR.pack(_MAGIC, _DONE_LEAF, 0, 0, 0))
    except OSError:
        # The receiver died mid-pull: ITS resize fails and retries
        # through the coordinator; the source must not care.
        pass


def _serve(
    srv: socket.socket,
    ckpt: HostCheckpoint,
    needs: Dict[int, List[int]],
    chunk_bytes: int,
    timeout: float,
    chaos,
    stats: TransferStats,
    stats_lock: threading.Lock,
) -> None:
    """Source accept loop (background): serve every receiver rank in
    ``needs`` concurrently, then close.  A receiver that never
    connects within ``timeout`` is abandoned — its failed resize is
    the coordinator's problem, and blocking the source's accept loop
    on it would turn one dead joiner into a stalled survivor."""

    def loop():
        expected = set(needs)
        threads = []
        srv.settimeout(timeout)
        try:
            while expected:
                try:
                    conn, _ = srv.accept()
                except (socket.timeout, OSError):
                    break
                try:
                    hello = bytearray(_HELLO.size)
                    conn.settimeout(timeout)
                    _tune(conn)
                    _recv_exact(conn, memoryview(hello))
                    magic, rank = _HELLO.unpack(bytes(hello))
                    if magic != _MAGIC or rank not in expected:
                        conn.close()
                        continue
                except (TransferError, OSError, struct.error):
                    conn.close()
                    continue
                expected.discard(rank)
                t = threading.Thread(
                    target=_serve_receiver,
                    args=(
                        conn, ckpt, needs[rank], chunk_bytes, chaos,
                        stats, stats_lock,
                    ),
                    daemon=True,
                    name=f"edl-restore-send-r{rank}",
                )
                t.start()
                threads.append(t)
            for t in threads:
                t.join(timeout)
        finally:
            srv.close()

    threading.Thread(
        target=loop, daemon=True, name="edl-restore-serve"
    ).start()


def _receive(
    host: str,
    port: int,
    rank: int,
    need: List[int],
    template_leaves: Sequence[Any],
    sizes: List[int],
    src_digests: List[int],
    timeout: float,
    chaos,
    on_leaf: Optional[Callable[[int, np.ndarray], None]],
    stats: TransferStats,
):
    """Pull this member's missing leaves from the source.  Buffers are
    allocated once per needed leaf and filled in place
    (``recv_into``); completed leaves go to ``on_leaf`` the moment
    their last chunk lands — on a dedicated placement thread, so the
    socket keeps draining at wire speed while device placement runs
    (inline placement would stall the source whenever the kernel
    buffers filled, serializing wire and placement instead of
    overlapping them).  CRC failures are recorded and the stream still
    drains to the DONE marker — tearing the connection down early
    would turn one flipped bit into a source-side error too.  Returns
    (buffers, torn-leaf set); torn leaves never reach ``on_leaf``."""
    import queue

    bufs = {
        i: np.empty(template_leaves[i].shape, np.dtype(template_leaves[i].dtype))
        for i in need
    }
    got = {i: 0 for i in need}
    #: running crc32 per leaf, chained across its in-order chunks: the
    #: completed leaf is checked against the SOURCE'S ADVERTISED digest
    #: (from the agreement), not just the per-chunk CRCs the source
    #: computed at send time — so source-side rot between its
    #: latest_verified() hash pass and the send is caught here, before
    #: adoption, instead of at the NEXT resize's re-hash.
    leaf_crc = {i: 0 for i in need}
    torn: set = set()

    place_q: "queue.Queue" = queue.Queue()
    place_errors: List[BaseException] = []

    def placer():
        while True:
            item = place_q.get()
            if item is None:
                return
            if place_errors:
                continue  # drain; the first error already aborts adoption
            try:
                on_leaf(item, bufs[item])
            except BaseException as e:  # noqa: BLE001 - re-raised below
                place_errors.append(e)

    place_thread = None
    if on_leaf is not None:
        place_thread = threading.Thread(
            target=placer, daemon=True, name="edl-restore-place"
        )
        place_thread.start()
    try:
        try:
            conn = socket.create_connection((host, port), timeout=timeout)
        except OSError as e:
            raise TransferError(
                f"restore transfer: cannot reach source {host}:{port}: {e}"
            ) from e
        try:
            with conn:
                conn.settimeout(timeout)
                _tune(conn)
                conn.sendall(_HELLO.pack(_MAGIC, rank))
                hdr = bytearray(_CHUNK_HDR.size)
                while True:
                    _recv_exact(conn, memoryview(hdr))
                    magic, leaf, off, length, crc = _CHUNK_HDR.unpack(
                        bytes(hdr)
                    )
                    if magic != _MAGIC:
                        raise TransferError(
                            "restore transfer: bad chunk magic"
                        )
                    if leaf == _DONE_LEAF:
                        break
                    if leaf not in bufs or off + length > sizes[leaf]:
                        raise TransferError(
                            f"restore transfer: chunk outside leaf {leaf} "
                            f"bounds (off={off} len={length})"
                        )
                    if off != got[leaf]:
                        raise TransferError(
                            f"restore transfer: out-of-order chunk for "
                            f"leaf {leaf} (off={off}, have {got[leaf]})"
                        )
                    region = memoryview(bufs[leaf]).cast("B")[
                        off : off + length
                    ]
                    _recv_exact(conn, region)
                    if chaos is not None and length > 0:
                        # chaos[transfer.chunk.torn]: a bit flip on
                        # the wire — the CRCs below must catch it and
                        # the restore must degrade, not adopt poisoned
                        # bytes.
                        for _ in chaos.due("transfer.chunk.torn"):
                            region[0] ^= 0xFF
                    if zlib.crc32(region) != crc:
                        torn.add(leaf)
                    leaf_crc[leaf] = zlib.crc32(region, leaf_crc[leaf])
                    stats.chunks_received += 1
                    stats.bytes_received += length
                    got[leaf] += length
                    if got[leaf] == sizes[leaf]:
                        if leaf_crc[leaf] != src_digests[leaf]:
                            torn.add(leaf)
                        if leaf not in torn:
                            stats.leaves_received += 1
                            if place_thread is not None:
                                place_q.put(leaf)
        except TransferError:
            raise
        except OSError as e:
            # socket.timeout and friends: a stalled/dead source must
            # surface as the transfer's typed error (the caller holds
            # and retries), not as a raw socket exception.
            raise TransferError(
                f"restore transfer: stream from {host}:{port} failed: {e}"
            ) from e
    finally:
        if place_thread is not None:
            place_q.put(None)
            place_thread.join(timeout)
    if place_thread is not None and place_thread.is_alive():
        raise TransferError(
            f"restore transfer: leaf placement still running after "
            f"{timeout}s drain timeout"
        )
    if place_errors:
        raise place_errors[0]
    short = [i for i in need if got[i] != sizes[i]]
    if short:
        raise TransferError(
            f"restore transfer: source closed with {len(short)} leaves "
            f"incomplete (first: leaf {short[0]}, "
            f"{got[short[0]]}/{sizes[short[0]]} bytes)"
        )
    # Torn (CRC-failed) leaves are NOT raised here: the stream drained
    # cleanly, and the verdict must be made world-consistent by the
    # confirmation all-gather in stream_restore before anyone acts.
    return bufs, torn


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------


def stream_restore(
    fabric,
    template_leaves: Sequence[Any],
    ckpt: Optional[HostCheckpoint],
    *,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    timeout: float = 120.0,
    chaos=None,
    on_leaf: Optional[Callable[[int, np.ndarray], None]] = None,
) -> TransferResult:
    """``_stream_restore`` + telemetry publication: the engine's final
    stats land in the metrics registry (wire-byte counters, the
    ``edl_transfer_seconds`` histogram) and the flight recorder, so a
    resize's transfer cost is visible on ``/metrics`` and every
    transfer is journaled for post-mortems."""
    result = _stream_restore(
        fabric,
        template_leaves,
        ckpt,
        chunk_bytes=chunk_bytes,
        timeout=timeout,
        chaos=chaos,
        on_leaf=on_leaf,
    )
    from edl_tpu import telemetry

    reg = telemetry.get_registry()
    s = result.stats
    if s.bytes_sent:
        reg.counter("edl_transfer_bytes_sent_total").inc(s.bytes_sent)
    if s.bytes_received:
        reg.counter("edl_transfer_bytes_received_total").inc(
            s.bytes_received
        )
    if s.chunks_received:
        reg.counter("edl_transfer_chunks_total").inc(s.chunks_received)
    if s.leaves_skipped:
        reg.counter("edl_transfer_leaves_skipped_total").inc(
            s.leaves_skipped
        )
    reg.histogram("edl_transfer_seconds").observe(s.seconds)
    telemetry.get_recorder().record(
        "transfer",
        {
            "mode": s.mode,
            "source_rank": s.source_rank,
            "step": s.step,
            "bytes_scheduled": s.bytes_scheduled,
            "bytes_sent": s.bytes_sent,
            "bytes_received": s.bytes_received,
            "leaves_received": s.leaves_received,
            "leaves_skipped": s.leaves_skipped,
        },
        step=s.step,
        timing={"seconds": round(s.seconds, 6)},
    )
    return result


def _stream_restore(
    fabric,
    template_leaves: Sequence[Any],
    ckpt: Optional[HostCheckpoint],
    *,
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
    timeout: float = 120.0,
    chaos=None,
    on_leaf: Optional[Callable[[int, np.ndarray], None]] = None,
) -> TransferResult:
    """Agree on one state across the world and move only the deltas.

    ``fabric``: agreement transport (rank, world, allgather,
    advertise_host).  ``template_leaves``: the model's abstract state
    leaves (shape/dtype), the shared schema every member's buffers and
    sizes derive from.  ``ckpt``: this member's newest verified local
    checkpoint, or None (a joiner).  ``on_leaf(i, arr)``: called for
    every leaf of the agreed state as it becomes available — local
    (digest-matched) leaves immediately after the agreement, received
    leaves the moment their last chunk lands — so the caller's device
    placement overlaps the remaining transfer.  Not called for modes
    "init"/"local", where the caller already has a better path.

    Every member of the world must call this in the same resize
    (the agreement is an all-gather).  Returns a TransferResult whose
    stats record the mode and the actual wire traffic."""
    t0 = time.perf_counter()
    sizes = _leaf_sizes(template_leaves)
    n = len(sizes)

    srv = None
    port = 0
    if ckpt is not None:
        # Every potential source listens BEFORE the agreement (the
        # gather doubles as the "server is up" barrier); losers close
        # right after.
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.bind(("0.0.0.0", 0))
        srv.listen(max(8, fabric.world))
        port = srv.getsockname()[1]

    ip = _ip_to_int(getattr(fabric, "advertise_host", "127.0.0.1"))
    try:
        world = _gather(
            fabric, _summary(ckpt, sizes, ip, port), _MSG_AGREE
        )
    except TransferError:
        if srv is not None:
            srv.close()
        raise
    haves, steps = world[:, 1], world[:, 2]

    if not haves.any():
        if srv is not None:
            srv.close()
        return TransferResult(stats=TransferStats(mode="init"))

    # Same deterministic source rule as ever: newest checkpoint, ties
    # to lowest rank — every member derives it from the shared gather.
    src = max(
        range(len(haves)), key=lambda r: (int(haves[r]), int(steps[r]), -r)
    )
    src_digests = [int(d) for d in world[src, _SUMMARY_HDR:]]
    crcs = world[:, _SUMMARY_HDR:]
    # needs[r] = leaves member r must receive (digest mismatch vs src).
    needs: Dict[int, List[int]] = {}
    for r in range(len(haves)):
        if r == src:
            continue
        miss = [i for i in range(n) if int(crcs[r, i]) != src_digests[i]]
        if miss:
            needs[r] = miss

    stats = TransferStats(
        mode="delta" if needs else "local",
        source_rank=src,
        step=int(steps[src]),
        bytes_scheduled=sum(
            sizes[i] for miss in needs.values() for i in miss
        ),
    )

    if not needs:
        # Identical bytes everywhere: nothing moves, every member
        # restores from its own store.
        if srv is not None:
            srv.close()
        if ckpt is None and n > 0:
            # Only reachable when the source advertised _NO_LEAF for
            # every slot (structurally incompatible checkpoint), which
            # "matches" a joiner's empty hand: there is no restore
            # path, and returning mode "local" would send the caller
            # into store.restore(None).
            raise TransferError(
                "source checkpoint cannot supply the model template "
                "(leaf count/size mismatch): no restore path for a "
                "joiner"
            )
        stats.leaves_skipped = n
        stats.seconds = time.perf_counter() - t0
        return TransferResult(
            stats=stats,
            leaves=None if ckpt is None else list(ckpt.leaves),
            leaf_digests=src_digests,
        )

    def confirm(my_torn) -> None:
        """Post-transfer confirmation: one tiny all-gather of per-rank
        ok flags (same vector shape as the agreement, tagged
        _MSG_CONFIRM — see _SUMMARY_HDR).  A torn transfer ANYWHERE
        fails the resize attempt on EVERY member — nobody adopts, the
        caller holds-and-retries, and the next agreement re-verifies
        the source's bytes (``latest_verified``), so persistent source
        corruption degrades the whole world to the next-oldest
        snapshot TOGETHER while a transient wire flip just
        re-transfers.  One member silently restoring an older local
        step instead would diverge the step counter across a live
        world."""
        vec = np.zeros(_SUMMARY_HDR + n, np.int64)
        vec[0] = _MSG_CONFIRM
        vec[1] = 0 if my_torn else 1
        ok = _gather(fabric, vec, _MSG_CONFIRM)[:, 1]
        if not ok.all():
            bad = [r for r in range(len(ok)) if not ok[r]]
            mine = (
                f" (this member's torn leaves: {sorted(my_torn)})"
                if my_torn
                else ""
            )
            raise TornTransferError(
                f"restore transfer: member(s) {bad} received chunk-CRC "
                f"failures{mine}: no member adopts; resize retries"
            )

    me = fabric.rank
    if me == src:
        if len(ckpt.leaves) != n:
            srv.close()
            raise TransferError(
                f"source checkpoint has {len(ckpt.leaves)} leaves but "
                f"the model template expects {n}: checkpoint/model "
                "mismatch cannot source a restore"
            )
        for i, leaf in enumerate(ckpt.leaves):
            if leaf.nbytes != sizes[i]:
                srv.close()
                raise TransferError(
                    f"source checkpoint leaf {i} is {leaf.nbytes} bytes "
                    f"but the model template expects {sizes[i]}: "
                    "checkpoint/model mismatch cannot source a restore"
                )
        # Serve in the background; our own placement proceeds now and
        # the confirmation gather below naturally holds us until every
        # receiver finished pulling (so bytes_sent is complete and the
        # verdict is shared).
        stats_lock = threading.Lock()
        _serve(
            srv, ckpt, needs, chunk_bytes, timeout, chaos,
            stats, stats_lock,
        )
        stats.leaves_skipped = n
        if on_leaf is not None:
            for i, leaf in enumerate(ckpt.leaves):
                on_leaf(i, leaf)
        confirm(set())
        stats.seconds = time.perf_counter() - t0
        return TransferResult(
            stats=stats,
            leaves=list(ckpt.leaves),
            leaf_digests=src_digests,
        )

    if srv is not None:
        srv.close()
    mine = needs.get(me, [])
    keep = [i for i in range(n) if i not in set(mine)]
    if ckpt is None and keep:
        # Only possible when the source itself advertised _NO_LEAF
        # slots (structurally incompatible checkpoint): the source is
        # raising the same diagnosis on its side right now.
        raise TransferError(
            "source checkpoint cannot supply the model template "
            "(leaf count/size mismatch): no restore path for a joiner"
        )
    stats.leaves_skipped = len(keep)
    if on_leaf is not None:
        # Local digest-matched leaves first: their device placement
        # dispatches before (and overlaps) the network pull.
        for i in keep:
            on_leaf(i, ckpt.leaves[i])
    if not mine:
        confirm(set())
        stats.seconds = time.perf_counter() - t0
        return TransferResult(
            stats=stats,
            leaves=list(ckpt.leaves),
            leaf_digests=src_digests,
        )
    bufs, torn = _receive(
        _int_to_ip(world[src, 4]),
        int(world[src, 5]),
        me,
        mine,
        template_leaves,
        sizes,
        src_digests,
        timeout,
        chaos,
        on_leaf,
        stats,
    )
    confirm(torn)
    leaves = [
        bufs[i] if i in bufs else ckpt.leaves[i] for i in range(n)
    ]
    stats.seconds = time.perf_counter() - t0
    return TransferResult(
        stats=stats, leaves=leaves, leaf_digests=src_digests
    )


# ---------------------------------------------------------------------------
# the retired path, kept callable for the benchmark comparison
# ---------------------------------------------------------------------------


def monolithic_broadcast_restore(
    template_leaves: Sequence[Any],
    ckpt: Optional[HostCheckpoint],
    is_source: bool,
) -> List[np.ndarray]:
    """The r05 restore path, verbatim in shape: one
    ``broadcast_one_to_all`` of every leaf to every member, zeros
    template on the receivers, full ``np.asarray`` copy after.  Not
    used by the runtime — ``bench.py``'s restore_paths section runs it
    side by side with ``stream_restore`` so the retirement stays a
    measured claim, not a remembered one."""
    from jax.experimental import multihost_utils

    if is_source:
        leaves = list(ckpt.leaves)
    else:
        leaves = [
            np.zeros(t.shape, np.dtype(t.dtype)) for t in template_leaves
        ]
    out = multihost_utils.broadcast_one_to_all(leaves, is_source=is_source)
    return [np.asarray(x) for x in out]
