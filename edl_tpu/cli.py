"""L5 CLI: submit / list / kill / manifests / crd / local-run / local-sim.

The reference had no CLI of its own — users went through the external
``paddlecloud`` client/server, which also created the k8s objects
(``pkg/resource/training_job.go:39-58``, ``pkg/controller.go:115-118``).
This CLI subsumes that role (SURVEY.md §2.2):

- ``submit``     validate a TrainingJob YAML and apply the CR (kubectl)
- ``manifests``  print the rendered trainer/coordinator manifests
- ``crd``        print the TrainingJob CustomResourceDefinition
- ``list``       list TrainingJobs (kubectl)
- ``kill``       delete a TrainingJob (kubectl)
- ``local-run``  the §7.3 end-to-end slice in one process: spec ->
                 validate -> elastic training on local devices with
                 mid-run resizes -> loss-continuity summary
- ``local-sim``  controller + autoscaler closed loop against an
                 in-memory fake cluster (no k8s needed)
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from typing import List, Optional

from edl_tpu.resource.training_job import TrainingJob, crd_manifest


def _load_job(path: str) -> TrainingJob:
    with open(path) as f:
        text = f.read()
    return TrainingJob.from_yaml(text).validate()


def _dump_yaml(objs) -> str:
    import yaml

    if isinstance(objs, dict):
        objs = [objs]
    return "---\n".join(yaml.safe_dump(o, sort_keys=False) for o in objs)


def _kubectl(
    argv: List[str], input: Optional[str] = None, kubectl: str = "kubectl"
) -> int:
    p = subprocess.run(
        [kubectl, *argv], input=input, text=True, capture_output=True
    )
    sys.stdout.write(p.stdout)
    sys.stderr.write(p.stderr)
    return p.returncode


def cmd_submit(args) -> int:
    job = _load_job(args.spec)
    manifest = job.to_manifest()
    if args.dry_run:
        print(_dump_yaml(manifest))
        return 0
    return _kubectl(
        ["apply", "-f", "-"],
        input=json.dumps(manifest),
        kubectl=args.kubectl,
    )


def cmd_manifests(args) -> int:
    from edl_tpu.controller.jobparser import (
        parse_to_coordinator,
        parse_to_serving_manifests,
        parse_to_trainer_manifests,
    )

    job = _load_job(args.spec)
    objs = (
        parse_to_trainer_manifests(job)
        + parse_to_coordinator(job)
        + parse_to_serving_manifests(job)
    )
    print(_dump_yaml(objs))
    return 0


def cmd_crd(args) -> int:
    print(_dump_yaml(crd_manifest()))
    return 0


def cmd_deploy(args) -> int:
    """Print (or apply) the full control-plane install: namespace, CRD,
    RBAC, controller Deployment — `kubectl apply -f <(edl deploy)`."""
    from edl_tpu.controller.deploy import deploy_manifests
    from edl_tpu.resource.training_job import DEFAULT_IMAGE

    objs = deploy_manifests(image=args.image or DEFAULT_IMAGE)
    if args.apply:
        return _kubectl(
            ["apply", "-f", "-"],
            input=json.dumps(
                {"apiVersion": "v1", "kind": "List", "items": objs}
            ),
            kubectl=args.kubectl,
        )
    print(_dump_yaml(objs))
    return 0


def cmd_list(args) -> int:
    return _kubectl(["get", "trainingjobs", "-A"], kubectl=args.kubectl)


def cmd_kill(args) -> int:
    return _kubectl(["delete", "trainingjob", args.name], kubectl=args.kubectl)


def _parse_resizes(specs: List[str]):
    """--resize-at step:world pairs."""
    out = []
    for s in specs or []:
        step, world = s.split(":")
        out.append((int(step), int(world)))
    return sorted(out)


def cmd_local_run(args) -> int:
    """One process, local devices: train the job's model elastically,
    applying the requested mid-run resizes — the minimum end-to-end
    slice of SURVEY.md §7.3."""
    if getattr(args, "platform", ""):
        from edl_tpu.launcher import force_platform

        force_platform(args.platform)
    import jax
    import optax

    from edl_tpu.models.base import bind_model
    from edl_tpu.runtime.coordinator import LocalCoordinator
    from edl_tpu.runtime.data import ShardedDataIterator
    from edl_tpu.runtime.elastic import ElasticTrainer

    job = _load_job(args.spec)
    if job.spec.compile_cache_dir:
        # Same persistent-XLA-cache wiring the deployed pods get via
        # EDL_COMPILE_CACHE_DIR: repeated local runs of one spec skip
        # recompilation entirely.
        from edl_tpu.launcher import configure_compile_cache

        configure_compile_cache(job.spec.compile_cache_dir)
    layout = job.spec.trainer.parallelism.axes()
    model_factory = bind_model(
        job.spec.trainer.entrypoint or "mnist",
        layout,
        workspace=job.spec.trainer.workspace,
    )
    model = model_factory(None)
    n_dev = len(jax.devices())
    t = job.spec.trainer
    start_world = min(t.min_instance, n_dev)
    gbs = job.spec.global_batch_size or max(64, 8 * n_dev)
    from edl_tpu.runtime.datasets import resolve_dataset

    dataset = resolve_dataset(
        model,
        getattr(args, "data_dir", "") or job.spec.dataset_dir,
        max(4096, gbs),
    )
    data = ShardedDataIterator(dataset, global_batch_size=gbs, seed=args.seed)
    # Local sim runs one-device trainers: quantize on w, not on the
    # deployed topology's w x chips.
    legal_list = [
        w for w in job.legal_world_sizes(chips_per_replica=1) if w <= n_dev
    ]
    if not legal_list:
        print(
            f"error: no legal world size <= {n_dev} local devices "
            f"(layout {layout or '{}'}, global batch "
            f"{job.spec.global_batch_size}); a layout's axis product "
            "must divide the local world",
            file=sys.stderr,
        )
        return 2
    # Clamp the start target to a legal size (a deployed layout may be
    # satisfiable only at topology chips, not at 1 device/trainer).
    start_world = max(
        [w for w in legal_list if w <= start_world] or [legal_list[0]]
    )
    coord = LocalCoordinator(
        target_world=start_world,
        max_world=min(t.max_instance, n_dev),
        legal_sizes=legal_list,
    )
    for i in range(min(t.max_instance, n_dev)):
        coord.register(f"local-{i}")
    store = None
    ckpt_dir = getattr(args, "checkpoint_dir", "") or job.spec.checkpoint_dir
    if ckpt_dir:
        from edl_tpu.checkpoint import HostDRAMStore

        store = HostDRAMStore(spill_dir=ckpt_dir)
    et = ElasticTrainer(
        model_factory if layout else model,
        optax.adam(1e-3),
        data,
        coord,
        store=store,
        checkpoint_interval=job.spec.checkpoint_interval_steps,
        seed=args.seed,
        layout=layout,
    )

    resizes = _parse_resizes(args.resize_at)
    steps = args.steps
    for at_step, world in resizes:
        if at_step > steps:
            break
        et.run(at_step)
        coord.set_target_world(world)
        print(f"[resize] step={at_step} -> target world {world}")
    et.run(steps)
    if store is not None and et.state is not None:
        # Durable runs persist the FINAL state, not just the last
        # interval/resize checkpoint.
        et.store.save_async(et.state, generation=et.generation)
    et.store.wait()

    first = et.history[0] if et.history else None
    last = et.history[-1] if et.history else None
    summary = {
        "job": job.name,
        "model": model.name,
        "steps": len(et.history),
        "first_loss": round(first.loss, 4) if first else None,
        "final_loss": round(last.loss, 4) if last else None,
        "resizes": [
            {
                "generation": e.generation,
                "world_size": e.world_size,
                "seconds": round(e.seconds, 4),
                "graceful": e.graceful,
            }
            for e in et.resize_events
        ],
        "world_sizes_seen": sorted({r.world_size for r in et.history}),
    }
    print(json.dumps(summary, indent=2))
    return 0


def cmd_ingest(args) -> int:
    """Stage a real corpus into a file-backed array store
    (``edl_tpu.runtime.datasets``): ``edl ingest mnist`` for IDX
    image/label pairs, ``edl ingest tokens`` for tokenized text.  The
    produced directory plugs into ``spec.dataset_dir`` /
    ``local-run --data-dir``."""
    from edl_tpu.runtime.datasets import (
        MANIFEST,
        ingest_mnist_idx,
        ingest_tokens,
    )

    if args.format == "mnist":
        if not (args.images and args.labels):
            print("error: ingest mnist needs --images and --labels", file=sys.stderr)
            return 2
        path = ingest_mnist_idx(args.out, args.images, args.labels)
    else:
        if not args.tokens:
            print("error: ingest tokens needs --tokens", file=sys.stderr)
            return 2
        path = ingest_tokens(args.out, args.tokens, seq_len=args.seq_len)
    import os

    with open(os.path.join(path, MANIFEST)) as f:
        print(f.read())
    return 0


def cmd_metrics(args) -> int:
    """Pretty-print a running job's merged metrics and recent
    flight-recorder events from its coordinator (`edl metrics
    <host:port>`).  ``--prom`` dumps the raw Prometheus exposition
    (what a scraper sees); ``--json`` dumps the merged telemetry
    document."""
    from edl_tpu.runtime.coord_service import HTTPCoordinator

    client = HTTPCoordinator(args.url, timeout=args.timeout)
    if args.prom:
        print(client.metrics_text(), end="")
        return 0
    snap = client.metrics()
    tel = {}
    try:
        tel = client.telemetry()
    except Exception:
        pass  # pre-telemetry coordinator: snapshot alone still prints
    if args.json:
        print(json.dumps({"coordinator": snap, "telemetry": tel}, indent=2))
        return 0

    print("coordinator")
    for k in sorted(snap):
        print(f"  {k:<24} {snap[k]}")
    merged = tel.get("merged") or {}
    rate = tel.get("step_rate")
    cost = tel.get("resize_cost_seconds")
    print("goodput")
    print(f"  {'observed_step_rate':<24} "
          f"{f'{rate:.3f} steps/s' if rate is not None else 'n/a'}")
    print(f"  {'resize_cost_seconds':<24} "
          f"{f'{cost:.3f}' if cost is not None else 'n/a'}")
    hists_all = merged.get("histograms") or {}
    gauges_all = merged.get("gauges") or {}
    counters_all = merged.get("counters") or {}
    if any(
        name.startswith("edl_serve_")
        for section in (hists_all, gauges_all, counters_all)
        for name in section
    ):
        # Serving fleet summary: the request-side signals the serving
        # lane scales on, pre-digested (p50/p95 from the merged
        # latency histogram, occupancy mean, requests by status).
        from edl_tpu.telemetry.aggregate import histogram_quantile

        print("serving")
        lat = hists_all.get("edl_serve_latency_seconds")
        for q, tag in ((0.5, "latency_p50"), (0.95, "latency_p95")):
            v = histogram_quantile(lat, q) if lat else None
            print(
                f"  {tag:<24} "
                f"{f'{v * 1000:.1f} ms' if v is not None else 'n/a'}"
            )
        occ = hists_all.get("edl_serve_batch_occupancy") or {}
        tot = sum(h["count"] for h in occ.values())
        if tot:
            mean = sum(h["sum"] for h in occ.values()) / tot
            print(f"  {'batch_occupancy_mean':<24} {mean:.3f}")
        depth = gauges_all.get("edl_serve_queue_depth") or {}
        if depth:
            print(f"  {'queue_depth_max':<24} {max(depth.values()):g}")
        wstep = gauges_all.get("edl_serve_weights_step") or {}
        if wstep:
            print(f"  {'weights_step':<24} {max(wstep.values()):g}")
        # Serving mesh shape + per-device footprint (ISSUE 18): dp×tp
        # and the bytes ONE device actually holds — the numbers an HBM
        # budget (and the hot-swap staging bill) are gated on.
        mdp = gauges_all.get("edl_serve_mesh_dp") or {}
        mtp = gauges_all.get("edl_serve_mesh_tp") or {}
        if mdp or mtp:
            dp_v = int(max(mdp.values())) if mdp else 1
            tp_v = int(max(mtp.values())) if mtp else 1
            print(f"  {'mesh':<24} dp={dp_v} tp={tp_v}")
        for gname, tag in (
            ("edl_serve_weight_shard_bytes_per_device", "weight_bytes/dev"),
            ("edl_serve_kv_pool_bytes_per_device", "kv_pool_bytes/dev"),
            ("edl_serve_kv_used_bytes_per_device", "kv_used_bytes/dev"),
        ):
            g = gauges_all.get(gname) or {}
            if g:
                print(f"  {tag:<24} {max(g.values()):g}")
        # Per-replica drain posture (ISSUE 15): which replicas are
        # serving / draining / drained, plus the drain counters — the
        # operator view of a rolling scale-down.
        drg = gauges_all.get("edl_serve_draining") or {}
        _DRAIN_STATES = {0: "serving", 1: "draining", 2: "drained"}
        for key in sorted(drg):
            state = _DRAIN_STATES.get(int(drg[key]), "?")
            print(f"  drain{{{key}}}{'':<8} {state}")
        drains = counters_all.get("edl_serve_drains_total") or {}
        if drains:
            print(
                f"  {'drains_total':<24} {sum(drains.values()):g}"
            )
        dsec = hists_all.get("edl_serve_drain_seconds") or {}
        dcount = sum(h["count"] for h in dsec.values())
        if dcount:
            dsum = sum(h["sum"] for h in dsec.values())
            print(
                f"  {'drain_seconds_mean':<24} "
                f"{dsum / dcount:.3f}"
            )
        # Live KV migration counters (ISSUE 16): sequences drains
        # handed to survivors instead of waiting out, the KV bytes
        # that moved, and the re-prefill fallbacks the ladder took.
        mig = counters_all.get("edl_serve_migrations_total") or {}
        if mig:
            print(f"  {'migrations_total':<24} {sum(mig.values()):g}")
            fb = sum(
                v for k, v in mig.items() if "outcome=fallback" in k
            )
            print(f"  {'migrate_fallbacks':<24} {fb:g}")
            mb = counters_all.get(
                "edl_serve_migrations_bytes_total"
            ) or {}
            if mb:
                print(
                    f"  {'migrated_kv_bytes':<24} {sum(mb.values()):g}"
                )
            msec = hists_all.get("edl_serve_migrate_seconds")
            m95 = histogram_quantile(msec, 0.95) if msec else None
            print(
                f"  {'migrate_p95':<24} "
                f"{f'{m95 * 1000:.1f} ms' if m95 is not None else 'n/a'}"
            )
        tok = counters_all.get("edl_serve_tokens_total") or {}
        if tok:
            # Decode stats (the token-iteration path): tokens/s is the
            # decode-iteration cadence the fleet sustained — emitted
            # tokens over the seconds the inter-token histogram
            # accumulated (its count/sum), aggregated across replicas.
            it_h = hists_all.get("edl_serve_intertoken_seconds") or {}
            it_count = sum(h["count"] for h in it_h.values())
            it_sum = sum(h["sum"] for h in it_h.values())
            print(f"  {'tokens_total':<24} {sum(tok.values()):g}")
            if it_sum > 0:
                print(
                    f"  {'decode_tokens_per_s':<24} "
                    f"{it_count / it_sum:.1f}"
                )
            ttft = hists_all.get("edl_serve_ttft_seconds")
            for q, tag in ((0.5, "ttft_p50"), (0.95, "ttft_p95")):
                v = histogram_quantile(ttft, q) if ttft else None
                print(
                    f"  {tag:<24} "
                    f"{f'{v * 1000:.1f} ms' if v is not None else 'n/a'}"
                )
            it95 = (
                histogram_quantile(
                    hists_all.get("edl_serve_intertoken_seconds"), 0.95
                )
                if it_h
                else None
            )
            print(
                f"  {'intertoken_p95':<24} "
                f"{f'{it95 * 1000:.2f} ms' if it95 is not None else 'n/a'}"
            )
            kv = gauges_all.get("edl_serve_kv_occupancy") or {}
            if kv:
                print(
                    f"  {'kv_slot_occupancy':<24} {max(kv.values()):.3f}"
                )
            # Chunked-prefill stats (ISSUE 14): admission pressure and
            # the stall it imposed on the running decode batch.
            chunks = counters_all.get(
                "edl_serve_prefill_chunks_total"
            ) or {}
            if chunks:
                print(
                    f"  {'prefill_chunks_total':<24} "
                    f"{sum(chunks.values()):g}"
                )
                ptok = counters_all.get(
                    "edl_serve_prefill_tokens_total"
                ) or {}
                if ptok:
                    print(
                        f"  {'prefill_tokens_total':<24} "
                        f"{sum(ptok.values()):g}"
                    )
            pq = gauges_all.get("edl_serve_prefill_queued_tokens") or {}
            if pq:
                print(
                    f"  {'queued_prefill_tokens':<24} "
                    f"{max(pq.values()):g}"
                )
            stall = hists_all.get("edl_serve_prefill_stall_seconds")
            if stall:
                s95 = histogram_quantile(stall, 0.95)
                print(
                    f"  {'prefill_stall_p95':<24} "
                    f"{f'{s95 * 1000:.2f} ms' if s95 is not None else 'n/a'}"
                )
        # Prefix-cache stats (ISSUE 17): shared-prefix admission reuse
        # — how often warm admissions skipped to the first cold block,
        # and what the retention cost under pressure.
        phits = counters_all.get("edl_serve_prefix_hits_total") or {}
        if phits:
            pmiss = (
                counters_all.get("edl_serve_prefix_misses_total") or {}
            )
            print(f"  {'prefix_hits':<24} {sum(phits.values()):g}")
            print(f"  {'prefix_misses':<24} {sum(pmiss.values()):g}")
            ratio = gauges_all.get("edl_serve_prefix_hit_ratio") or {}
            if ratio:
                print(
                    f"  {'prefix_hit_ratio':<24} "
                    f"{max(ratio.values()):.3f}"
                )
            reused = (
                counters_all.get("edl_serve_prefix_blocks_reused_total")
                or {}
            )
            print(
                f"  {'prefix_blocks_reused':<24} "
                f"{sum(reused.values()):g}"
            )
            pev = (
                counters_all.get("edl_serve_prefix_evictions_total")
                or {}
            )
            print(f"  {'prefix_evictions':<24} {sum(pev.values()):g}")
        req = counters_all.get("edl_serve_requests_total") or {}
        for key in sorted(req):
            print(f"  requests{{{key}}}{'':<10} {req[key]:g}")
    if any(
        name.startswith("edl_route_")
        for section in (gauges_all, counters_all)
        for name in section
    ):
        # Front-door summary (ISSUE 20): the fault-masking the router
        # did on the fleet's behalf — backends by health state, request
        # outcomes, steers off draining replicas, per-attempt failures
        # absorbed, the eject/readmit ledger, and stream re-drives.
        print("router")
        backends = gauges_all.get("edl_route_backends") or {}
        for key in sorted(backends):
            print(f"  backends{{{key}}}{'':<8} {backends[key]:g}")
        rreq = counters_all.get("edl_route_requests_total") or {}
        for key in sorted(rreq):
            print(f"  requests{{{key}}}{'':<8} {rreq[key]:g}")
        rsteer = counters_all.get("edl_route_steers_total") or {}
        if rsteer:
            print(f"  {'steers_total':<24} {sum(rsteer.values()):g}")
        rretry = counters_all.get("edl_route_retries_total") or {}
        if rretry:
            print(
                f"  {'retries_absorbed':<24} {sum(rretry.values()):g}"
            )
            for key in sorted(rretry):
                print(f"  retries{{{key}}}{'':<9} {rretry[key]:g}")
        for cname, tag in (
            ("edl_route_ejections_total", "ejections_total"),
            ("edl_route_readmits_total", "readmits_total"),
        ):
            c = counters_all.get(cname) or {}
            if c:
                print(f"  {tag:<24} {sum(c.values()):g}")
        rdrv = counters_all.get("edl_route_redrives_total") or {}
        for key in sorted(rdrv):
            print(f"  redrives{{{key}}}{'':<8} {rdrv[key]:g}")
        raff = counters_all.get("edl_route_affinity_total") or {}
        for key in sorted(raff):
            print(f"  affinity{{{key}}}{'':<8} {raff[key]:g}")
    counters = counters_all
    if counters:
        print("counters (merged across trainers)")
        for name in sorted(counters):
            for key in sorted(counters[name]):
                label = f"{{{key}}}" if key else ""
                print(f"  {name}{label:<32} {counters[name][key]:g}")
    hists = merged.get("histograms") or {}
    if hists:
        print("histograms (merged: count / mean)")
        for name in sorted(hists):
            for key in sorted(hists[name]):
                h = hists[name][key]
                mean = h["sum"] / h["count"] if h["count"] else 0.0
                label = f"{{{key}}}" if key else ""
                print(
                    f"  {name}{label:<32} {h['count']} / {mean:.6f}"
                )
    events = (tel.get("events") or [])[-args.events:]
    if events:
        print(f"flight recorder (last {len(events)} events)")
        for ev in events:
            data = json.dumps(ev.get("data") or {}, sort_keys=True)
            print(
                f"  step={ev.get('step'):<7} gen={ev.get('generation'):<4} "
                f"{ev.get('kind'):<20} {data}"
            )
    return 0


def cmd_route(args) -> int:
    """Print a routerd's live routing table (`edl route <host:port>`):
    every backend the front door knows, its health state
    (healthy/draining/ejected), the live load score admissions are
    spread by, and the vitals behind it — the operator's answer to
    \"where is my traffic going and why\"."""
    import urllib.request

    addr = args.url if "//" in args.url else f"http://{args.url}"
    with urllib.request.urlopen(
        f"{addr}/routes", timeout=args.timeout
    ) as resp:
        table = json.loads(resp.read())
    if args.json:
        print(json.dumps(table, indent=2))
        return 0
    print("router")
    print(f"  {'plan_generation':<24} {table.get('plan_generation')}")
    p95 = table.get("ttft_p95_s")
    print(
        f"  {'fleet_ttft_p95':<24} "
        f"{f'{p95 * 1000:.1f} ms' if p95 is not None else 'n/a'}"
    )
    print(
        f"  {'affinity_entries':<24} {table.get('affinity_entries', 0)}"
    )
    replicas = table.get("replicas") or []
    if not replicas:
        print("  (no backends)")
        return 0
    print(
        f"  {'replica':<12} {'address':<22} {'health':<9} "
        f"{'score':>7} {'queue':>6} {'kv':>6} {'fails':>6} gen"
    )
    for r in sorted(replicas, key=lambda x: x.get("score") or 0.0):
        kv = r.get("kv_occupancy") or 0.0
        print(
            f"  {r.get('replica', '?'):<12} "
            f"{r.get('address', '?'):<22} "
            f"{r.get('health', '?'):<9} "
            f"{r.get('score', 0.0):>7.2f} "
            f"{r.get('queue_depth', 0):>6g} "
            f"{kv:>6.2f} "
            f"{r.get('consecutive_failures', 0):>6g} "
            f"{'yes' if r.get('can_generate') else 'no'}"
        )
    return 0


def _parse_fleet_member(spec: str):
    """``name=url[,chips=N][,priority=P]`` -> (name, url, chips, prio)."""
    name, sep, rest = spec.partition("=")
    if not sep:
        raise ValueError(f"--job/--serve wants name=url[,k=v], got {spec!r}")
    parts = rest.split(",")
    url = parts[0]
    chips, priority = 1, 0
    for kv in parts[1:]:
        k, _, v = kv.partition("=")
        if k == "chips":
            chips = int(v)
        elif k == "priority":
            priority = int(v)
        else:
            raise ValueError(f"unknown fleet member option {k!r}")
    return name, url, chips, priority


def cmd_fleet(args) -> int:
    """Cluster-wide fleet status (`edl fleet --job lo=host:port,chips=4
    --serve api=host:port`): one table over every bidder's coordinator
    — world/target, chips, the training goodput signals the market's
    objective reads (goodput frac, step rate), and the serving SLO
    signals its hard constraints read (p95, queue depth, rejections) —
    plus chip totals.  The same reads the arbiter's bidders make each
    tick, so what this prints IS the market's next input."""
    from edl_tpu.runtime.coord_service import HTTPCoordinator
    from edl_tpu.telemetry.aggregate import histogram_quantile

    rows = []
    for kind, specs in (("training", args.job), ("serving", args.serve)):
        for spec in specs or []:
            name, url, chips, priority = _parse_fleet_member(spec)
            row = {
                "job": name,
                "kind": kind,
                "priority": priority,
                "chips_per_unit": chips,
                "url": url,
            }
            client = HTTPCoordinator(url, timeout=args.timeout)
            try:
                snap = client.metrics() or {}
            except Exception as e:
                row["error"] = f"unreachable: {e}"
                rows.append(row)
                continue
            row["world"] = snap.get("world_size")
            row["target"] = snap.get("target_world")
            # Same fallback the market's bidders read (TrainingBidder.
            # collect / ServingLane.current_replicas): target first,
            # live world while a retarget hasn't landed — the table
            # must show the market's actual next input.
            units = int(
                snap.get("target_world") or snap.get("world_size") or 0
            )
            row["chips"] = units * chips
            try:
                tel = client.telemetry() or {}
            except Exception:
                tel = {}
            goodput = tel.get("goodput") or {}
            row["goodput_frac"] = goodput.get("frac")
            row["step_rate"] = tel.get("step_rate")
            hists = (tel.get("merged") or {}).get("histograms") or {}
            gauges = (tel.get("merged") or {}).get("gauges") or {}
            lat = hists.get("edl_serve_latency_seconds")
            if lat:
                # histogram_quantile merges label-keyed series itself
                # (with the bucket-schema-skew guard)
                p95 = histogram_quantile(lat, 0.95)
                row["p95_ms"] = round(p95 * 1000, 2) if p95 else None
            depth = gauges.get("edl_serve_queue_depth") or {}
            if depth:
                row["queue_depth"] = max(depth.values())
            rows.append(row)
    if not rows:
        print(
            "error: no bidders (give --job name=url and/or "
            "--serve name=url)",
            file=sys.stderr,
        )
        return 2
    allocated = sum(r.get("chips") or 0 for r in rows)
    if args.json:
        print(
            json.dumps(
                {
                    "bidders": rows,
                    "chips_allocated": allocated,
                    "chips_total": args.chips or None,
                },
                indent=2,
            )
        )
        return 0

    def fmt(v, nd=3):
        if v is None:
            return "-"
        if isinstance(v, float):
            return f"{v:.{nd}f}"
        return str(v)

    print(
        f"{'JOB':<14} {'KIND':<9} {'PRI':>3} {'WORLD':>5} {'TARGET':>6} "
        f"{'CHIPS':>5} {'GOODPUT':>7} {'STEP/S':>7} {'P95_MS':>7} "
        f"{'QUEUE':>5}"
    )
    for r in rows:
        if "error" in r:
            print(f"{r['job']:<14} {r['kind']:<9} {r['error']}")
            continue
        print(
            f"{r['job']:<14} {r['kind']:<9} {r['priority']:>3} "
            f"{fmt(r.get('world')):>5} {fmt(r.get('target')):>6} "
            f"{fmt(r.get('chips')):>5} {fmt(r.get('goodput_frac')):>7} "
            f"{fmt(r.get('step_rate'), 2):>7} "
            f"{fmt(r.get('p95_ms'), 2):>7} {fmt(r.get('queue_depth')):>5}"
        )
    total = f" / {args.chips} total" if args.chips else ""
    print(f"chips allocated: {allocated}{total}")
    return 0


def cmd_serve(args) -> int:
    """Run an elastic inference-serving replica (`edl serve --spec
    job.yaml` or `edl serve --entrypoint mnist --checkpoint-dir d/`):
    load the newest verified checkpoint, AOT-warm the padded-bucket
    forwards, open the HTTP front (/predict /healthz /metrics), and —
    when a serving coordinator is given — register into the serving
    world the autoscaler's serving lane scales."""
    if getattr(args, "platform", ""):
        from edl_tpu.launcher import force_platform

        force_platform(args.platform)
    entrypoint = args.entrypoint
    checkpoint_dir = args.checkpoint_dir
    port = args.port
    max_batch = args.max_batch
    queue_limit = 0
    deadline_ms = args.deadline_ms
    if args.spec:
        job = _load_job(args.spec)
        entrypoint = entrypoint or job.spec.trainer.entrypoint
        checkpoint_dir = checkpoint_dir or job.spec.checkpoint_dir
        sv = job.spec.serving
        if sv is not None:
            # The WHOLE serving section applies locally, same as the
            # deployed path's serving_pod_env — one spec, one behavior.
            port = port or sv.port
            max_batch = max_batch or sv.max_batch
            queue_limit = sv.queue_limit
            deadline_ms = deadline_ms or sv.deadline_ms
    from edl_tpu.serving import serve_run

    replica = serve_run(
        entrypoint=entrypoint,
        coordinator_addr=args.coordinator,
        checkpoint_dir=checkpoint_dir,
        port=port,
        max_batch=max_batch,
        queue_limit=queue_limit,
        deadline_ms=deadline_ms,
    )
    engine = replica.engine
    print(
        json.dumps(
            {
                "replica": replica.replica_id,
                "model": engine.model.name,
                "port": replica.server.port if replica.server else None,
                "weights_step": engine.weights_step,
                "warm_buckets": list(engine.warm_buckets),
            }
        )
    )
    try:
        if args.duration > 0:
            import time

            time.sleep(args.duration)
        else:
            import threading

            threading.Event().wait()  # serve until killed
    except KeyboardInterrupt:
        pass
    finally:
        replica.stop()
    return 0


def cmd_trace(args) -> int:
    """Merge the cluster's flight-recorder journals into ONE causally
    ordered Chrome-trace/Perfetto timeline (`edl trace <host:port>`):
    the coordinator's journal (which already holds every member's
    reported event tail, origin-tagged) is fetched over `/telemetry`,
    member lanes are clock-aligned with the NTP-style offsets the
    members estimated from their heartbeats, and the result is written
    as JSON for ui.perfetto.dev / chrome://tracing — pid = member,
    tid = subsystem, duration slices for resizes (with per-phase child
    slices), instants for votes/quiesce/saves/decisions.

    ``--journal name=path`` merges on-disk JSONL spills
    (EDL_FLIGHT_RECORDER_FILE) instead of / in addition to the live
    coordinator — the post-mortem path.  ``--trace-id`` filters to one
    causal chain; ``--summary`` prints the goodput decomposition and
    the trace chains instead of only writing the file."""
    from edl_tpu.telemetry import trace as tracing

    streams = {}
    offsets = {}
    goodput = None
    if args.url:
        from edl_tpu.runtime.coord_service import HTTPCoordinator

        client = HTTPCoordinator(args.url, timeout=args.timeout)
        tel = client.telemetry()
        streams.update(tracing.member_streams(tel.get("events") or []))
        offsets = {
            m: o
            for m, o in (tel.get("clock_offsets") or {}).items()
            if o is not None
        }
        goodput = tel.get("goodput")
    for spec in args.journal or []:
        name, sep, path = spec.partition("=")
        if not sep:
            import os

            name, path = os.path.basename(spec), spec
        streams[name] = tracing.load_journal(path)
    if not streams:
        print(
            "error: nothing to merge (give a coordinator URL and/or "
            "--journal name=events.jsonl)",
            file=sys.stderr,
        )
        return 2
    merged = tracing.merge_events(streams, offsets)
    if args.summary:
        print(f"events merged: {len(merged)} from {len(streams)} lane(s)")
        if offsets:
            for m in sorted(offsets):
                print(f"  clock offset {m:<20} {offsets[m]:+.6f}s")
        print("goodput")
        if goodput:
            print(f"  {'frac':<24} {goodput['frac']:.4f}")
            print(f"  {'total_s':<24} {goodput['total_s']:.3f}")
            for state in sorted(goodput.get("seconds") or {}):
                print(
                    f"  {state:<24} {goodput['seconds'][state]:.3f}s"
                )
        else:
            print("  n/a (no goodput ledger reported)")
        chains = tracing.trace_chains(merged)
        if chains:
            print(f"causal chains ({len(chains)})")
            for tid_, evs in sorted(
                chains.items(), key=lambda kv: kv[1][0]["wall_aligned"]
            ):
                kinds = [e.get("kind") for e in evs]
                members = sorted({e["member"] for e in evs})
                print(
                    f"  {tid_}  {len(evs)} events over "
                    f"{','.join(members)}: {' -> '.join(kinds[:10])}"
                    + (" ..." if len(kinds) > 10 else "")
                )
    doc = tracing.chrome_trace(merged, trace_id=args.trace_id)
    out = args.out
    with open(out, "w") as f:
        json.dump(doc, f)
    print(
        f"merged trace: {out} "
        f"({len(doc['traceEvents'])} trace events; open at "
        "ui.perfetto.dev or chrome://tracing)"
    )
    return 0


def cmd_controller(args) -> int:
    """Run the control plane against a real cluster: watch TrainingJob
    CRs and reconcile/autoscale forever — the reference's whole
    deliverable (``cmd/edl/edl.go:47-50``: two goroutines, watch +
    autoscaler loop), plus the creation wiring its TODO promised."""
    import time

    from edl_tpu.autoscaler.scaler import Autoscaler
    from edl_tpu.cluster.cluster import Cluster
    from edl_tpu.cluster.kube import KubectlAPI
    from edl_tpu.controller.controller import Controller
    from edl_tpu.controller.watch import TrainingJobWatcher

    kube = KubectlAPI(namespace=args.namespace, kubectl=args.kubectl)
    cluster = Cluster(kube)
    ctrl = Controller(cluster, Autoscaler(cluster, max_load_desired=args.max_load))
    watcher = TrainingJobWatcher(kube.list_training_jobs, ctrl)

    n = 0
    while True:
        try:
            watcher.poll_once()
            ctrl.run_once()
        except Exception:
            import traceback

            traceback.print_exc()
        n += 1
        if args.iterations and n >= args.iterations:
            break
        time.sleep(args.interval)
    if args.iterations:
        print(
            json.dumps(
                {"jobs": ctrl.job_statuses(), "cluster": ctrl.cluster_metrics()},
                indent=2,
            )
        )
    return 0


def cmd_local_sim(args) -> int:
    """Controller + autoscaler closed loop against FakeKube: shows the
    scheduling/scaling story without k8s or devices."""
    from edl_tpu.autoscaler.scaler import Autoscaler
    from edl_tpu.cluster.cluster import Cluster
    from edl_tpu.cluster.kube import FakeKube, NodeInfo
    from edl_tpu.controller.controller import Controller

    jobs = [_load_job(p) for p in args.spec]
    kube = FakeKube(
        [
            NodeInfo(
                name=f"pool-{i}",
                cpu_milli=args.node_cpu_milli,
                memory_mega=args.node_memory_mega,
                tpu_chips=args.node_tpu_chips,
            )
            for i in range(args.nodes)
        ]
    )
    cluster = Cluster(kube)
    ctrl = Controller(cluster, Autoscaler(cluster, max_load_desired=args.max_load))
    for job in jobs:
        ctrl.on_add(job)
    for i in range(args.iterations):
        ctrl.run_once()
        kube.retry_scheduling()
    ctrl.reconcile_status()
    print(
        json.dumps(
            {"jobs": ctrl.job_statuses(), "cluster": ctrl.cluster_metrics()},
            indent=2,
        )
    )
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="edl", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("submit", help="validate + apply a TrainingJob")
    s.add_argument("spec")
    s.add_argument("--dry-run", action="store_true")
    s.add_argument("--kubectl", default="kubectl", help="kubectl binary")
    s.set_defaults(fn=cmd_submit)

    s = sub.add_parser("manifests", help="print rendered k8s manifests")
    s.add_argument("spec")
    s.set_defaults(fn=cmd_manifests)

    s = sub.add_parser("crd", help="print the TrainingJob CRD")
    s.set_defaults(fn=cmd_crd)

    s = sub.add_parser(
        "deploy", help="print/apply the control-plane install (CRD+RBAC+controller)"
    )
    s.add_argument("--image", default=None, help="controller image override")
    s.add_argument("--apply", action="store_true", help="kubectl apply it")
    s.add_argument("--kubectl", default="kubectl")
    s.set_defaults(fn=cmd_deploy)

    s = sub.add_parser("list", help="list TrainingJobs")
    s.add_argument("--kubectl", default="kubectl", help="kubectl binary")
    s.set_defaults(fn=cmd_list)

    s = sub.add_parser("kill", help="delete a TrainingJob")
    s.add_argument("name")
    s.add_argument("--kubectl", default="kubectl", help="kubectl binary")
    s.set_defaults(fn=cmd_kill)

    s = sub.add_parser("local-run", help="end-to-end elastic run, local devices")
    s.add_argument("spec")
    s.add_argument("--steps", type=int, default=50)
    s.add_argument("--seed", type=int, default=0)
    s.add_argument(
        "--platform",
        default="",
        help=(
            "force a JAX platform (config-level: wins even where an "
            "early jax import latched another platform from the env)"
        ),
    )
    s.add_argument(
        "--resize-at",
        action="append",
        metavar="STEP:WORLD",
        help="trigger a resize at a step (repeatable)",
    )
    s.add_argument(
        "--data-dir",
        default="",
        help=(
            "train from a file-backed array store (memory-mapped .npy "
            "directory, see edl_tpu.runtime.datasets) instead of "
            "synthetic data; overrides spec.dataset_dir"
        ),
    )
    s.add_argument(
        "--checkpoint-dir",
        default="",
        help=(
            "durable checkpoint directory (spill + cold-start restore); "
            "overrides spec.checkpoint_dir"
        ),
    )
    s.set_defaults(fn=cmd_local_run)

    s = sub.add_parser(
        "ingest", help="stage a real corpus into a file-backed array store"
    )
    s.add_argument("format", choices=["mnist", "tokens"])
    s.add_argument("--out", required=True, help="array-store directory")
    s.add_argument("--images", default="", help="IDX image file (mnist)")
    s.add_argument("--labels", default="", help="IDX label file (mnist)")
    s.add_argument("--tokens", default="", help="token corpus (.npy/.u16/.u32)")
    s.add_argument(
        "--seq-len", type=int, default=2048, help="row length (tokens) - 1"
    )
    s.set_defaults(fn=cmd_ingest)

    s = sub.add_parser(
        "metrics",
        help="pretty-print a running job's merged metrics + flight "
        "recorder (from its coordinator URL)",
    )
    s.add_argument("url", help="coordinator address (host:port)")
    s.add_argument(
        "--events", type=int, default=20, help="flight-recorder tail length"
    )
    s.add_argument(
        "--prom",
        action="store_true",
        help="dump the raw Prometheus text exposition instead",
    )
    s.add_argument(
        "--json", action="store_true", help="dump raw JSON instead"
    )
    s.add_argument("--timeout", type=float, default=5.0)
    s.set_defaults(fn=cmd_metrics)

    s = sub.add_parser(
        "route",
        help="print a routerd's live routing table (backends, health, "
        "load scores)",
    )
    s.add_argument("url", help="router address (host:port)")
    s.add_argument("--json", action="store_true", help="dump raw JSON")
    s.add_argument("--timeout", type=float, default=5.0)
    s.set_defaults(fn=cmd_route)

    s = sub.add_parser(
        "fleet",
        help="cluster-wide fleet status: every bidder's world/chips + "
        "the market's goodput/SLO input signals",
    )
    s.add_argument(
        "--job",
        action="append",
        metavar="NAME=URL[,chips=N][,priority=P]",
        help="a training job's coordinator (repeatable)",
    )
    s.add_argument(
        "--serve",
        action="append",
        metavar="NAME=URL[,chips=N]",
        help="a serving fleet's coordinator (repeatable)",
    )
    s.add_argument(
        "--chips", type=int, default=0, help="inventory total (for the footer)"
    )
    s.add_argument("--json", action="store_true", help="dump raw JSON")
    s.add_argument("--timeout", type=float, default=5.0)
    s.set_defaults(fn=cmd_fleet)

    s = sub.add_parser(
        "serve",
        help="run an inference-serving replica (checkpoint-backed, "
        "continuous-batched, hot-swapping)",
    )
    s.add_argument("--spec", default="", help="TrainingJob YAML (serving "
                   "defaults come from its spec.serving section)")
    s.add_argument("--entrypoint", default="", help="registered model name")
    s.add_argument(
        "--coordinator", default="", help="serving-world coordinator address"
    )
    s.add_argument(
        "--checkpoint-dir",
        default="",
        help="durable checkpoint dir to serve from (training spills here)",
    )
    s.add_argument("--port", type=int, default=0)
    s.add_argument("--max-batch", type=int, default=0)
    s.add_argument("--deadline-ms", type=int, default=0)
    s.add_argument(
        "--duration",
        type=float,
        default=0.0,
        help="serve for N seconds then exit (0 = forever)",
    )
    s.add_argument("--platform", default="", help="force a JAX platform")
    s.set_defaults(fn=cmd_serve)

    s = sub.add_parser(
        "trace",
        help="merge coordinator + member flight journals into one "
        "clock-aligned Perfetto timeline (+ goodput summary)",
    )
    s.add_argument(
        "url",
        nargs="?",
        default="",
        help="coordinator address (host:port); omit for --journal-only",
    )
    s.add_argument(
        "--journal",
        action="append",
        metavar="NAME=PATH",
        help="merge an on-disk flight-recorder JSONL spill "
        "(EDL_FLIGHT_RECORDER_FILE) as lane NAME (repeatable)",
    )
    s.add_argument(
        "--out", default="edl-trace.json", help="output Chrome-trace JSON"
    )
    s.add_argument(
        "--trace-id", default="", help="filter to one causal chain"
    )
    s.add_argument(
        "--summary",
        action="store_true",
        help="print the goodput decomposition + causal chains",
    )
    s.add_argument("--timeout", type=float, default=5.0)
    s.set_defaults(fn=cmd_trace)

    s = sub.add_parser(
        "controller", help="run the control-plane daemon against a cluster"
    )
    s.add_argument("--namespace", default="default")
    s.add_argument("--kubectl", default="kubectl", help="kubectl binary")
    s.add_argument(
        "--interval", type=float, default=5.0, help="reconcile period (ref 5s tick)"
    )
    s.add_argument(
        "--iterations",
        type=int,
        default=0,
        help="stop after N reconcile loops and print statuses (0 = forever)",
    )
    s.add_argument("--max-load", type=float, default=0.97)
    s.set_defaults(fn=cmd_controller)

    s = sub.add_parser("local-sim", help="controller+autoscaler vs fake cluster")
    s.add_argument("spec", nargs="+")
    s.add_argument("--nodes", type=int, default=4)
    s.add_argument("--node-tpu-chips", type=int, default=4)
    s.add_argument("--node-cpu-milli", type=int, default=8000)
    s.add_argument("--node-memory-mega", type=int, default=32768)
    s.add_argument("--max-load", type=float, default=0.97)
    s.add_argument("--iterations", type=int, default=6)
    s.set_defaults(fn=cmd_local_sim)

    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        # Downstream pager/head closed the pipe: normal CLI etiquette.
        import os

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0


if __name__ == "__main__":
    sys.exit(main())
