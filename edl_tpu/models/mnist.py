"""MNIST ConvNet — benchmark config 2 (BASELINE.md): "MNIST ConvNet,
elastic min=1 max=4 trainers (scale-up under idle cluster)".

A small flax.linen CNN.  Input pipeline note: this environment has no
egress, so the default data source is a deterministic synthetic
MNIST-shaped distribution (digit-dependent Gaussian blobs — linearly
separable enough that loss visibly falls, which is what the elastic
loss-continuity tests need); a real MNIST ``.npz`` can be supplied to
the data iterator instead.
"""

from __future__ import annotations

from typing import Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from edl_tpu.models.base import ModelDef, register_model

NUM_CLASSES = 10


class ConvNet(nn.Module):
    """LeNet-ish ConvNet, bfloat16 compute / float32 params."""

    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):  # x: [B, 28, 28, 1] float32
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(256, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(NUM_CLASSES, dtype=jnp.float32)(x)
        return x


@register_model("mnist")
def mnist() -> ModelDef:
    module = ConvNet()
    sample = jnp.zeros((1, 28, 28, 1), jnp.float32)

    def init_params(rng: jax.Array):
        return module.init(rng, sample)["params"]

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        logits = module.apply({"params": params}, batch["image"])
        labels = jax.nn.one_hot(batch["label"], NUM_CLASSES)
        loss = jnp.mean(
            -jnp.sum(labels * jax.nn.log_softmax(logits), axis=-1)
        )
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return loss, {"loss": loss, "accuracy": acc}

    def synth_batch(rng: np.random.RandomState, n: int):
        label = rng.randint(0, NUM_CLASSES, size=(n,))
        # Digit-dependent blob: mean brightness pattern per class.
        base = np.zeros((n, 28, 28, 1), np.float32)
        for c in range(NUM_CLASSES):
            idx = label == c
            if not idx.any():
                continue
            patt = np.zeros((28, 28, 1), np.float32)
            patt[2 + 2 * c : 6 + 2 * c, 4:24, 0] = 1.0
            base[idx] = patt
        img = base + 0.3 * rng.randn(n, 28, 28, 1).astype(np.float32)
        return {"image": img, "label": label.astype(np.int32)}

    # rough: conv1 25*32*24^2*2, conv2 25*32*64*8^2*2, dense 1024*256*2 + 256*10*2
    flops_fwd = 2 * (25 * 32 * 24 * 24 + 25 * 32 * 64 * 8 * 8 + 1024 * 256 + 256 * 10)
    return ModelDef(
        name="mnist",
        init_params=init_params,
        loss_fn=loss_fn,
        synth_batch=synth_batch,
        flops_per_example=3 * flops_fwd,
    )
