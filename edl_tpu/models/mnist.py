"""MNIST ConvNet — benchmark config 2 (BASELINE.md): "MNIST ConvNet,
elastic min=1 max=4 trainers (scale-up under idle cluster)".

A small flax.linen CNN.  Input pipeline note: this environment has no
egress, so the default data source is a deterministic synthetic
MNIST-shaped distribution (digit-dependent Gaussian blobs — linearly
separable enough that loss visibly falls, which is what the elastic
loss-continuity tests need); a real MNIST ``.npz`` can be supplied to
the data iterator instead.
"""

from __future__ import annotations

from typing import Dict, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from edl_tpu.models.base import ModelDef, register_model

NUM_CLASSES = 10


class ConvNet(nn.Module):
    """LeNet-ish ConvNet, bfloat16 compute / float32 params."""

    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):  # x: [B, 28, 28, 1] float32
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(256, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(NUM_CLASSES, dtype=jnp.float32)(x)
        return x


def _partition_rules(params):
    """Megatron-style rules for the MLP head (the parameter mass):
    Dense_0 column-parallel over tp + row-sharded over fsdp, Dense_1
    row-parallel.  Conv kernels shard output channels over tp.  Axes
    absent from the mesh are filtered by the Trainer, so one rule set
    serves every layout — this is what makes ``mnist`` usable as the
    cheap dp x fsdp / dp x tp deployable-layout model in tests."""
    from jax.sharding import PartitionSpec as P

    def spec_for(path: str, x) -> P:
        if "Dense_0/kernel" in path:  # [3136, 256]
            return P("fsdp", "tp")
        if "Dense_0/bias" in path:  # [256]
            return P("tp")
        if "Dense_1/kernel" in path:  # [256, 10]
            return P("tp", None)
        if "Conv" in path and x.ndim == 4:  # [5,5,in,out]
            return P(None, None, None, "tp")
        return P()

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    leaves = [
        spec_for("/".join(str(getattr(k, "key", k)) for k in path), leaf)
        for path, leaf in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


@register_model("mnist")
def mnist() -> ModelDef:
    module = ConvNet()
    sample = jnp.zeros((1, 28, 28, 1), jnp.float32)

    def init_params(rng: jax.Array):
        return module.init(rng, sample)["params"]

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        logits = module.apply({"params": params}, batch["image"])
        labels = jax.nn.one_hot(batch["label"], NUM_CLASSES)
        loss = jnp.mean(
            -jnp.sum(labels * jax.nn.log_softmax(logits), axis=-1)
        )
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return loss, {"loss": loss, "accuracy": acc}

    def predict_fn(params, inputs) -> Dict[str, jax.Array]:
        logits = module.apply({"params": params}, inputs["image"])
        return {"logits": logits, "label": jnp.argmax(logits, -1)}

    def synth_batch(rng: np.random.RandomState, n: int):
        label = rng.randint(0, NUM_CLASSES, size=(n,))
        # Digit-dependent blob: mean brightness pattern per class.
        base = np.zeros((n, 28, 28, 1), np.float32)
        for c in range(NUM_CLASSES):
            idx = label == c
            if not idx.any():
                continue
            patt = np.zeros((28, 28, 1), np.float32)
            patt[2 + 2 * c : 6 + 2 * c, 4:24, 0] = 1.0
            base[idx] = patt
        img = base + 0.3 * rng.randn(n, 28, 28, 1).astype(np.float32)
        return {"image": img, "label": label.astype(np.int32)}

    # rough: conv1 25*32*24^2*2, conv2 25*32*64*8^2*2, dense 1024*256*2 + 256*10*2
    flops_fwd = 2 * (25 * 32 * 24 * 24 + 25 * 32 * 64 * 8 * 8 + 1024 * 256 + 256 * 10)
    return ModelDef(
        name="mnist",
        init_params=init_params,
        loss_fn=loss_fn,
        synth_batch=synth_batch,
        param_partition=_partition_rules,
        flops_per_example=3 * flops_fwd,
        predict_fn=predict_fn,
        predict_inputs=("image",),
    )
