"""Common model interface for the trainer runtime.

The reference framework never sees the model — user code arrives as an
opaque ``Entrypoint`` + ``TRAINER_PACKAGE`` workspace executed by
``paddle_k8s`` (``pkg/jobparser.go:288-291``).  Our runtime is the
training half too, so it defines a minimal functional contract a model
must satisfy to be trained elastically: pure ``init``/``loss`` functions
(jit-traceable, shape-static) plus a synthetic-batch generator used by
tests and benchmarks (real input pipelines plug in at the data-iterator
layer, not here).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax

Batch = Dict[str, Any]
Params = Any


@dataclass(frozen=True)
class DecodeSpec:
    """A model family's incremental-decode contract (ISSUE 13): the
    two executables the serving engine AOT-warms per padded bucket,
    plus the cache geometry it allocates the paged KV pool from.

    Both functions are pure and jit-traceable, with the cache as
    EXPLICIT carried state (pools in, pools out — never flax mutable
    collections), so the engine can donate the pool buffers and hold
    the compiled executables:

    - ``prefill_fn(params, tokens[B,P], lengths[B], kpool, vpool,
      tables[B,mb]) -> (ids[B], kpool', vpool')`` — run the prompt
      through the normal causal forward once, scatter every layer's
      K/V into the pool blocks, return the greedy next token read at
      each row's last real position (``lengths - 1``).  ``P`` is a
      block-aligned padded bucket; positions past ``lengths`` hold
      garbage K/V that later decode writes overwrite and masks never
      expose.
    - ``decode_fn(params, tokens[B], lengths[B], kpool, vpool,
      tables) -> (ids[B], kpool', vpool')`` — one token of compute:
      write the token's K/V at position ``lengths[i]``, attend over
      the cache through the block table, return the next greedy id.
    - ``chunk_fn(params, tokens[B,C], offsets[B], lengths[B], kpool,
      vpool, tables) -> (ids[B], kpool', vpool')`` — chunked prefill
      (ISSUE 14): one block-aligned prompt slice carrying an explicit
      cache offset; scatters its K/V at ``offsets`` and attends
      causally over all previously-filled positions through the
      (window-truncated) block table.  ``lengths`` = total filled
      positions after the chunk; the returned id is the first sampled
      token when the chunk is the prompt's last (exact-match contract
      vs monolithic ``prefill_fn``).  None = the family predates
      chunked prefill and the engine falls back to monolithic only.

    Pools are ``[layers, num_blocks, block_tokens, heads, head_dim]``
    of ``cache_dtype``; ``max_len`` bounds prompt + generated length
    (the positional-table range).
    """

    layers: int
    heads: int
    head_dim: int
    max_len: int
    cache_dtype: Any
    prefill_fn: Callable[..., Tuple[Any, Any, Any]]
    decode_fn: Callable[..., Tuple[Any, Any, Any]]
    chunk_fn: Optional[Callable[..., Tuple[Any, Any, Any]]] = None


@dataclass(frozen=True)
class ModelDef:
    """A trainable model as pure functions.

    - ``init_params(rng)``            -> params pytree
    - ``loss_fn(params, batch, rng)`` -> (scalar loss, aux metrics dict)
    - ``synth_batch(rng, n)``         -> host-side numpy batch of size n
    - ``param_partition(params)``     -> optional PartitionSpec pytree for
      model-sharded (tp/fsdp) training; None means replicate.
    - ``predict_fn(params, inputs)``  -> optional forward-only apply
      (no loss, no labels, no grads) for the inference-serving path.
    """

    name: str
    init_params: Callable[[jax.Array], Params]
    loss_fn: Callable[[Params, Batch, jax.Array], Tuple[jax.Array, Dict[str, jax.Array]]]
    synth_batch: Callable[[Any, int], Batch]
    param_partition: Optional[Callable[[Params], Any]] = None
    #: approximate FLOPs per example (fwd+bwd) for MFU accounting; 0 = unknown
    flops_per_example: int = 0
    #: trained tokens per example (sequence length) for tokens/s
    #: accounting; 0 = not a token model.  Kept on the model so
    #: benchmarks cannot drift from the model's actual shape (ADVICE r3)
    tokens_per_example: int = 0
    #: forward-only apply: ``predict_fn(params, inputs) -> outputs
    #: dict`` where ``inputs`` holds exactly the ``predict_inputs``
    #: keys of a host batch (labels never cross the serving wire).
    #: Pure and jit-traceable like ``loss_fn``; None = the model family
    #: has no serving path (``pipeline_lm``'s 1F1B schedule weaves the
    #: backward into the schedule itself — its ModelDef routes serving
    #: through the GPipe forward instead, see models/pipeline_lm.py)
    predict_fn: Optional[Callable[[Params, Batch], Dict[str, Any]]] = None
    #: batch keys ``predict_fn`` consumes (the serving request schema;
    #: a strict subset of ``synth_batch``'s keys)
    predict_inputs: Tuple[str, ...] = ()
    #: incremental-decode contract (KV-cached prefill/decode pair) for
    #: autoregressive serving; None = the family only serves single-
    #: shot forwards through ``predict_fn``
    decode: Optional[DecodeSpec] = None


def divisor_at_most(n: int, want: int) -> int:
    """Largest divisor of ``n`` that is <= ``want`` — the shared
    quantizer for width-like knobs that must divide a token/batch count
    (MoE routing groups, pipeline microbatch counts)."""
    m = max(1, min(want, n))
    while n % m != 0:
        m -= 1
    return m


_REGISTRY: Dict[str, Callable[..., ModelDef]] = {}


def register_model(name: str):
    def deco(factory: Callable[..., ModelDef]):
        _REGISTRY[name] = factory
        return factory

    return deco


def load_workspace_factory(workspace: str) -> Callable[..., ModelDef]:
    """Load user training code from ``workspace``/model.py.

    The user-code contract (the reference's whole trainer interface:
    an opaque ``Entrypoint`` run inside ``TRAINER_PACKAGE``,
    ``pkg/jobparser.go:288-291``): the workspace directory contains a
    ``model.py`` exposing ``build(**kwargs) -> ModelDef``.  The
    workspace dir is put on ``sys.path`` while executing so user code
    may import its sibling modules."""
    import importlib.util
    import sys

    path = os.path.join(workspace, "model.py")
    if not os.path.isfile(path):
        raise ValueError(
            f"workspace {workspace!r} has no model.py (the user-code "
            "contract: model.py exposing build(**kwargs) -> ModelDef)"
        )
    modname = f"_edl_workspace_{abs(hash(os.path.abspath(path)))}"
    spec = importlib.util.spec_from_file_location(modname, path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, workspace)
    try:
        sys.modules[modname] = mod
        spec.loader.exec_module(mod)
    finally:
        try:
            sys.path.remove(workspace)
        except ValueError:
            pass
    build = getattr(mod, "build", None)
    if not callable(build):
        raise ValueError(
            f"{path} defines no callable build(**kwargs) -> ModelDef"
        )
    return build


def _resolve_factory(name: str, workspace: str = "") -> Callable[..., ModelDef]:
    """Registry lookup, falling back to the workspace's ``build`` for
    unregistered entrypoints (ref ``pkg/jobparser.go:288-291``)."""
    import edl_tpu.models  # noqa: F401  (register built-ins)

    factory = _REGISTRY.get(name)
    if factory is not None:
        return factory
    if workspace:
        return load_workspace_factory(workspace)
    raise ValueError(
        f"unknown model {name!r}; registered: {sorted(_REGISTRY)} "
        "(set trainer.workspace to train user code)"
    )


def get_model(name: str, workspace: str = "", **kwargs) -> ModelDef:
    """Build a model by entrypoint name (used by the CLI/launcher to
    turn a TrainingJob entrypoint into a runnable model).  Unregistered
    names load from ``workspace``/model.py when given."""
    model = _resolve_factory(name, workspace)(**kwargs)
    if not isinstance(model, ModelDef):
        raise ValueError(
            f"model factory for {name!r} returned {type(model).__name__}, "
            "not a ModelDef"
        )
    return model


def registered_models():
    import edl_tpu.models  # noqa: F401

    return sorted(_REGISTRY)


#: Layout axis -> the factory kwarg that carries the mesh into
#: mesh-aware model families.  tp/fsdp need no kwarg: partition rules
#: (``ModelDef.param_partition``) cover them, and the Trainer filters
#: rule axes to whatever the mesh actually has.
_MESH_KWARGS = {"sp": "sp_mesh", "ep": "ep_mesh", "pp": "pp_mesh"}


def bind_model(name: str, layout=None, workspace: str = "", **kwargs):
    """Bind an entrypoint + parallelism layout into a mesh -> ModelDef
    factory for the elastic runtime.

    Elasticity rebuilds the device mesh every generation, and the
    sp/ep/pp model families close over the mesh (ring attention's
    shard_map, expert activation constraints, the pipeline schedule) —
    so a deployed layout needs the model REBUILT per mesh, not built
    once (the reference never faced this: its trainer spec was one flat
    data-parallel pool, ``pkg/resource/training_job.go:128-134``).

    Validates up front (fail at submit/boot, not mid-resize):
    - the entrypoint exists and accepts the mesh kwargs the layout needs;
    - tp/fsdp layouts require the model to declare partition rules
      (otherwise params would replicate and the axes carry nothing).

    Returns ``build(mesh=None) -> ModelDef``; ``build(None)`` gives a
    mesh-free instance (synthetic-data probing, single-chip runs).
    """
    import inspect

    layout = {a: int(s) for a, s in (layout or {}).items() if int(s) > 1}
    factory = _resolve_factory(name, workspace)
    try:
        params = inspect.signature(factory).parameters
        has_varkw = any(
            p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
        )
    except (TypeError, ValueError):  # pragma: no cover - C callables
        params, has_varkw = {}, True
    needed = {a: _MESH_KWARGS[a] for a in layout if a in _MESH_KWARGS}
    missing = [
        f"{a} (kwarg {kw})"
        for a, kw in needed.items()
        if kw not in params and not has_varkw
    ]
    if missing:
        raise ValueError(
            f"model {name!r} does not support layout axes: "
            f"{', '.join(missing)}"
        )
    def _checked(model) -> ModelDef:
        if not isinstance(model, ModelDef):
            raise ValueError(
                f"model factory for {name!r} returned "
                f"{type(model).__name__}, not a ModelDef"
            )
        return model

    # The mesh-free instance is immutable (frozen ModelDef) and mesh-
    # independent, so build it at most once: callers probe it for data
    # shapes / partition presence and ElasticTrainer binds it again —
    # without the cache a workspace user's build() would execute three
    # times at boot.
    mesh_free: list = []

    def build(mesh=None) -> ModelDef:
        if mesh is None:
            if not mesh_free:
                mesh_free.append(_checked(factory(**kwargs)))
            return mesh_free[0]
        kw = dict(kwargs)
        for axis, kwarg in needed.items():
            kw[kwarg] = mesh
        return _checked(factory(**kw))

    if any(a in layout for a in ("tp", "fsdp")):
        if build(None).param_partition is None:
            raise ValueError(
                f"model {name!r} declares no partition rules; a "
                "tp/fsdp layout would shard nothing"
            )

    return build
