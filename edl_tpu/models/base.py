"""Common model interface for the trainer runtime.

The reference framework never sees the model — user code arrives as an
opaque ``Entrypoint`` + ``TRAINER_PACKAGE`` workspace executed by
``paddle_k8s`` (``pkg/jobparser.go:288-291``).  Our runtime is the
training half too, so it defines a minimal functional contract a model
must satisfy to be trained elastically: pure ``init``/``loss`` functions
(jit-traceable, shape-static) plus a synthetic-batch generator used by
tests and benchmarks (real input pipelines plug in at the data-iterator
layer, not here).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax

Batch = Dict[str, Any]
Params = Any


@dataclass(frozen=True)
class ModelDef:
    """A trainable model as pure functions.

    - ``init_params(rng)``            -> params pytree
    - ``loss_fn(params, batch, rng)`` -> (scalar loss, aux metrics dict)
    - ``synth_batch(rng, n)``         -> host-side numpy batch of size n
    - ``param_partition(params)``     -> optional PartitionSpec pytree for
      model-sharded (tp/fsdp) training; None means replicate.
    """

    name: str
    init_params: Callable[[jax.Array], Params]
    loss_fn: Callable[[Params, Batch, jax.Array], Tuple[jax.Array, Dict[str, jax.Array]]]
    synth_batch: Callable[[Any, int], Batch]
    param_partition: Optional[Callable[[Params], Any]] = None
    #: approximate FLOPs per example (fwd+bwd) for MFU accounting; 0 = unknown
    flops_per_example: int = 0
    #: trained tokens per example (sequence length) for tokens/s
    #: accounting; 0 = not a token model.  Kept on the model so
    #: benchmarks cannot drift from the model's actual shape (ADVICE r3)
    tokens_per_example: int = 0


_REGISTRY: Dict[str, Callable[..., ModelDef]] = {}


def register_model(name: str):
    def deco(factory: Callable[..., ModelDef]):
        _REGISTRY[name] = factory
        return factory

    return deco


def get_model(name: str, **kwargs) -> ModelDef:
    """Build a registered model by name (used by the CLI/launcher to turn
    a TrainingJob entrypoint into a runnable model)."""
    # Import built-ins lazily so registration happens on first lookup.
    import edl_tpu.models  # noqa: F401

    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; registered: {sorted(_REGISTRY)}"
        ) from None
    return factory(**kwargs)


def registered_models():
    import edl_tpu.models  # noqa: F401

    return sorted(_REGISTRY)
