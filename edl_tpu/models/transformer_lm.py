"""Decoder-only transformer LM — the long-context workload.

Where ``transformer.py`` is the WMT encoder-decoder benchmark config,
this family is the sequence-parallel path: causal self-attention runs
as **ring attention** over the mesh's ``sp`` axis
(``edl_tpu.ops.ring_attention``), so sequences shard across devices and
context length scales with the ring size instead of one device's HBM.

Build with ``get_model("transformer_lm", sp_mesh=mesh)`` to enable the
ring (the model needs the mesh because ring attention is a
``shard_map`` over it); without a mesh it runs fused single-device
attention — same math, so tests can diff the two.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from edl_tpu.models.base import DecodeSpec, ModelDef, register_model
from edl_tpu.ops import fused_attention, ring_attention


class CausalSelfAttention(nn.Module):
    num_heads: int
    d_model: int
    sp_mesh: Optional[Mesh] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, kv=None):
        head_dim = self.d_model // self.num_heads
        qkv = nn.DenseGeneral(
            features=(3, self.num_heads, head_dim),
            axis=-1,
            dtype=self.dtype,
            name="qkv",
        )(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B,T,H,D]
        if kv is not None:
            # Incremental-decode path (models/decode.py): this layer's
            # K/V scatter into the paged pool.  Prefill keeps the
            # module's own causal attention (the training math over
            # the prompt); decode attends over the gathered cache.
            kp, vp = kv.write(k, v)
            if kv.prefill:
                out = fused_attention(q, k, v, causal=True)
            else:
                out = kv.attend(q, kp, vp)
            proj = nn.DenseGeneral(
                features=self.d_model,
                axis=(-2, -1),
                dtype=self.dtype,
                name="out",
            )(out.astype(self.dtype))
            return proj, (kp, vp)
        if self.sp_mesh is not None:
            out = ring_attention(q, k, v, self.sp_mesh, axis="sp", causal=True)
        else:
            out = fused_attention(q, k, v, causal=True)  # flash kernel on TPU
        return nn.DenseGeneral(
            features=self.d_model,
            axis=(-2, -1),
            dtype=self.dtype,
            name="out",
        )(out.astype(self.dtype))


class LMBlock(nn.Module):
    num_heads: int
    d_model: int
    d_ff: int
    sp_mesh: Optional[Mesh] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, kv=None):
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        attn = CausalSelfAttention(
            self.num_heads, self.d_model, self.sp_mesh, self.dtype, name="attn"
        )
        if kv is not None:
            a, pools = attn(h, kv=kv)
            x = x + a
        else:
            x = x + attn(h)
            pools = None
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        h = nn.Dense(self.d_ff, dtype=self.dtype, name="wi")(h)
        h = nn.gelu(h)
        out = x + nn.Dense(self.d_model, dtype=self.dtype, name="wo")(h)
        return out if kv is None else (out, pools)


class TransformerLM(nn.Module):
    vocab_size: int
    d_model: int
    d_ff: int
    num_heads: int
    num_layers: int
    max_len: int
    sp_mesh: Optional[Mesh] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, tokens, return_features: bool = False, kv=None):
        """tokens: [B, T] int32.  Returns [B, T, V] logits, or the
        pre-projection [B, T, D] features when ``return_features``
        (the chunked-loss path, ``ops/losses.tied_vocab_xent``).

        ``kv`` (incremental decode): ``(kpool, vpool, tables, lengths,
        prefill)`` — pools [L, nb, bt, H, D], per-row block tables and
        lengths (models/decode.py).  Prefill runs the normal causal
        forward over the prompt while scattering every layer's K/V
        into the pool; decode takes ``tokens`` [B] (ONE token per row,
        embedded at position ``lengths[i]``) and attends through the
        block table.  A SIX-tuple ``(kpool, vpool, tables, lengths,
        offsets, "chunk")`` selects chunked prefill (ISSUE 14):
        ``tokens`` [B, C] is one block-aligned prompt slice embedded
        at positions ``offsets[i] + c``, scattered at its offset, and
        attending causally over every previously-filled position.
        Returns (features, kpool', vpool')."""
        from edl_tpu.models.decode import LayerKV

        embed = nn.Embed(
            self.vocab_size,
            self.d_model,
            embedding_init=nn.initializers.normal(1.0),
            name="embed",
        )
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (self.max_len, self.d_model),
        )
        if kv is not None:
            offsets = None
            if len(kv) == 6:
                kpool, vpool, tables, lengths, offsets, prefill = kv
            else:
                kpool, vpool, tables, lengths, prefill = kv
            if prefill == "chunk":
                T = tokens.shape[1]
                cpos = offsets[:, None] + jnp.arange(T)[None, :]
                x = (embed(tokens) + pos[cpos]).astype(self.dtype)
            elif prefill:
                T = tokens.shape[1]
                x = (embed(tokens) + pos[None, :T]).astype(self.dtype)
            else:
                x = (
                    embed(tokens[:, None]) + pos[lengths][:, None]
                ).astype(self.dtype)
            for i in range(self.num_layers):
                layer_kv = LayerKV(
                    kpool[i], vpool[i], tables, lengths, prefill,
                    offsets=offsets,
                )
                x, (kl, vl) = LMBlock(
                    self.num_heads,
                    self.d_model,
                    self.d_ff,
                    self.sp_mesh,
                    self.dtype,
                    name=f"layer_{i}",
                )(x, kv=layer_kv)
                kpool = kpool.at[i].set(kl)
                vpool = vpool.at[i].set(vl)
            x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
            return x, kpool, vpool
        T = tokens.shape[1]
        x = (embed(tokens) + pos[None, :T]).astype(self.dtype)
        for i in range(self.num_layers):
            x = LMBlock(
                self.num_heads,
                self.d_model,
                self.d_ff,
                self.sp_mesh,
                self.dtype,
                name=f"layer_{i}",
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        if return_features:
            return x
        # Weight-tied projection in bf16 with f32 MXU accumulation (an
        # f32 [*, vocab] matmul runs far below bf16 peak; see
        # models/transformer.py).
        return jnp.einsum(
            "btd,vd->btv",
            x.astype(self.dtype),
            embed.embedding.astype(self.dtype),
            preferred_element_type=jnp.float32,
        )


def _partition_rules(params) -> Any:
    def spec_for(path: str, x) -> P:
        if x.ndim <= 1 or "pos_embed" in path:
            return P()
        if "embedding" in path:
            return P("tp", "fsdp")
        if "qkv/kernel" in path:  # [d_model, 3, H, D]
            return P("fsdp", None, "tp", None)
        if "out/kernel" in path:  # [H, D, d_model]
            return P("tp", None, "fsdp")
        if "wi/kernel" in path:
            return P("fsdp", "tp")
        if "wo/kernel" in path:
            return P("tp", "fsdp")
        if x.ndim == 2:
            return P("fsdp", None)
        return P()

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    leaves = [
        spec_for("/".join(str(getattr(k, "key", k)) for k in path), leaf)
        for path, leaf in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def lm_synth_batch(vocab: int, L: int):
    """Deterministic periodic token stream (period via offset) —
    learnable with context; shared by every LM family so their
    synthetic corpora (and bench numbers) stay comparable."""

    def synth_batch(rng: np.random.RandomState, n: int):
        start = rng.randint(3, vocab - 8, size=(n, 1))
        t = np.arange(L + 1)[None, :]
        tokens = 3 + ((start - 3) + t) % (vocab - 3)
        return {"tokens": tokens.astype(np.int32)}

    return synth_batch


def lm_flops(vocab: int, d_model: int, d_ff: int, layers: int, L: int) -> int:
    """True executed matmul FLOPs per example for a decoder LM
    (fwd+bwd): per-token layer matmuls + causal attention score/PV
    terms (causal halves the T^2 work) + the tied vocab projection.
    Shared by every LM family so MFU accounting can't diverge."""
    params_per_layer = 4 * d_model * d_model + 2 * d_model * d_ff
    return (
        6 * (layers * params_per_layer + vocab * d_model) * L
        + 12 * layers * L * L * d_model // 2
    )


@register_model("transformer_lm")
def transformer_lm(
    tiny: bool = False,
    seq_len: Optional[int] = None,
    sp_mesh: Optional[Mesh] = None,
) -> ModelDef:
    if tiny:
        vocab, d_model, d_ff, heads, layers = 256, 64, 256, 4, 2
        L = seq_len or 64
    else:
        vocab, d_model, d_ff, heads, layers = 32000, 768, 3072, 12, 12
        L = seq_len or 2048
    module = TransformerLM(
        vocab_size=vocab,
        d_model=d_model,
        d_ff=d_ff,
        num_heads=heads,
        num_layers=layers,
        max_len=L,
        sp_mesh=sp_mesh,
    )
    sample = jnp.zeros((1, L), jnp.int32)

    def init_params(rng: jax.Array):
        return module.init(rng, sample)["params"]

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        from edl_tpu.ops.losses import best_vocab_xent

        tokens = batch["tokens"]
        labels = tokens[:, 1:]
        x = module.apply(
            {"params": params}, tokens[:, :-1], return_features=True
        )
        loss, _ = best_vocab_xent(
            x, params["embed"]["embedding"], labels, labels != 0
        )
        return loss, {"loss": loss}

    def predict_fn(params, inputs) -> Dict[str, jax.Array]:
        """Forward-only next-token prediction.  Request token rows may
        carry the training corpus's L+1 layout (context + shifted
        label); the static slice keeps the positional table in range
        either way.  Greedy ids only — the [B, T, vocab] logits never
        leave the device."""
        tokens = inputs["tokens"][:, :L]
        x = module.apply({"params": params}, tokens, return_features=True)
        logits = jnp.einsum(
            "btd,vd->btv",
            x.astype(jnp.bfloat16),
            params["embed"]["embedding"].astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        return {"tokens": jnp.argmax(logits, -1)}

    synth_batch = lm_synth_batch(vocab, L)
    flops = lm_flops(vocab, d_model, d_ff, layers, L)
    return ModelDef(
        name="transformer_lm",
        init_params=init_params,
        loss_fn=loss_fn,
        synth_batch=synth_batch,
        param_partition=_partition_rules,
        flops_per_example=flops,
        tokens_per_example=L,
        predict_fn=predict_fn,
        predict_inputs=("tokens",),
        decode=lm_decode_spec(module, heads, d_model, L),
    )


def lm_decode_spec(module, heads: int, d_model: int, L: int) -> DecodeSpec:
    """KV-cached prefill/decode pair for a module whose ``__call__``
    threads the ``kv`` cache tuple (TransformerLM / MoELM — shared so
    the families cannot drift).  ``drop_intermediates``: pass-through
    for MoE modules that sow router diagnostics (discarded — serving
    reads tokens, not load-balance telemetry)."""
    from edl_tpu.models.decode import greedy_from_features

    sows = getattr(module, "num_experts", None) is not None

    def _apply(params, tokens, kv):
        if sows:
            out, _ = module.apply(
                {"params": params},
                tokens,
                kv=kv,
                mutable=["intermediates"],
            )
            return out
        return module.apply({"params": params}, tokens, kv=kv)

    def prefill_fn(params, tokens, lengths, kpool, vpool, tables):
        feats, kp, vp = _apply(
            params, tokens, (kpool, vpool, tables, lengths, True)
        )
        last = jnp.clip(lengths - 1, 0, tokens.shape[1] - 1)
        ids = greedy_from_features(
            feats, params["embed"]["embedding"], positions=last
        )
        return ids, kp, vp

    def decode_fn(params, tokens, lengths, kpool, vpool, tables):
        feats, kp, vp = _apply(
            params, tokens, (kpool, vpool, tables, lengths, False)
        )
        ids = greedy_from_features(feats, params["embed"]["embedding"])
        return ids, kp, vp

    def chunk_fn(params, tokens, offsets, lengths, kpool, vpool, tables):
        # Chunked prefill (ISSUE 14): one block-aligned prompt slice at
        # an explicit cache offset.  ``lengths`` = the TOTAL filled
        # positions after this chunk (offset + true chunk length), so
        # the greedy read lands on the prompt's last real position when
        # this is the final chunk (the first sampled token — the one
        # that must match monolithic prefill exactly).
        feats, kp, vp = _apply(
            params, tokens, (kpool, vpool, tables, lengths, offsets, "chunk")
        )
        last = jnp.clip(lengths - 1 - offsets, 0, tokens.shape[1] - 1)
        ids = greedy_from_features(
            feats, params["embed"]["embedding"], positions=last
        )
        return ids, kp, vp

    return DecodeSpec(
        layers=module.num_layers,
        heads=heads,
        head_dim=d_model // heads,
        max_len=L,
        cache_dtype=module.dtype,
        prefill_fn=prefill_fn,
        decode_fn=decode_fn,
        chunk_fn=chunk_fn,
    )


@register_model("longcontext_lm")
def longcontext_lm(
    tiny: bool = False,
    seq_len: Optional[int] = None,
    sp_mesh: Optional[Mesh] = None,
) -> ModelDef:
    """The long-context workload as a first-class registry entry: the
    same decoder-only family at the flash-attention context lengths
    ``bench_longcontext_lm`` measures (4k default; ring attention when
    an ``sp_mesh`` is bound).  Registered separately so serving specs
    and the decode path can name it without smuggling ``seq_len``
    overrides through every layer."""
    import dataclasses

    base = transformer_lm(
        tiny=tiny,
        seq_len=seq_len or (128 if tiny else 4096),
        sp_mesh=sp_mesh,
    )
    return dataclasses.replace(base, name="longcontext_lm")
