"""Decoder-only transformer LM — the long-context workload.

Where ``transformer.py`` is the WMT encoder-decoder benchmark config,
this family is the sequence-parallel path: causal self-attention runs
as **ring attention** over the mesh's ``sp`` axis
(``edl_tpu.ops.ring_attention``), so sequences shard across devices and
context length scales with the ring size instead of one device's HBM.

Build with ``get_model("transformer_lm", sp_mesh=mesh)`` to enable the
ring (the model needs the mesh because ring attention is a
``shard_map`` over it); without a mesh it runs fused single-device
attention — same math, so tests can diff the two.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from edl_tpu.models.base import ModelDef, register_model
from edl_tpu.ops import fused_attention, ring_attention


class CausalSelfAttention(nn.Module):
    num_heads: int
    d_model: int
    sp_mesh: Optional[Mesh] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        head_dim = self.d_model // self.num_heads
        qkv = nn.DenseGeneral(
            features=(3, self.num_heads, head_dim),
            axis=-1,
            dtype=self.dtype,
            name="qkv",
        )(x)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B,T,H,D]
        if self.sp_mesh is not None:
            out = ring_attention(q, k, v, self.sp_mesh, axis="sp", causal=True)
        else:
            out = fused_attention(q, k, v, causal=True)  # flash kernel on TPU
        return nn.DenseGeneral(
            features=self.d_model,
            axis=(-2, -1),
            dtype=self.dtype,
            name="out",
        )(out.astype(self.dtype))


class LMBlock(nn.Module):
    num_heads: int
    d_model: int
    d_ff: int
    sp_mesh: Optional[Mesh] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        x = x + CausalSelfAttention(
            self.num_heads, self.d_model, self.sp_mesh, self.dtype, name="attn"
        )(h)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        h = nn.Dense(self.d_ff, dtype=self.dtype, name="wi")(h)
        h = nn.gelu(h)
        return x + nn.Dense(self.d_model, dtype=self.dtype, name="wo")(h)


class TransformerLM(nn.Module):
    vocab_size: int
    d_model: int
    d_ff: int
    num_heads: int
    num_layers: int
    max_len: int
    sp_mesh: Optional[Mesh] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, tokens, return_features: bool = False):
        """tokens: [B, T] int32.  Returns [B, T, V] logits, or the
        pre-projection [B, T, D] features when ``return_features``
        (the chunked-loss path, ``ops/losses.tied_vocab_xent``)."""
        T = tokens.shape[1]
        embed = nn.Embed(
            self.vocab_size,
            self.d_model,
            embedding_init=nn.initializers.normal(1.0),
            name="embed",
        )
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (self.max_len, self.d_model),
        )
        x = (embed(tokens) + pos[None, :T]).astype(self.dtype)
        for i in range(self.num_layers):
            x = LMBlock(
                self.num_heads,
                self.d_model,
                self.d_ff,
                self.sp_mesh,
                self.dtype,
                name=f"layer_{i}",
            )(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        if return_features:
            return x
        # Weight-tied projection in bf16 with f32 MXU accumulation (an
        # f32 [*, vocab] matmul runs far below bf16 peak; see
        # models/transformer.py).
        return jnp.einsum(
            "btd,vd->btv",
            x.astype(self.dtype),
            embed.embedding.astype(self.dtype),
            preferred_element_type=jnp.float32,
        )


def _partition_rules(params) -> Any:
    def spec_for(path: str, x) -> P:
        if x.ndim <= 1 or "pos_embed" in path:
            return P()
        if "embedding" in path:
            return P("tp", "fsdp")
        if "qkv/kernel" in path:  # [d_model, 3, H, D]
            return P("fsdp", None, "tp", None)
        if "out/kernel" in path:  # [H, D, d_model]
            return P("tp", None, "fsdp")
        if "wi/kernel" in path:
            return P("fsdp", "tp")
        if "wo/kernel" in path:
            return P("tp", "fsdp")
        if x.ndim == 2:
            return P("fsdp", None)
        return P()

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    leaves = [
        spec_for("/".join(str(getattr(k, "key", k)) for k in path), leaf)
        for path, leaf in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def lm_synth_batch(vocab: int, L: int):
    """Deterministic periodic token stream (period via offset) —
    learnable with context; shared by every LM family so their
    synthetic corpora (and bench numbers) stay comparable."""

    def synth_batch(rng: np.random.RandomState, n: int):
        start = rng.randint(3, vocab - 8, size=(n, 1))
        t = np.arange(L + 1)[None, :]
        tokens = 3 + ((start - 3) + t) % (vocab - 3)
        return {"tokens": tokens.astype(np.int32)}

    return synth_batch


def lm_flops(vocab: int, d_model: int, d_ff: int, layers: int, L: int) -> int:
    """True executed matmul FLOPs per example for a decoder LM
    (fwd+bwd): per-token layer matmuls + causal attention score/PV
    terms (causal halves the T^2 work) + the tied vocab projection.
    Shared by every LM family so MFU accounting can't diverge."""
    params_per_layer = 4 * d_model * d_model + 2 * d_model * d_ff
    return (
        6 * (layers * params_per_layer + vocab * d_model) * L
        + 12 * layers * L * L * d_model // 2
    )


@register_model("transformer_lm")
def transformer_lm(
    tiny: bool = False,
    seq_len: Optional[int] = None,
    sp_mesh: Optional[Mesh] = None,
) -> ModelDef:
    if tiny:
        vocab, d_model, d_ff, heads, layers = 256, 64, 256, 4, 2
        L = seq_len or 64
    else:
        vocab, d_model, d_ff, heads, layers = 32000, 768, 3072, 12, 12
        L = seq_len or 2048
    module = TransformerLM(
        vocab_size=vocab,
        d_model=d_model,
        d_ff=d_ff,
        num_heads=heads,
        num_layers=layers,
        max_len=L,
        sp_mesh=sp_mesh,
    )
    sample = jnp.zeros((1, L), jnp.int32)

    def init_params(rng: jax.Array):
        return module.init(rng, sample)["params"]

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        from edl_tpu.ops.losses import best_vocab_xent

        tokens = batch["tokens"]
        labels = tokens[:, 1:]
        x = module.apply(
            {"params": params}, tokens[:, :-1], return_features=True
        )
        loss, _ = best_vocab_xent(
            x, params["embed"]["embedding"], labels, labels != 0
        )
        return loss, {"loss": loss}

    def predict_fn(params, inputs) -> Dict[str, jax.Array]:
        """Forward-only next-token prediction.  Request token rows may
        carry the training corpus's L+1 layout (context + shifted
        label); the static slice keeps the positional table in range
        either way.  Greedy ids only — the [B, T, vocab] logits never
        leave the device."""
        tokens = inputs["tokens"][:, :L]
        x = module.apply({"params": params}, tokens, return_features=True)
        logits = jnp.einsum(
            "btd,vd->btv",
            x.astype(jnp.bfloat16),
            params["embed"]["embedding"].astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        return {"tokens": jnp.argmax(logits, -1)}

    synth_batch = lm_synth_batch(vocab, L)
    flops = lm_flops(vocab, d_model, d_ff, layers, L)
    return ModelDef(
        name="transformer_lm",
        init_params=init_params,
        loss_fn=loss_fn,
        synth_batch=synth_batch,
        param_partition=_partition_rules,
        flops_per_example=flops,
        tokens_per_example=L,
        predict_fn=predict_fn,
        predict_inputs=("tokens",),
    )
