"""Model zoo: benchmark workloads from BASELINE.md configs 1-5.

Importing this package registers all built-in models with
``edl_tpu.models.base.get_model``.
"""

from edl_tpu.models.base import (
    DecodeSpec,
    ModelDef,
    bind_model,
    get_model,
    load_workspace_factory,
    register_model,
    registered_models,
)

# Built-ins register on import.
import edl_tpu.models.fit_a_line  # noqa: F401
import edl_tpu.models.mnist  # noqa: F401
import edl_tpu.models.resnet  # noqa: F401
import edl_tpu.models.transformer  # noqa: F401
import edl_tpu.models.transformer_lm  # noqa: F401
import edl_tpu.models.moe  # noqa: F401
import edl_tpu.models.pipeline_lm  # noqa: F401

__all__ = [
    "DecodeSpec",
    "ModelDef",
    "bind_model",
    "get_model",
    "load_workspace_factory",
    "register_model",
    "registered_models",
]
