"""Mixture-of-Experts LM — the expert-parallel (``ep``) model family.

Beyond the reference's scope (it never saw a model at all, SURVEY.md
§2.3), this family exists to make the mesh's ``ep`` axis load-bearing:
experts shard over ``ep``, so scaling experts means adding chips on
that axis rather than growing every chip's memory.

TPU-first routing: **static-shape capacity-based top-1 dispatch** (the
Switch-Transformer recipe) expressed entirely as einsums —

- router logits -> top-1 expert per token,
- tokens route within fixed-size GROUPS (so the one-hot dispatch
  tensor is [groups, G, E, C] with C proportional to G/E — routing
  memory and FLOPs stay LINEAR in total tokens; ungrouped capacity
  routing is quadratic and cannot fit full-size configs),
- each token's position in its expert's per-group buffer comes from a
  capacity cumulative-sum; tokens past capacity are dropped (their
  residual stream passes through unchanged),
- the ``dispatch`` one-hot scatters tokens to expert buffers and its
  gate-weighted transpose (``combine``) gathers them back.

No gathers, no dynamic shapes, no ragged anything: the dispatch/combine
einsums are MXU matmuls.  The router adds the standard load-balancing
auxiliary loss (mean fraction x mean probability per expert) so
training actually spreads load.

Roofline (measured on one v5e at batch 8, T=2048, full-step ablations):
the family's MFU ceiling is set by the CHASSIS, not the routing — with
all routing machinery replaced by one dense matmul of the same width
the step only dropped from 0.161 s to 0.141 s (r4 1536-wide experts),
so routing costs ~12% of the step while attention + the streamed vocab
xent dominate.  Consequences baked in below: expert d_ff follows the
Switch convention (== dense FFN width) to put more MXU mass behind the
fixed chassis cost, the routing group is chosen by wall time (G=256),
and a sort+``jax.lax.ragged_dot`` formulation measured SLOWER
(0.178 s/step) than the capacity einsums on this jaxlib — re-evaluate
before retrying it.  Capacity drops are reported per step
(``moe_drop_rate`` in metrics/bench) so MFU cannot hide them.

Partition rules: expert weights are [E, d_model, d_ff] sharded
``P("ep", "fsdp", "tp")``.  Pass ``ep_mesh`` to ALSO pin the expert
buffers' activation sharding (``with_sharding_constraint`` over the
``ep`` axis): storage sharding alone leaves GSPMD free to all-gather
the expert weights per step, which would make the ep axis
non-load-bearing.  With the constraint, every expert matmul runs on
its device's LOCAL expert shard and the partitioner inserts the
token<->expert redistribution collective (all-to-all on TPU
topologies; the CPU partitioner picks gather-based forms) — compiler-
inserted, like every collective in this framework (SURVEY.md §2.5).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from edl_tpu.models.base import ModelDef, divisor_at_most, register_model
from edl_tpu.models.transformer_lm import CausalSelfAttention


#: routing group width quantizer (shared largest-divisor helper)
_group_size = divisor_at_most


class MoEMlp(nn.Module):
    """Top-1 capacity-routed expert MLP over ``num_experts`` experts.

    ``ep_mesh``: optional mesh carrying an ``ep`` axis; when present
    the expert buffers get an explicit activation sharding constraint
    so every expert matmul runs on its device's local expert shard
    (instead of GSPMD all-gathering the expert weights)."""

    d_model: int
    d_ff: int
    num_experts: int
    capacity_factor: float = 1.25
    #: routing group width (tokens).  The dispatch/combine einsums cost
    #: ~2 * capacity_factor * group * d_model MACs PER TOKEN — linear in
    #: the group width — so smaller groups make routing cheaper relative
    #: to the expert MLP (2 * d_ff per token), at the price of more
    #: capacity-drop variance within each group.  512 was the r4
    #: default; see bench detail for the measured sweep.
    group: int = 256
    ep_mesh: Optional[Mesh] = None
    dtype: Any = jnp.bfloat16

    def _constrain(self, x):
        if self.ep_mesh is None or "ep" not in self.ep_mesh.axis_names:
            return x
        from jax.sharding import NamedSharding

        spec = P(*([None] * (x.ndim - 3)), "ep", None, None)
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.ep_mesh, spec)
        )

    @nn.compact
    def __call__(self, x):
        b, t, d = x.shape
        n = b * t
        e = self.num_experts
        G = _group_size(n, self.group)  # routing group width (tokens)
        g = n // G
        cap = max(1, int(self.capacity_factor * G / e))
        tokens = x.reshape(n, d)

        # Router in f32: small, numerically load-bearing.
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            tokens.astype(jnp.float32)
        )  # [n, E]
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(probs, axis=-1)  # [n]
        gate = jnp.max(probs, axis=-1)  # [n] router weight of the winner
        onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)  # [n, E]

        # Load-balancing aux loss (Switch): e * sum_e fraction_e * prob_e.
        frac = jnp.mean(onehot, axis=0)
        mean_prob = jnp.mean(probs, axis=0)
        self.sow("intermediates", "aux_loss", e * jnp.sum(frac * mean_prob))

        # Position of each token within its expert's PER-GROUP capacity
        # buffer: exclusive cumsum within the group.  Static shapes
        # throughout — tokens at position >= cap are DROPPED (pass
        # through on the residual stream), the standard capacity
        # tradeoff.
        oh_g = onehot.reshape(g, G, e)
        pos = jnp.cumsum(oh_g, axis=1) - oh_g  # [g, G, E]
        pos_in_expert = jnp.sum(pos * oh_g, axis=-1).astype(jnp.int32)
        keep = pos_in_expert < cap
        # Capacity-drop rate: fraction of tokens whose expert buffer was
        # full (they pass through on the residual stream).  Reported so
        # MFU numbers can't hide quality loss behind dropped compute.
        self.sow(
            "intermediates",
            "drop_rate",
            1.0 - jnp.mean(keep.astype(jnp.float32)),
        )
        slot = jax.nn.one_hot(
            jnp.where(keep, pos_in_expert, cap), cap, dtype=jnp.float32
        )  # [g, G, C] (dropped tokens one-hot to nowhere)
        dispatch = oh_g[..., None] * slot[:, :, None, :]  # [g, G, E, C]
        combine = dispatch * gate.reshape(g, G)[..., None, None]

        # Scatter tokens to expert buffers, run every expert, gather.
        wi = self.param(
            "wi",
            nn.initializers.lecun_normal(),
            (e, d, self.d_ff),
            jnp.float32,
        )
        wo = self.param(
            "wo",
            nn.initializers.lecun_normal(),
            (e, self.d_ff, d),
            jnp.float32,
        )
        tok_g = tokens.reshape(g, G, d).astype(self.dtype)
        buffers = self._constrain(
            jnp.einsum("gnec,gnd->gecd", dispatch.astype(self.dtype), tok_g)
        )
        h = jnp.einsum("gecd,edf->gecf", buffers, wi.astype(self.dtype))
        h = nn.gelu(h)
        out_buffers = self._constrain(
            jnp.einsum("gecf,efd->gecd", h, wo.astype(self.dtype))
        )
        out = jnp.einsum(
            "gnec,gecd->gnd", combine.astype(self.dtype), out_buffers
        )
        return out.reshape(b, t, d)


class MoEBlock(nn.Module):
    num_heads: int
    d_model: int
    d_ff: int
    num_experts: int
    group: int = 256
    sp_mesh: Optional[Mesh] = None
    ep_mesh: Optional[Mesh] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, kv=None):
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        attn = CausalSelfAttention(
            self.num_heads, self.d_model, self.sp_mesh, self.dtype, name="attn"
        )
        if kv is not None:
            a, pools = attn(h, kv=kv)
            x = x + a
        else:
            x = x + attn(h)
            pools = None
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        # The WHOLE serving path routes PER TOKEN (group 1): capacity
        # grouping couples tokens within a group — a decode batch
        # groups UNRELATED sequences, and a chunked prefill (ISSUE 14)
        # regroups the SAME sequence differently per chunk split — so
        # per-token routing keeps each sequence's output a pure
        # function of its own tokens, independent of batch-mates AND
        # of where the scheduler cut its prompt (capacity never binds:
        # cap = max(1, 1.25/E) = 1 with position always 0, so chunked
        # and monolithic prefill emit identical tokens).  The group
        # width is routing-only (no params), so the swap is free;
        # training keeps the capacity grouping.
        group = 1 if kv is not None else self.group
        out = x + MoEMlp(
            self.d_model,
            self.d_ff,
            self.num_experts,
            group=group,
            ep_mesh=self.ep_mesh,
            dtype=self.dtype,
            name="moe",
        )(h)
        return out if kv is None else (out, pools)


class MoELM(nn.Module):
    vocab_size: int
    d_model: int
    d_ff: int
    num_heads: int
    num_layers: int
    num_experts: int
    max_len: int
    group: int = 256
    sp_mesh: Optional[Mesh] = None
    ep_mesh: Optional[Mesh] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, tokens, kv=None):
        from edl_tpu.models.decode import LayerKV

        embed = nn.Embed(
            self.vocab_size,
            self.d_model,
            embedding_init=nn.initializers.normal(1.0),
            name="embed",
        )
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (self.max_len, self.d_model),
        )
        if kv is not None:
            # Incremental decode (see TransformerLM.__call__): cache
            # tuple threaded per layer, features + pools returned; the
            # six-tuple form selects chunked prefill at ``offsets``.
            offsets = None
            if len(kv) == 6:
                kpool, vpool, tables, lengths, offsets, prefill = kv
            else:
                kpool, vpool, tables, lengths, prefill = kv
            if prefill == "chunk":
                T = tokens.shape[1]
                cpos = offsets[:, None] + jnp.arange(T)[None, :]
                x = (embed(tokens) + pos[cpos]).astype(self.dtype)
            elif prefill:
                T = tokens.shape[1]
                x = (embed(tokens) + pos[None, :T]).astype(self.dtype)
            else:
                x = (
                    embed(tokens[:, None]) + pos[lengths][:, None]
                ).astype(self.dtype)
            for i in range(self.num_layers):
                layer_kv = LayerKV(
                    kpool[i], vpool[i], tables, lengths, prefill,
                    offsets=offsets,
                )
                x, (kl, vl) = MoEBlock(
                    self.num_heads,
                    self.d_model,
                    self.d_ff,
                    self.num_experts,
                    group=self.group,
                    sp_mesh=self.sp_mesh,
                    ep_mesh=self.ep_mesh,
                    dtype=self.dtype,
                    name=f"layer_{i}",
                )(x, kv=layer_kv)
                kpool = kpool.at[i].set(kl)
                vpool = vpool.at[i].set(vl)
            x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
            return x, kpool, vpool
        T = tokens.shape[1]
        x = (embed(tokens) + pos[None, :T]).astype(self.dtype)
        for i in range(self.num_layers):
            x = MoEBlock(
                self.num_heads,
                self.d_model,
                self.d_ff,
                self.num_experts,
                group=self.group,
                sp_mesh=self.sp_mesh,
                ep_mesh=self.ep_mesh,
                dtype=self.dtype,
                name=f"layer_{i}",
            )(x)
        return nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)


def _partition_rules(params) -> Any:
    """Expert weights shard over ``ep`` on the expert dim; everything
    else follows the LM family's tp/fsdp conventions."""

    def spec_for(path: str, x) -> P:
        if x.ndim <= 1 or "pos_embed" in path:
            return P()
        if "embedding" in path:
            return P("tp", "fsdp")
        if "moe/wi" in path:  # [E, d_model, d_ff]
            return P("ep", "fsdp", "tp")
        if "moe/wo" in path:  # [E, d_ff, d_model]
            return P("ep", "tp", "fsdp")
        if "qkv/kernel" in path:
            return P("fsdp", None, "tp", None)
        if "out/kernel" in path:
            return P("tp", None, "fsdp")
        if x.ndim == 2:
            return P("fsdp", None)
        return P()

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    leaves = [
        spec_for("/".join(str(getattr(k, "key", k)) for k in path), leaf)
        for path, leaf in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


@register_model("moe_lm")
def moe_lm(
    tiny: bool = False,
    seq_len: Optional[int] = None,
    num_experts: Optional[int] = None,
    group_size: Optional[int] = None,
    sp_mesh: Optional[Mesh] = None,
    ep_mesh: Optional[Mesh] = None,
) -> ModelDef:
    if tiny:
        vocab, d_model, d_ff, heads, layers = 256, 64, 128, 4, 2
        experts = num_experts or 4
        L = seq_len or 64
    else:
        # Expert width follows the Switch-Transformer convention:
        # d_ff == the dense FFN width (4 * d_model), NOT a fraction of
        # it.  The r4 family's 1536-wide experts left so little MXU
        # mass per routed token that the chassis (attention + vocab
        # xent) capped MFU ~0.32; at 3072 the measured v5e figure is
        # 0.385-0.39 at batch 8 (BENCH r5 sweep).
        vocab, d_model, d_ff, heads, layers = 32000, 768, 3072, 12, 12
        experts = num_experts or 8
        L = seq_len or 2048
    # Routing group 256: measured fastest tokens/s on v5e at the full
    # size (0.1742 s/step vs 0.1782 at G=512 and 0.1780 at G=128,
    # batch 8) — G was chosen by WALL TIME, not by credited FLOPs (the
    # dispatch einsums' cost is linear in G, so big G inflates the
    # credited-FLOPs MFU without moving throughput).
    group = group_size or 256
    module = MoELM(
        vocab_size=vocab,
        d_model=d_model,
        d_ff=d_ff,
        num_heads=heads,
        num_layers=layers,
        num_experts=experts,
        max_len=L,
        group=group,
        sp_mesh=sp_mesh,
        ep_mesh=ep_mesh,
    )
    sample = jnp.zeros((1, L), jnp.int32)

    def init_params(rng: jax.Array):
        return module.init(rng, sample)["params"]

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        from edl_tpu.ops.losses import best_vocab_xent

        tokens = batch["tokens"]
        labels = tokens[:, 1:]
        x, inter = module.apply(
            {"params": params},
            tokens[:, :-1],
            mutable=["intermediates"],
        )
        loss, _ = best_vocab_xent(
            x, params["embed"]["embedding"], labels, labels != 0
        )

        def _mean_of(key: str):
            vals = [
                jnp.asarray(leaf)
                for path, leaf in jax.tree_util.tree_flatten_with_path(inter)[0]
                if any(str(getattr(k, "key", k)) == key for k in path)
            ]
            return (
                sum(vals) / len(vals) if vals else jnp.float32(0)
            )

        aux = _mean_of("aux_loss")
        drop = _mean_of("drop_rate")
        total = loss + 0.01 * aux
        return total, {
            "loss": loss,
            "moe_aux_loss": aux,
            "moe_drop_rate": drop,
        }

    def predict_fn(params, inputs) -> Dict[str, jax.Array]:
        """Forward-only routed prediction (same top-1 routing as the
        train step; the router's aux/drop intermediates are discarded
        — serving reads tokens, not load-balance diagnostics)."""
        tokens = inputs["tokens"][:, :L]
        x, _ = module.apply(
            {"params": params}, tokens, mutable=["intermediates"]
        )
        logits = jnp.einsum(
            "btd,vd->btv",
            x.astype(jnp.bfloat16),
            params["embed"]["embedding"].astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        return {"tokens": jnp.argmax(logits, -1)}

    def synth_batch(rng: np.random.RandomState, n: int):
        start = rng.randint(3, vocab - 8, size=(n, 1))
        t = np.arange(L + 1)[None, :]
        tokens = 3 + ((start - 3) + t) % (vocab - 3)
        return {"tokens": tokens.astype(np.int32)}

    # Active FLOPs per example: attention/proj as a dense LM, one
    # expert's MLP per token (top-1 routing), the vocab projection,
    # AND the dispatch/combine einsums — per token those touch
    # ~2 * capacity_factor * G * d_model MACs (G = routing group
    # width), which at G=512 is the same order as the expert MLP and
    # must not be silently dropped from MFU accounting.
    att_proj = 4 * d_model * d_model
    G = min(group, L)
    route = 2 * int(1.25 * G) * d_model
    flops = (
        6
        * (layers * (att_proj + 2 * d_model * d_ff + route) + vocab * d_model)
        * L
        + 12 * layers * L * L * d_model // 2
    )
    from edl_tpu.models.transformer_lm import lm_decode_spec

    return ModelDef(
        name="moe_lm",
        init_params=init_params,
        loss_fn=loss_fn,
        synth_batch=synth_batch,
        param_partition=_partition_rules,
        flops_per_example=flops,
        tokens_per_example=L,
        predict_fn=predict_fn,
        predict_inputs=("tokens",),
        decode=lm_decode_spec(module, heads, d_model, L),
    )
