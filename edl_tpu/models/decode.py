"""Paged KV-cache primitives for incremental (autoregressive) decode.

The serving decode path (ISSUE 13 / ROADMAP item 1) splits generation
into two jit-carried-state phases:

- **prefill**: the prompt runs through the normal causal forward ONCE,
  and every layer's K/V projections are written into a block-paged
  cache pool — so the quadratic prefix recompute happens exactly once
  per sequence.
- **decode**: each subsequent token is ONE position of compute — the
  query attends over the cached K/V gathered through the sequence's
  block table, and the new token's K/V is scattered into the pool at
  its position.

The cache is EXPLICIT state (pool arrays passed in and returned, never
flax mutable collections): the serving engine AOT-lowers prefill and
decode executables from abstract shapes with the pools donated, so
steady-state decode re-uses the pool buffers in place and performs
zero XLA compiles (the ``InferenceEngine.warm`` discipline).

Paging (the Orca/vLLM recipe, host-managed): the pool is
``[layers, num_blocks, block_tokens, heads, head_dim]``; a sequence
owns an ordered list of fixed-size blocks recorded in a per-sequence
**block table** ``[max_blocks]`` of physical block ids.  Logical
position ``p`` lives at ``(table[p // block_tokens], p % block_tokens)``.
Block 0 is the TRASH block: padding rows of a decode batch (and
unallocated table tails) point at it, so their writes land somewhere
harmless and their gathers stay in range.  The free list itself lives
host-side in the serving engine (``serving.engine.KVBlockPool``) —
device code only ever sees tables.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from edl_tpu.parallel.mesh import hint_activation

#: physical block id every padding row / unallocated table slot points
#: at.  Real sequences never own block 0.
TRASH_BLOCK = 0


def write_prefill_kv(kpool_l, vpool_l, tables, k, v):
    """Scatter a prompt's per-layer K/V ``[B, P, H, D]`` into the pool
    at the sequences' first ``P // block_tokens`` blocks.  ``P`` must
    be a multiple of the pool's block_tokens (the engine pads prompts
    to block-aligned buckets).  Returns the updated pools."""
    nb, bt, h, d = kpool_l.shape
    b, p = k.shape[0], k.shape[1]
    nblk = p // bt
    blocks = tables[:, :nblk]  # [B, nblk]
    k_b = k.reshape(b, nblk, bt, h, d)
    v_b = v.reshape(b, nblk, bt, h, d)
    return (
        kpool_l.at[blocks].set(k_b.astype(kpool_l.dtype)),
        vpool_l.at[blocks].set(v_b.astype(vpool_l.dtype)),
    )


def write_chunk_kv(kpool_l, vpool_l, tables, offsets, k, v):
    """Scatter one prompt CHUNK's per-layer K/V ``[B, C, H, D]`` into
    the pool at each row's block-aligned cache offset ``offsets[i]``
    (the positions already filled by earlier chunks).  ``C`` must be a
    multiple of block_tokens and ``offsets`` block-aligned — the
    chunked-prefill scheduler only splits prompts at block boundaries
    (the final chunk pads to its bucket like monolithic prefill).
    Returns the updated pools."""
    nb, bt, h, d = kpool_l.shape
    b, c = k.shape[0], k.shape[1]
    nblk = c // bt
    idx = (offsets // bt)[:, None] + jnp.arange(nblk)[None, :]
    blocks = jnp.take_along_axis(tables, idx, axis=1)  # [B, nblk]
    k_b = k.reshape(b, nblk, bt, h, d)
    v_b = v.reshape(b, nblk, bt, h, d)
    return (
        kpool_l.at[blocks].set(k_b.astype(kpool_l.dtype)),
        vpool_l.at[blocks].set(v_b.astype(vpool_l.dtype)),
    )


def paged_chunk_attention(q, kpool_l, vpool_l, tables, offsets):
    """Chunk-prefill attention over the paged cache.

    ``q``: [B, C, H, D] — a prompt chunk whose global positions are
    ``offsets[i] + c`` (its own K/V already written to the pool, so it
    attends to itself AND every previously-filled position).  Gathers
    each row's cache window through its (window-truncated) block table
    — the engine passes only the first ``past_bucket + chunk_bucket``
    blocks, so compute scales with the filled prefix, not the full
    context — masks keys beyond each query's global position (causal
    over the whole prefix), and returns [B, C, H, D] in f32."""
    nb, bt, h, d = kpool_l.shape
    b, mb = tables.shape
    c = q.shape[1]
    m = mb * bt
    # Head axis pinned over tp (ambient-mesh filtered: a no-op off tp
    # meshes): the gather, scores and PV einsums are all head-parallel,
    # so pinning keeps GSPMD from replicating the cache window.
    q = hint_activation(q, None, None, "tp", None)
    k_g = hint_activation(
        kpool_l[tables].reshape(b, m, h, d), None, None, "tp", None
    )
    v_g = hint_activation(
        vpool_l[tables].reshape(b, m, h, d), None, None, "tp", None
    )
    scale = 1.0 / (d ** 0.5)
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk",
        q.astype(jnp.float32),
        k_g.astype(jnp.float32),
    ) * scale
    qpos = offsets[:, None] + jnp.arange(c)[None, :]  # [B, C] global
    mask = jnp.arange(m)[None, None, :] <= qpos[:, :, None]  # [B, C, m]
    scores = jnp.where(mask[:, None, :, :], scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v_g.astype(jnp.float32))


def write_decode_kv(kpool_l, vpool_l, tables, lengths, k, v):
    """Scatter one new token's K/V ``[B, H, D]`` at each row's current
    position ``lengths[i]`` through its block table.  Padding rows
    (table full of TRASH_BLOCK, length 0) write into the trash block.
    Returns the updated pools."""
    nb, bt, h, d = kpool_l.shape
    blocks = jnp.take_along_axis(
        tables, (lengths // bt)[:, None], axis=1
    )[:, 0]  # [B]
    offs = lengths % bt
    return (
        kpool_l.at[blocks, offs].set(k.astype(kpool_l.dtype)),
        vpool_l.at[blocks, offs].set(v.astype(vpool_l.dtype)),
    )


def paged_decode_attention(q, kpool_l, vpool_l, tables, lengths):
    """One-token attention over the paged cache.

    ``q``: [B, H, D] (the new token's query, already written to the
    pool along with its K/V — it attends to itself).  Gathers each
    row's cache ``[max_blocks * block_tokens, H, D]`` through its block
    table, masks positions ``> lengths[i]`` (the new token sits AT
    ``lengths[i]``), and returns [B, H, D] in f32.
    """
    nb, bt, h, d = kpool_l.shape
    k_g = kpool_l[tables]  # [B, mb, bt, H, D]
    v_g = vpool_l[tables]
    b, mb = tables.shape
    m = mb * bt
    # Head-parallel throughout: pin the head axis over tp (no-op off
    # tp meshes) so the gathered cache stays sharded like the pool.
    q = hint_activation(q, None, "tp", None)
    k_g = hint_activation(k_g.reshape(b, m, h, d), None, None, "tp", None)
    v_g = hint_activation(v_g.reshape(b, m, h, d), None, None, "tp", None)
    scale = 1.0 / (d ** 0.5)
    scores = jnp.einsum(
        "bhd,bkhd->bhk",
        q.astype(jnp.float32),
        k_g.astype(jnp.float32),
    ) * scale
    mask = jnp.arange(m)[None, :] <= lengths[:, None]  # [B, m]
    scores = jnp.where(mask[:, None, :], scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", w, v_g.astype(jnp.float32))


def cache_abstract(
    layers: int,
    num_blocks: int,
    block_tokens: int,
    heads: int,
    head_dim: int,
    dtype=jnp.bfloat16,
) -> Tuple[jax.ShapeDtypeStruct, jax.ShapeDtypeStruct]:
    """Abstract (k, v) pool shapes — what the engine's AOT warmer
    lowers the decode executables from (zero device allocation)."""
    shape = (layers, num_blocks, block_tokens, heads, head_dim)
    return (
        jax.ShapeDtypeStruct(shape, dtype),
        jax.ShapeDtypeStruct(shape, dtype),
    )


class LayerKV:
    """Per-layer cache view threaded through a model's attention
    modules.  ``mode`` switches the three phases (STATIC: the engine
    compiles prefill, chunk and decode as separate executables):

    - ``"prefill"`` — monolithic prompt forward (ISSUE 13): attention
      stays the module's own causal path, K/V scatter from position 0.
    - ``"chunk"``   — one block-aligned prompt SLICE at an explicit
      cache offset (ISSUE 14): K/V scatter at ``offsets``, attention
      runs over the gathered cache window (self + every previously-
      filled position, causally masked).
    - ``"decode"``  — one token per row at ``lengths``.

    Attention modules call exactly two hooks:

    - ``write(k, v)`` — scatter this layer's new K/V; returns the
      updated (kpool_l, vpool_l) which the module must thread back out.
    - ``attend(q, kpool_l, vpool_l)`` — paged attention for the
      chunk/decode phases ([B, T, H, D] query -> [B, T, H, D] f32);
      prefill-phase attention stays the module's own causal path (the
      math the train step uses), gated module-side on ``prefill``.
    """

    def __init__(
        self, kpool_l, vpool_l, tables, lengths, prefill, offsets=None
    ):
        self.kpool_l = kpool_l
        self.vpool_l = vpool_l
        self.tables = tables
        self.lengths = lengths
        #: accepts the legacy bool (True = monolithic prefill, False =
        #: decode) or the string "chunk"
        self.mode = (
            "chunk"
            if prefill == "chunk"
            else ("prefill" if prefill else "decode")
        )
        self.prefill = self.mode == "prefill"
        self.offsets = offsets

    def write(self, k, v):
        """k, v: [B, P, H, D] (prefill), [B, C, H, D] (chunk) or
        [B, 1, H, D] (decode)."""
        if self.mode == "prefill":
            return write_prefill_kv(
                self.kpool_l, self.vpool_l, self.tables, k, v
            )
        if self.mode == "chunk":
            return write_chunk_kv(
                self.kpool_l, self.vpool_l, self.tables, self.offsets, k, v
            )
        return write_decode_kv(
            self.kpool_l,
            self.vpool_l,
            self.tables,
            self.lengths,
            k[:, 0],
            v[:, 0],
        )

    def attend(self, q, kpool_l, vpool_l):
        """Paged attention: chunk phase (q: [B, C, H, D]) attends over
        the whole filled prefix; decode phase (q: [B, 1, H, D]) over
        the cache at ``lengths``."""
        if self.mode == "chunk":
            return paged_chunk_attention(
                q, kpool_l, vpool_l, self.tables, self.offsets
            )
        out = paged_decode_attention(
            q[:, 0], kpool_l, vpool_l, self.tables, self.lengths
        )
        return out[:, None]


def greedy_from_features(features, embedding, positions=None):
    """Tied-vocab greedy ids from pre-projection features.

    ``features``: [B, T, D]; ``embedding``: [V, D].  When ``positions``
    ([B] int32) is given, only that one position's logits are computed
    (the prefill's next-token read); otherwise T == 1 (decode).
    Returns [B] int32 ids.
    """
    if positions is not None:
        features = jnp.take_along_axis(
            features, positions[:, None, None], axis=1
        )
    logits = jnp.einsum(
        "btd,vd->btv",
        features.astype(jnp.bfloat16),
        embedding.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )
    return jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
