"""Paged KV-cache primitives for incremental (autoregressive) decode.

The serving decode path (ISSUE 13 / ROADMAP item 1) splits generation
into two jit-carried-state phases:

- **prefill**: the prompt runs through the normal causal forward ONCE,
  and every layer's K/V projections are written into a block-paged
  cache pool — so the quadratic prefix recompute happens exactly once
  per sequence.
- **decode**: each subsequent token is ONE position of compute — the
  query attends over the cached K/V gathered through the sequence's
  block table, and the new token's K/V is scattered into the pool at
  its position.

The cache is EXPLICIT state (pool arrays passed in and returned, never
flax mutable collections): the serving engine AOT-lowers prefill and
decode executables from abstract shapes with the pools donated, so
steady-state decode re-uses the pool buffers in place and performs
zero XLA compiles (the ``InferenceEngine.warm`` discipline).

Paging (the Orca/vLLM recipe, host-managed): the pool is
``[layers, num_blocks, block_tokens, heads, head_dim]``; a sequence
owns an ordered list of fixed-size blocks recorded in a per-sequence
**block table** ``[max_blocks]`` of physical block ids.  Logical
position ``p`` lives at ``(table[p // block_tokens], p % block_tokens)``.
Block 0 is the TRASH block: padding rows of a decode batch (and
unallocated table tails) point at it, so their writes land somewhere
harmless and their gathers stay in range.  The free list itself lives
host-side in the serving engine (``serving.engine.KVBlockPool``) —
device code only ever sees tables.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

#: physical block id every padding row / unallocated table slot points
#: at.  Real sequences never own block 0.
TRASH_BLOCK = 0


def write_prefill_kv(kpool_l, vpool_l, tables, k, v):
    """Scatter a prompt's per-layer K/V ``[B, P, H, D]`` into the pool
    at the sequences' first ``P // block_tokens`` blocks.  ``P`` must
    be a multiple of the pool's block_tokens (the engine pads prompts
    to block-aligned buckets).  Returns the updated pools."""
    nb, bt, h, d = kpool_l.shape
    b, p = k.shape[0], k.shape[1]
    nblk = p // bt
    blocks = tables[:, :nblk]  # [B, nblk]
    k_b = k.reshape(b, nblk, bt, h, d)
    v_b = v.reshape(b, nblk, bt, h, d)
    return (
        kpool_l.at[blocks].set(k_b.astype(kpool_l.dtype)),
        vpool_l.at[blocks].set(v_b.astype(vpool_l.dtype)),
    )


def write_decode_kv(kpool_l, vpool_l, tables, lengths, k, v):
    """Scatter one new token's K/V ``[B, H, D]`` at each row's current
    position ``lengths[i]`` through its block table.  Padding rows
    (table full of TRASH_BLOCK, length 0) write into the trash block.
    Returns the updated pools."""
    nb, bt, h, d = kpool_l.shape
    blocks = jnp.take_along_axis(
        tables, (lengths // bt)[:, None], axis=1
    )[:, 0]  # [B]
    offs = lengths % bt
    return (
        kpool_l.at[blocks, offs].set(k.astype(kpool_l.dtype)),
        vpool_l.at[blocks, offs].set(v.astype(vpool_l.dtype)),
    )


def paged_decode_attention(q, kpool_l, vpool_l, tables, lengths):
    """One-token attention over the paged cache.

    ``q``: [B, H, D] (the new token's query, already written to the
    pool along with its K/V — it attends to itself).  Gathers each
    row's cache ``[max_blocks * block_tokens, H, D]`` through its block
    table, masks positions ``> lengths[i]`` (the new token sits AT
    ``lengths[i]``), and returns [B, H, D] in f32.
    """
    nb, bt, h, d = kpool_l.shape
    k_g = kpool_l[tables]  # [B, mb, bt, H, D]
    v_g = vpool_l[tables]
    b, mb = tables.shape
    m = mb * bt
    k_g = k_g.reshape(b, m, h, d)
    v_g = v_g.reshape(b, m, h, d)
    scale = 1.0 / (d ** 0.5)
    scores = jnp.einsum(
        "bhd,bkhd->bhk",
        q.astype(jnp.float32),
        k_g.astype(jnp.float32),
    ) * scale
    mask = jnp.arange(m)[None, :] <= lengths[:, None]  # [B, m]
    scores = jnp.where(mask[:, None, :], scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", w, v_g.astype(jnp.float32))


def cache_abstract(
    layers: int,
    num_blocks: int,
    block_tokens: int,
    heads: int,
    head_dim: int,
    dtype=jnp.bfloat16,
) -> Tuple[jax.ShapeDtypeStruct, jax.ShapeDtypeStruct]:
    """Abstract (k, v) pool shapes — what the engine's AOT warmer
    lowers the decode executables from (zero device allocation)."""
    shape = (layers, num_blocks, block_tokens, heads, head_dim)
    return (
        jax.ShapeDtypeStruct(shape, dtype),
        jax.ShapeDtypeStruct(shape, dtype),
    )


class LayerKV:
    """Per-layer cache view threaded through a model's attention
    modules.  ``prefill`` switches the two phases (a STATIC flag: the
    engine compiles prefill and decode as separate executables).

    Attention modules call exactly two hooks:

    - ``write(k, v)`` — scatter this layer's new K/V; returns the
      updated (kpool_l, vpool_l) which the module must thread back out.
    - ``attend(q, kpool_l, vpool_l)`` — decode-phase paged attention
      ([B, 1, H, D] query -> [B, 1, H, D] f32); prefill-phase attention
      stays the module's own causal path (the math the train step
      uses).
    """

    def __init__(self, kpool_l, vpool_l, tables, lengths, prefill: bool):
        self.kpool_l = kpool_l
        self.vpool_l = vpool_l
        self.tables = tables
        self.lengths = lengths
        self.prefill = prefill

    def write(self, k, v):
        """k, v: [B, P, H, D] (prefill) or [B, 1, H, D] (decode)."""
        if self.prefill:
            return write_prefill_kv(
                self.kpool_l, self.vpool_l, self.tables, k, v
            )
        return write_decode_kv(
            self.kpool_l,
            self.vpool_l,
            self.tables,
            self.lengths,
            k[:, 0],
            v[:, 0],
        )

    def attend(self, q, kpool_l, vpool_l):
        """Decode-phase paged attention (q: [B, 1, H, D])."""
        out = paged_decode_attention(
            q[:, 0], kpool_l, vpool_l, self.tables, self.lengths
        )
        return out[:, None]


def greedy_from_features(features, embedding, positions=None):
    """Tied-vocab greedy ids from pre-projection features.

    ``features``: [B, T, D]; ``embedding``: [V, D].  When ``positions``
    ([B] int32) is given, only that one position's logits are computed
    (the prefill's next-token read); otherwise T == 1 (decode).
    Returns [B] int32 ids.
    """
    if positions is not None:
        features = jnp.take_along_axis(
            features, positions[:, None, None], axis=1
        )
    logits = jnp.einsum(
        "btd,vd->btv",
        features.astype(jnp.bfloat16),
        embedding.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )
    return jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
