"""Pipeline-parallel LM — the ``pp`` model family.

A decoder LM whose block stack runs through the GPipe microbatch
schedule (``parallel/pipeline.pipeline_apply``): block parameters carry
a leading STAGE dimension sharded over the mesh's ``pp`` axis, each pp
rank owns ``layers/S`` blocks, and activations hop stages via
ppermute.  Embedding, final norm, and the vocab loss run outside the
pipeline (replicated over ``pp``, sharded over ``dp`` on the batch).

Stacked-parameter trick: ONE ``LMBlock`` is initialized per layer with
its own rng, and the per-layer trees are stacked leaf-wise to
``[layers, ...]`` then reshaped ``[S, layers/S, ...]`` — so
``stage_fn`` is just "apply my ``layers/S`` blocks in order with
tree-indexed params".  No bespoke pipelined module code: the SAME
``LMBlock`` used by ``transformer_lm`` flows through the pipeline
(SURVEY.md §2.3: pipeline parallelism is absent from the reference;
this family exceeds the parity bar).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import flax.linen as nn

from edl_tpu.models.base import ModelDef, divisor_at_most, register_model
from edl_tpu.models.transformer_lm import LMBlock, lm_flops, lm_synth_batch
from edl_tpu.parallel.pipeline import pipeline_1f1b_loss, pipeline_apply


@register_model("pipeline_lm")
def pipeline_lm(
    tiny: bool = False,
    seq_len: Optional[int] = None,
    pp_mesh: Optional[Mesh] = None,
    num_stages: Optional[int] = None,
    num_microbatches: int = 4,
    schedule: str = "gpipe",
) -> ModelDef:
    """``pp_mesh``: mesh carrying the ``pp`` axis (stage count defaults
    to its size; without a mesh the stages run sequentially — same
    code path, so CPU tests and the one-chip TPU run the identical
    model).

    ``schedule``: "gpipe" (scan-under-AD; activation memory O(M)
    microbatches) or "1f1b" (one-forward-one-backward with in-schedule
    gradients; activation memory O(S), forward recompute in the
    backward sub-tick — see ``parallel/pipeline.pipeline_1f1b_loss``).

    CAVEAT (1f1b): ``pipeline_1f1b_loss`` has NO grad-free evaluation
    path — the backward sub-ticks are woven into the schedule itself,
    so calling ``loss_fn`` outside ``jax.grad`` (an eval loop, a
    validation pass) still pays the FULL backward schedule: every
    stage vjp, every grad accumulator, ~3x the forward-only FLOPs.
    Evaluation-heavy workloads should score with a "gpipe"-schedule
    (or non-pp) instance of the same params instead (ADVICE r5)."""
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    if tiny:
        vocab, d_model, d_ff, heads, layers = 256, 64, 256, 4, 4
        L = seq_len or 64
    else:
        vocab, d_model, d_ff, heads, layers = 32000, 768, 3072, 12, 12
        L = seq_len or 2048

    if num_stages is None:
        sizes = (
            dict(zip(pp_mesh.axis_names, pp_mesh.devices.shape))
            if pp_mesh is not None
            else {}
        )
        num_stages = sizes.get("pp", 1) or 1
    if layers % num_stages != 0:
        raise ValueError(
            f"{layers} layers do not split into {num_stages} stages"
        )
    per_stage = layers // num_stages

    block = LMBlock(num_heads=heads, d_model=d_model, d_ff=d_ff)

    class _Outer(nn.Module):
        """Embedding + final norm (everything OUTSIDE the pipeline)."""

        @nn.compact
        def __call__(self, tokens):
            embed = nn.Embed(
                vocab,
                d_model,
                embedding_init=nn.initializers.normal(1.0),
                name="embed",
            )
            pos = self.param(
                "pos_embed", nn.initializers.normal(0.02), (L, d_model)
            )
            x = (embed(tokens) + pos[None, : tokens.shape[1]]).astype(
                jnp.bfloat16
            )
            return x

    outer = _Outer()
    ln_f = nn.LayerNorm(dtype=jnp.float32)
    sample_tokens = jnp.zeros((1, L), jnp.int32)
    sample_x = jnp.zeros((1, L, d_model), jnp.bfloat16)

    def init_params(rng: jax.Array):
        r_outer, r_ln, r_blocks = jax.random.split(rng, 3)
        params = {
            "outer": outer.init(r_outer, sample_tokens)["params"],
            "ln_f": ln_f.init(r_ln, sample_x)["params"],
        }
        layer_rngs = jax.random.split(r_blocks, layers)
        per_layer = [
            block.init(layer_rngs[i], sample_x)["params"]
            for i in range(layers)
        ]
        stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *per_layer)
        # [layers, ...] -> [S, layers/S, ...]
        params["blocks"] = jax.tree.map(
            lambda p: p.reshape(num_stages, per_stage, *p.shape[1:]),
            stacked,
        )
        return params

    def stage_fn(stage_params, h):
        """Apply this stage's ``per_stage`` blocks in order."""
        for i in range(per_stage):
            layer_p = jax.tree.map(lambda p: p[i], stage_params)
            h = block.apply({"params": layer_p}, h)
        return h

    def features(params, tokens):
        x = outer.apply({"params": params["outer"]}, tokens)
        if pp_mesh is not None and "pp" in pp_mesh.axis_names:
            b, t, d = x.shape
            flat = pipeline_apply(
                lambda p, h: stage_fn(
                    p, h.reshape(-1, t, d)
                ).reshape(h.shape),
                params["blocks"],
                x.reshape(b, t * d),
                pp_mesh,
                # Largest divisor of b (plain min could pick an M that
                # does not divide the batch, e.g. b=6 -> M=4, and
                # pipeline_apply would reject a valid global batch).
                num_microbatches=divisor_at_most(b, num_microbatches),
            )
            x = flat.reshape(b, t, d)
        else:
            for s in range(num_stages):
                x = stage_fn(
                    jax.tree.map(lambda p: p[s], params["blocks"]), x
                )
        return ln_f.apply({"params": params["ln_f"]}, x)

    def _head_fn(head_params, h_flat, labels_mb):
        """Last-stage loss head for the 1F1B schedule: final norm +
        tied-vocab xent on ONE microbatch, returned as (sum, count) so
        microbatch combination is exactly the full-batch mean."""
        from edl_tpu.ops.losses import best_vocab_xent

        mb = h_flat.shape[0]
        y = ln_f.apply(
            {"params": head_params["ln_f"]},
            h_flat.reshape(mb, -1, d_model),
        )
        valid = labels_mb != 0
        mean, _ = best_vocab_xent(
            y, head_params["embedding"], labels_mb, valid
        )
        cnt = jnp.sum(valid.astype(jnp.float32))
        return mean * cnt, cnt

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        from edl_tpu.ops.losses import best_vocab_xent

        tokens = batch["tokens"]
        labels = tokens[:, 1:]
        piped = pp_mesh is not None and "pp" in pp_mesh.axis_names
        if schedule == "1f1b" and piped:
            x = outer.apply({"params": params["outer"]}, tokens[:, :-1])
            b, t, d = x.shape
            head_params = {
                "ln_f": params["ln_f"],
                # tied projection: the embedding receives gradient both
                # here (head) and through the outer embed lookup
                "embedding": params["outer"]["embed"]["embedding"],
            }
            loss = pipeline_1f1b_loss(
                lambda p, h: stage_fn(p, h.reshape(-1, t, d)).reshape(
                    h.shape
                ),
                _head_fn,
                params["blocks"],
                head_params,
                x.reshape(b, t * d),
                labels,
                pp_mesh,
                num_microbatches=divisor_at_most(b, num_microbatches),
            )
            return loss, {"loss": loss}
        x = features(params, tokens[:, :-1])
        loss, _ = best_vocab_xent(
            x,
            params["outer"]["embed"]["embedding"],
            labels,
            labels != 0,
        )
        return loss, {"loss": loss}

    synth_batch = lm_synth_batch(vocab, L)

    def param_partition(params) -> Any:
        """Stage dim over ``pp``; everything else replicated (tp/fsdp
        within a stage composes later — the pipeline is the axis this
        family exists to exercise)."""

        def spec_for(path, x):
            if path and path[0] == "blocks" and x.ndim >= 1:
                return P("pp")
            return P()

        flat = jax.tree_util.tree_flatten_with_path(params)[0]
        treedef = jax.tree_util.tree_structure(params)
        leaves = [
            spec_for(
                [str(getattr(k, "key", k)) for k in path], leaf
            )
            for path, leaf in flat
        ]
        return jax.tree_util.tree_unflatten(treedef, leaves)

    def predict_fn(params, inputs) -> Dict[str, jax.Array]:
        """Forward-only serving path: ALWAYS the GPipe forward
        (``features``), never the 1F1B schedule.  1F1B weaves the
        backward sub-ticks into the schedule itself (the ADVICE r5
        caveat at the ``schedule`` flag above): a grad-free caller
        still pays every stage vjp and grad accumulator, ~3x the
        forward FLOPs.  ``features`` runs the identical stacked stage
        params through ``pipeline_apply`` (pipelined over ``pp`` when
        the mesh carries the axis, sequentially otherwise), so a
        1F1B-trained checkpoint serves grad-free with no re-export."""
        tokens = inputs["tokens"][:, :L]
        x = features(params, tokens)
        logits = jnp.einsum(
            "btd,vd->btv",
            x.astype(jnp.bfloat16),
            params["outer"]["embed"]["embedding"].astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        return {"tokens": jnp.argmax(logits, -1)}

    flops = lm_flops(vocab, d_model, d_ff, layers, L)
    return ModelDef(
        name="pipeline_lm",
        init_params=init_params,
        loss_fn=loss_fn,
        synth_batch=synth_batch,
        param_partition=param_partition,
        flops_per_example=flops,
        tokens_per_example=L,
        predict_fn=predict_fn,
        predict_inputs=("tokens",),
    )
