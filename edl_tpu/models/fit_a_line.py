"""fit_a_line: linear regression on the UCI-housing-shaped problem.

Benchmark config 1 (BASELINE.md): "fit_a_line linear-regression
TrainingJob, min=max=1 trainer".  The reference ran this as an external
PaddlePaddle program; here it is the smallest ModelDef exercising the
full elastic runtime.  Synthetic data is drawn from a fixed ground-truth
affine map so loss has a known floor near the noise variance.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from edl_tpu.models.base import ModelDef, register_model

FEATURES = 13  # UCI housing feature count


@register_model("fit_a_line")
def fit_a_line(features: int = FEATURES, noise: float = 0.01) -> ModelDef:
    rng_w = np.random.RandomState(0)
    true_w = rng_w.randn(features).astype(np.float32)
    true_b = np.float32(0.5)

    def init_params(rng: jax.Array):
        kw, _ = jax.random.split(rng)
        return {
            "w": jax.random.normal(kw, (features,), jnp.float32) * 0.01,
            "b": jnp.zeros((), jnp.float32),
        }

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        pred = batch["x"] @ params["w"] + params["b"]
        loss = jnp.mean((pred - batch["y"]) ** 2)
        return loss, {"mse": loss}

    def predict_fn(params, inputs) -> Dict[str, jax.Array]:
        return {"pred": inputs["x"] @ params["w"] + params["b"]}

    def synth_batch(rng: np.random.RandomState, n: int):
        x = rng.randn(n, features).astype(np.float32)
        y = x @ true_w + true_b + noise * rng.randn(n).astype(np.float32)
        return {"x": x, "y": y.astype(np.float32)}

    return ModelDef(
        name="fit_a_line",
        init_params=init_params,
        loss_fn=loss_fn,
        synth_batch=synth_batch,
        flops_per_example=6 * features,  # fwd 2F + bwd 4F
        predict_fn=predict_fn,
        predict_inputs=("x",),
    )
