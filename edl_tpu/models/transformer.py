"""Transformer-base — benchmark config 4 (BASELINE.md): "Transformer-
base WMT en-de, elastic DP with mid-job preemption".  Also the
framework's flagship model (``__graft_entry__``).

TPU-first design decisions:

- **bfloat16 compute, float32 params/accumulators** — matmuls land on
  the MXU at full rate; softmax/layernorm accumulate in f32.
- **Static shapes** — fixed ``seq_len`` with padding masks; no dynamic
  slicing anywhere, so XLA tiles every einsum.
- **Partition rules** (``param_partition``) name how every weight
  shards over the mesh: attention/FFN kernels split over ``tp`` on the
  head/ffn dimension, embeddings over ``tp`` on vocab, everything
  optionally sharded over ``fsdp`` on the other dimension (ZeRO-style).
  Pure-DP meshes ignore the rules (axes of size 1).
- **Sequence parallelism hook** — attention is pluggable: the default
  is fused single-device attention; ``edl_tpu.ops.ring_attention``
  drops in for the ``sp`` axis (long-context path).

The reference framework never sees a model (user code was an opaque
entrypoint, ``pkg/jobparser.go:288-291``); this file exists because the
TPU rebuild owns the trainer half too (SURVEY.md §0).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from edl_tpu.models.base import ModelDef, register_model
from edl_tpu.parallel.mesh import hint_activation

#: Activation batch placement: rows over the data axes (filtered to
#: whatever the ambient mesh has — see hint_activation).
_BATCH = ("dp", "fsdp")


class MlpBlock(nn.Module):
    d_model: int
    d_ff: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.d_ff, dtype=self.dtype, name="wi")(x)
        # ffn dim over tp (matches wi's P("fsdp","tp") column split) —
        # pins the backward's transpose layouts so GSPMD never resolves
        # a mismatch by replicating the whole activation (VERDICT r4
        # weak-2: "Involuntary full rematerialization").
        h = hint_activation(h, _BATCH, None, "tp")
        h = nn.gelu(h)
        out = nn.Dense(self.d_model, dtype=self.dtype, name="wo")(h)
        return hint_activation(out, _BATCH, None, None)


class MultiHeadAttention(nn.Module):
    """Attention with a structured mask (kv padding + causal flag), so
    the hot path can dispatch to the best kernel for the backend/shape
    (``edl_tpu.ops.fused_attention``: Pallas flash kernel on TPU at
    long context, XLA's fused reference otherwise) instead of always
    materializing a dense [B, H, Tq, Tk] mask."""

    num_heads: int
    d_model: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, q_in, kv_in, kv_pad=None, causal=False):
        from edl_tpu.ops import fused_attention

        head_dim = self.d_model // self.num_heads
        if q_in is kv_in:
            # Self-attention: one fused QKV matmul (3x the MXU work per
            # dispatch instead of three skinny [d, d] matmuls).
            qkv = nn.DenseGeneral(
                features=(3, self.num_heads, head_dim),
                axis=-1,
                dtype=self.dtype,
                name="qkv",
            )(q_in)
            # heads over tp (matches the qkv kernel's head split)
            qkv = hint_activation(qkv, _BATCH, None, None, "tp", None)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        else:
            q = nn.DenseGeneral(
                features=(self.num_heads, head_dim),
                axis=-1,
                dtype=self.dtype,
                name="query",
            )(q_in)
            q = hint_activation(q, _BATCH, None, "tp", None)
            kv = nn.DenseGeneral(
                features=(2, self.num_heads, head_dim),
                axis=-1,
                dtype=self.dtype,
                name="kv",
            )(kv_in)
            kv = hint_activation(kv, _BATCH, None, None, "tp", None)
            k, v = kv[:, :, 0], kv[:, :, 1]
        out = fused_attention(q, k, v, causal=causal, kv_mask=kv_pad)
        out = nn.DenseGeneral(
            features=self.d_model,
            axis=(-2, -1),
            dtype=self.dtype,
            name="out",
        )(out)
        return hint_activation(out, _BATCH, None, None)


class EncoderLayer(nn.Module):
    num_heads: int
    d_model: int
    d_ff: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, src_pad):
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_attn")(x)
        x = x + MultiHeadAttention(
            self.num_heads, self.d_model, self.dtype, name="attn"
        )(h, h, kv_pad=src_pad)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_mlp")(x)
        return x + MlpBlock(self.d_model, self.d_ff, self.dtype, name="mlp")(h)


class DecoderLayer(nn.Module):
    num_heads: int
    d_model: int
    d_ff: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, y, enc, tgt_pad, src_pad):
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_self")(y)
        y = y + MultiHeadAttention(
            self.num_heads, self.d_model, self.dtype, name="self_attn"
        )(h, h, kv_pad=tgt_pad, causal=True)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_cross")(y)
        y = y + MultiHeadAttention(
            self.num_heads, self.d_model, self.dtype, name="cross_attn"
        )(h, enc, kv_pad=src_pad)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_mlp")(y)
        return y + MlpBlock(self.d_model, self.d_ff, self.dtype, name="mlp")(h)


class Transformer(nn.Module):
    """Encoder-decoder, transformer-base shape by default."""

    vocab_size: int = 32000
    d_model: int = 512
    d_ff: int = 2048
    num_heads: int = 8
    num_layers: int = 6
    max_len: int = 256
    dtype: Any = jnp.bfloat16

    def setup(self):
        self.embed = nn.Embed(
            self.vocab_size,
            self.d_model,
            embedding_init=nn.initializers.normal(stddev=1.0),
            name="embed",
        )
        self.pos_embed = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (self.max_len, self.d_model),
        )
        self.encoder = [
            EncoderLayer(
                self.num_heads, self.d_model, self.d_ff, self.dtype, name=f"enc_{i}"
            )
            for i in range(self.num_layers)
        ]
        self.decoder = [
            DecoderLayer(
                self.num_heads, self.d_model, self.d_ff, self.dtype, name=f"dec_{i}"
            )
            for i in range(self.num_layers)
        ]
        self.ln_out = nn.LayerNorm(dtype=jnp.float32, name="ln_out")

    def features(self, src, tgt):
        """Pre-projection decoder features: [B, Tt, d_model] f32.

        Split from ``__call__`` so the loss can run the weight-tied
        vocab projection chunked (``ops/losses.tied_vocab_xent``)
        without ever materializing [B, T, V] logits in HBM."""
        B, Ts = src.shape
        Tt = tgt.shape[1]
        src_pad = src != 0  # [B, Ts]
        tgt_pad = tgt != 0

        x = (self.embed(src) + self.pos_embed[None, :Ts]).astype(self.dtype)
        x = hint_activation(x, _BATCH, None, None)
        for layer in self.encoder:
            x = layer(x, src_pad)

        y = (self.embed(tgt) + self.pos_embed[None, :Tt]).astype(self.dtype)
        y = hint_activation(y, _BATCH, None, None)
        for layer in self.decoder:
            y = layer(y, x, tgt_pad, src_pad)

        return self.ln_out(y)

    def __call__(self, src, tgt):
        """src, tgt: [B, T] int32 (0 = pad).  Returns [B, T, V] logits."""
        y = self.features(src, tgt)
        # Weight-tied output projection (transformer-base convention).
        # bf16 operands with f32 MXU accumulation: an f32 [*, 32k-vocab]
        # matmul runs at a fraction of bf16 peak and is ~30% of model
        # FLOPs — a major MFU lever at base scale.
        logits = jnp.einsum(
            "btd,vd->btv",
            y.astype(self.dtype),
            self.embed.embedding.astype(self.dtype),
            preferred_element_type=jnp.float32,
        )
        return logits


def _partition_rules(params) -> Any:
    """PartitionSpec pytree: tp on heads/ffn/vocab, fsdp on the
    complementary dimension.  Mesh axes of size 1 make any rule a
    no-op, so one rule set serves every mesh."""

    def spec_for(path: str, x) -> P:
        if x.ndim <= 1:
            return P()  # biases, layernorm scales: replicate
        if "embedding" in path or "pos_embed" in path:
            # Vocab over tp x fsdp, d_model WHOLE: same total sharding
            # as the old P("tp", "fsdp"), but the lookup's gather then
            # produces d-complete rows (masked local gather + psum)
            # instead of d-sharded ones whose backward transpose GSPMD
            # can only fix by replicating the activations (VERDICT r4
            # weak-2).
            return P(("tp", "fsdp"), None) if "embedding" in path else P()
        if "wi/kernel" in path:  # [d_model, d_ff]
            return P("fsdp", "tp")
        if "wo/kernel" in path:  # [d_ff, d_model]
            return P("tp", "fsdp")
        if "qkv/kernel" in path or "/kv/kernel" in path:
            # [d_model, 3|2, heads, head_dim]: shard heads over tp
            return P("fsdp", None, "tp", None)
        if "query/kernel" in path:
            # [d_model, heads, head_dim]: shard heads over tp
            return P("fsdp", "tp", None)
        if "out/kernel" in path:  # [heads, head_dim, d_model]
            return P("tp", None, "fsdp")
        if x.ndim == 2:
            return P("fsdp", None)
        return P()

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    specs = {}
    for path, leaf in flat:
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        specs[name] = spec_for(name, leaf)
    # rebuild tree in the same structure
    treedef = jax.tree_util.tree_structure(params)
    leaves = [
        specs["/".join(str(getattr(k, "key", k)) for k in path)]
        for path, _ in flat
    ]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def _make(
    vocab_size: int,
    d_model: int,
    d_ff: int,
    num_heads: int,
    num_layers: int,
    seq_len: int,
    name: str,
) -> ModelDef:
    module = Transformer(
        vocab_size=vocab_size,
        d_model=d_model,
        d_ff=d_ff,
        num_heads=num_heads,
        num_layers=num_layers,
        max_len=seq_len,
    )
    sample = jnp.zeros((1, seq_len), jnp.int32)

    def init_params(rng: jax.Array):
        return module.init(rng, sample, sample)["params"]

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        from edl_tpu.ops.losses import best_vocab_xent

        src, tgt = batch["src"], batch["tgt"]
        # Decoder consumes the full-length tgt (position i predicts
        # token i+1 under the causal mask; the last position's output
        # is sliced off before the loss).  Keeping T a power-of-two
        # instead of T-1 keeps every attention block MXU-tileable.
        labels = tgt[:, 1:]
        y = module.apply(
            {"params": params}, src, tgt, method=Transformer.features
        )
        loss, acc = best_vocab_xent(
            y[:, :-1], params["embed"]["embedding"], labels, labels != 0
        )
        return loss, {"loss": loss, "token_accuracy": acc}

    def predict_fn(params, inputs) -> Dict[str, jax.Array]:
        """Forward-only translation scoring: decoder features -> tied
        vocab logits -> greedy next-token ids (logits stay on device;
        only the argmax ids cross the serving wire)."""
        y = module.apply(
            {"params": params},
            inputs["src"],
            inputs["tgt"],
            method=Transformer.features,
        )
        logits = jnp.einsum(
            "btd,vd->btv",
            y.astype(jnp.bfloat16),
            params["embed"]["embedding"].astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        return {"tokens": jnp.argmax(logits, -1)}

    def synth_batch(rng: np.random.RandomState, n: int):
        """Synthetic translation task: tgt is a deterministic function
        of src (reversal with vocab offset), so the model can actually
        learn and loss continuity is observable."""
        L = seq_len
        lengths = rng.randint(L // 2, L, size=(n,))
        src = np.zeros((n, L), np.int32)
        tgt = np.zeros((n, L), np.int32)
        body = rng.randint(3, vocab_size, size=(n, L))
        for i, ln in enumerate(lengths):
            src[i, :ln] = body[i, :ln]
            rev = body[i, :ln][::-1]
            mapped = 3 + ((rev - 3 + 1) % (vocab_size - 3))
            tgt[i, 0] = 1  # BOS
            tgt[i, 1 : ln + 1] = mapped[: L - 1]
        return {"src": src, "tgt": tgt}

    # True executed matmul FLOPs per example, fwd+bwd (6 per MAC —
    # 2 fwd, 4 bwd), PaLM-style: matmul params x tokens PLUS the
    # attention score/PV terms (12*T^2*d per attention op).  The naive
    # "6 * params * T" undercounts: decoder layers carry TWO attention
    # blocks (self + cross = 8d^2/token, not 4d^2) and the T^2 terms
    # are real MXU work.
    T = seq_len
    enc_tok = 4 * d_model * d_model + 2 * d_model * d_ff
    dec_tok = 8 * d_model * d_model + 2 * d_model * d_ff
    layer_flops = 6 * T * num_layers * (enc_tok + dec_tok)
    # Attention score/PV terms: enc self + dec cross at full T^2
    # (12*T^2*d each fwd+bwd), dec self CAUSAL at half (6*T^2*d) —
    # consistent with transformer_lm's causal accounting.
    attn_flops = (12 * 2 + 6) * num_layers * T * T * d_model
    logits_flops = 6 * T * vocab_size * d_model
    flops = layer_flops + attn_flops + logits_flops

    return ModelDef(
        name=name,
        init_params=init_params,
        loss_fn=loss_fn,
        synth_batch=synth_batch,
        param_partition=_partition_rules,
        flops_per_example=flops,
        tokens_per_example=seq_len,
        predict_fn=predict_fn,
        predict_inputs=("src", "tgt"),
    )


@register_model("transformer_base")
def transformer_base(tiny: bool = False, seq_len: Optional[int] = None) -> ModelDef:
    """Transformer-base (WMT en-de scale).  ``tiny=True`` gives the
    test/CI shape (same code path, ~100x smaller)."""
    if tiny:
        return _make(
            vocab_size=256,
            d_model=64,
            d_ff=256,
            num_heads=4,
            num_layers=2,
            seq_len=seq_len or 32,
            name="transformer_base",
        )
    return _make(
        vocab_size=32000,
        d_model=512,
        d_ff=2048,
        num_heads=8,
        num_layers=6,
        seq_len=seq_len or 256,
        name="transformer_base",
    )
