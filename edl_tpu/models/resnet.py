"""ResNet-50 — benchmark config 3 (BASELINE.md): "ResNet-50 / ImageNet,
elastic 4 -> 64 trainers, pserver -> allreduce migration".

TPU-first notes:

- **GroupNorm instead of BatchNorm.**  BatchNorm carries mutable
  batch statistics that (a) break the pure params -> loss contract the
  elastic checkpoint/restore path relies on and (b) entangle replicas
  through cross-device stat sync under a *changing* DP width — exactly
  the elasticity hazard SURVEY.md §7.4 warns about (batch semantics
  must be invariant to world size).  GroupNorm is deterministic per
  example, so resizes are bit-clean.
- bfloat16 convs (MXU), float32 norms and final logits.
- NHWC layout (TPU-native conv layout).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from edl_tpu.models.base import ModelDef, register_model


class BottleneckBlock(nn.Module):
    features: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        residual = x
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.GroupNorm, num_groups=32, dtype=jnp.float32)

        y = conv(self.features, (1, 1), name="conv1")(x)
        y = norm(name="norm1")(y)
        y = nn.relu(y)
        y = conv(self.features, (3, 3), self.strides, name="conv2")(y)
        y = norm(name="norm2")(y)
        y = nn.relu(y)
        y = conv(self.features * 4, (1, 1), name="conv3")(y)
        y = norm(name="norm3")(y)

        if residual.shape != y.shape:
            residual = conv(
                self.features * 4, (1, 1), self.strides, name="proj"
            )(x)
            residual = norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int] = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):  # x: [B, H, W, 3] float32 NHWC
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.width, (7, 7), (2, 2), use_bias=False, dtype=self.dtype, name="stem"
        )(x)
        x = nn.GroupNorm(num_groups=32, dtype=jnp.float32, name="stem_norm")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(
                    self.width * 2**i,
                    strides,
                    self.dtype,
                    name=f"stage{i}_block{j}",
                )(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def _make(image_size: int, num_classes: int, stage_sizes, width, name) -> ModelDef:
    module = ResNet(
        stage_sizes=tuple(stage_sizes), num_classes=num_classes, width=width
    )
    sample = jnp.zeros((1, image_size, image_size, 3), jnp.float32)

    def init_params(rng: jax.Array):
        return module.init(rng, sample)["params"]

    def loss_fn(params, batch, rng) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        logits = module.apply({"params": params}, batch["image"])
        labels = jax.nn.one_hot(batch["label"], num_classes)
        loss = jnp.mean(-jnp.sum(labels * jax.nn.log_softmax(logits), axis=-1))
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return loss, {"loss": loss, "accuracy": acc}

    def predict_fn(params, inputs) -> Dict[str, jax.Array]:
        logits = module.apply({"params": params}, inputs["image"])
        return {"logits": logits, "label": jnp.argmax(logits, -1)}

    def synth_batch(rng: np.random.RandomState, n: int):
        """Class-dependent spatial stripes (a brightness-only signal
        would be erased by normalization; spatial structure survives)."""
        label = rng.randint(0, num_classes, size=(n,))
        img = 0.5 * rng.randn(n, image_size, image_size, 3).astype(np.float32)
        band = max(2, image_size // num_classes)
        for c in range(num_classes):
            idx = label == c
            if idx.any():
                row = (c * image_size) // num_classes
                img[idx, row : row + band, :, :] += 2.0
        return {"image": img, "label": label.astype(np.int32)}

    # ResNet-50 @224: ~4.1 GFLOPs fwd; scale by (size/224)^2, x3 for bwd
    flops = int(3 * 4.1e9 * (image_size / 224) ** 2)
    return ModelDef(
        name=name,
        init_params=init_params,
        loss_fn=loss_fn,
        synth_batch=synth_batch,
        flops_per_example=flops,
        predict_fn=predict_fn,
        predict_inputs=("image",),
    )


@register_model("resnet50")
def resnet50(tiny: bool = False) -> ModelDef:
    """ResNet-50.  ``tiny=True`` gives a 2-2-2 stage, 32x32, 10-class
    variant for tests (same code path)."""
    if tiny:
        return _make(32, 10, (1, 1, 1), 32, "resnet50")
    return _make(224, 1000, (3, 4, 6, 3), 64, "resnet50")
