"""L3 autoscaler control loop: events + ticker -> plan -> actuation.

The reference's ``Autoscaler.Run`` (``pkg/autoscaler.go:451-485``):
select on a 5s ticker and an event channel, inventory the cluster,
detect pending jobs, dry-run the fixed point over the reschedulable
jobs, and actuate by rewriting trainer parallelism.  Same loop here,
with ``run_once`` factored out so tests drive it synchronously (the
reference's loop was untestable and only smoke-checked for liveness,
``pkg/autoscaler_test.go:29-45``).

Inventory departure (fix, don't replicate): the reference charged
*unscheduled* pending pods' requests against cluster usage
(``pkg/cluster.go:202-210`` lists all non-terminal pods), inflating
load with demand that consumes nothing physically.  We charge only
scheduled pods; unscheduled demand enters the algorithm explicitly as
``pending_tpu_demand`` (see ``algorithm.scale_dry_run``), which both
sheds room for it and stops scale-ups from stealing that room.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

from edl_tpu.autoscaler.algorithm import (
    JobView,
    PendingDemand,
    scale_all_jobs_dry_run,
)
from edl_tpu.cluster.cluster import Cluster
from edl_tpu.resource.training_job import TrainingJob

DEFAULT_LOOP_SECONDS = 5.0  # ref defaultLoopDur (pkg/autoscaler.go:30-32)
DEFAULT_MAX_LOAD_DESIRED = 0.97  # ref cmd/edl/edl.go:19-20


def wait_for_world_ack(client, timeout: float) -> bool:
    """Bounded wait for a retargeted world to re-form — the consensus
    stop agreement's actuation-side half: until every surviving member
    acks the new generation, the victims may still be stepping toward
    the agreed stop boundary, and a SIGTERM (pod deletion) or a chip
    reallocation mid-quiesce yanks them out of a live world.  Shared by
    the training lane's victim deletion and the fleet arbiter's
    preemption path (a preempted trainer's chips move to a serving
    fleet only after its world drained).  Best effort: coordinators
    without the signal (test doubles, pre-consensus versions) and
    worlds with no live trainers (``acked_members`` 0) skip the wait;
    returns False on timeout (the broken-world machinery still
    recovers, it just pays a replay)."""
    import time

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            m = client.metrics()
        except Exception:
            return True
        if not isinstance(m, dict) or "world_acked" not in m:
            return True  # pre-consensus coordinator: nothing to wait on
        if m.get("world_acked") or not m.get("acked_members"):
            return True
        time.sleep(0.5)
    return False


@dataclass
class _Event:
    type: str  # "add" | "update" | "del"  (ref eventType, :141-147)
    job: TrainingJob


@dataclass
class ScalePlan:
    """One loop iteration's outcome (for logging/metrics/tests)."""

    targets: Dict[str, int]
    diff: Dict[str, int]
    have_pending: bool
    pending: PendingDemand
    #: per-job structured decision trace (goodput-annotated): what the
    #: dry run proposed, what was observed, why it did/didn't actuate
    decisions: Optional[List[dict]] = None


class Autoscaler:
    def __init__(
        self,
        cluster: Cluster,
        max_load_desired: float = DEFAULT_MAX_LOAD_DESIRED,
        loop_seconds: float = DEFAULT_LOOP_SECONDS,
        coord_client_factory=None,
    ):
        """``coord_client_factory``: job -> coordinator client (the
        actuation handshake's transport); defaults to the HTTP client
        resolved from the job's coordinator Service.  Injectable so
        tests can point it at an in-process coordinator."""
        from edl_tpu.controller.coordclient import make_coord_client

        self.cluster = cluster
        self.max_load_desired = max_load_desired
        self.loop_seconds = loop_seconds
        self.jobs: Dict[str, TrainingJob] = {}
        self._events: "queue.Queue[_Event]" = queue.Queue()
        self._stop = threading.Event()
        self.plans: List[ScalePlan] = []
        self._coord_client = coord_client_factory or make_coord_client
        # Goodput-annotated decision log (edl_tpu.telemetry): each tick
        # records, per candidate job, the dry-run trace plus the
        # OBSERVED step rate / resize cost from the job coordinator's
        # merged trainer telemetry — elastic decisions driven by
        # measured throughput, not declared replica ranges alone
        # (Varuna/Bamboo, PAPERS.md).  Bounded; newest last.
        from edl_tpu import telemetry

        self.decision_log: List[dict] = []
        self.decision_log_max = 256
        self._recorder = telemetry.get_recorder()
        reg = telemetry.get_registry()
        self._m_ticks = reg.counter("edl_autoscaler_ticks_total")
        self._m_actuations = reg.counter("edl_autoscaler_actuations_total")
        self._g_step_rate = reg.gauge("edl_observed_step_rate")
        self._g_resize_cost = reg.gauge("edl_observed_resize_cost_seconds")
        #: goodput observation failure memo: job -> tick of last failed
        #: probe.  An unreachable coordinator (fake clusters, jobs still
        #: scheduling) must not charge its connect-retry latency to
        #: EVERY 5s tick — re-probe only every goodput_retry_ticks.
        self._tick_count = 0
        self._goodput_failed_tick: Dict[str, int] = {}
        self.goodput_retry_ticks = 20
        #: how long a scale-down waits for the retargeted world to ack
        #: (= every member, victims included, left the old world at the
        #: consensus-agreed stop boundary) before deleting victim pods
        #: — deleting earlier SIGTERMs a victim mid-quiesce and turns
        #: the clean agreed-boundary teardown into a world-break +
        #: replay for the survivors
        self.victim_drain_timeout = 20.0

    # -- event intake (ref OnAdd/OnUpdate/OnDel, :158-171) -------------------
    def on_add(self, job: TrainingJob):
        self._events.put(_Event("add", job))

    def on_update(self, job: TrainingJob):
        self._events.put(_Event("update", job))

    def on_del(self, job: TrainingJob):
        self._events.put(_Event("del", job))

    def _drain_events(self):
        """ref updateJobList (:383-402), minus the TrainerJob caching —
        the workload is re-fetched fresh each loop anyway."""
        while True:
            try:
                evt = self._events.get_nowait()
            except queue.Empty:
                return
            if evt.type in ("add", "update"):
                self.jobs[evt.job.name] = evt.job
            elif evt.type == "del":
                self.jobs.pop(evt.job.name, None)

    # -- one decision cycle ---------------------------------------------------
    def run_once(
        self, workloads=None, pods_by_job=None, pod_nodes=None
    ) -> Optional[ScalePlan]:
        """Inventory -> pending detection -> fixed-point dry run ->
        actuation.  Returns the plan (None when there was nothing to
        decide over).  ``workloads`` / ``pods_by_job`` / ``pod_nodes``:
        optional snapshots (``Cluster.trainer_workloads_map`` /
        ``job_pods_map`` / ``job_pod_nodes_map``) shared across the
        controller tick; computed here — both pod maps from ONE pod
        list — when absent."""
        self._drain_events()
        if not self.jobs:
            return None
        r = self.cluster.inquiry_resource()
        if pods_by_job is None or pod_nodes is None:
            pods = self.cluster.kube.list_pods()  # ONE pod list
            if pods_by_job is None:
                pods_by_job = self.cluster.job_pods_map(pods)
            if pod_nodes is None:
                pod_nodes = self.cluster.job_pod_nodes_map(pods)
        if workloads is None:
            workloads = self.cluster.trainer_workloads_map()  # ONE list

        views: List[tuple] = []
        demand = PendingDemand()
        have_pending = False
        for job in self.jobs.values():
            w = workloads.get(job.name)
            if w is None:
                continue  # not created yet (ref tryToRetrieve..., :424-447)
            total, running, pending, _ = pods_by_job.get(job.name, (0, 0, 0, 0))
            if total > 0 and total == pending:
                # every pod pending: the job cannot start (ref
                # findPendingJob, :406-422).  Its min-instance needs
                # become explicit demand on every axis it consumes.
                have_pending = True
                t = job.spec.trainer
                hosts = job.hosts_per_replica()  # pods per replica
                demand.tpu_chips += t.min_instance * job.tpu_per_trainer()
                demand.cpu_milli += (
                    t.min_instance * hosts * t.resources.cpu_request_milli()
                )
                demand.mem_mega += (
                    t.min_instance * hosts * t.resources.mem_request_mega()
                )
                continue  # a fully-pending job is demand, not a candidate
            views.append(
                (
                    JobView.from_job(
                        job,
                        parallelism=w.parallelism,
                        pod_nodes=pod_nodes.get(job.name),
                    ),
                    total,
                    running,
                )
            )

        # Reschedulable set: stable jobs always; every job when pending
        # exists (ref findTrainingJobsMightBeRescheduled, :487-511).
        candidates = [
            v for v, total, running in views if total == running or have_pending
        ]
        if not candidates and not demand:
            return None

        diff = scale_all_jobs_dry_run(
            candidates,
            r.deepcopy(),
            self.max_load_desired,
            pending=demand,
        )
        self._m_ticks.inc()
        self._tick_count += 1

        targets: Dict[str, int] = {}
        for v in candidates:
            if diff.get(v.name):
                targets[v.name] = v.parallelism + diff[v.name]
        applied, stop_steps, traces = self._actuate(targets, diff)
        # Decisions are journaled AFTER actuation so ``actuated``
        # reports what actually happened (a PUT that gave up under a
        # conflict storm is exactly the case the log exists for).
        decisions = self._record_decisions(
            candidates, diff, targets, have_pending, applied, stop_steps,
            traces,
        )
        plan = ScalePlan(
            targets=targets,
            diff=diff,
            have_pending=have_pending,
            pending=demand,
            decisions=decisions,
        )
        self.plans.append(plan)
        return plan

    def _observe_goodput(self, name: str) -> dict:
        """Best-effort read of the job coordinator's merged trainer
        telemetry (``GET /telemetry``): observed step rate, mean resize
        cost, cumulative steps.  Empty dict when the coordinator is
        unreachable or predates telemetry — the decision still logs,
        just without observations."""
        job = self.jobs.get(name)
        if job is None:
            return {}
        last_fail = self._goodput_failed_tick.get(name)
        if (
            last_fail is not None
            and self._tick_count - last_fail < self.goodput_retry_ticks
        ):
            return {}
        try:
            client = self._coord_client(job)
            tel = getattr(client, "telemetry", None)
            if tel is None:
                return {}
            t = tel() or {}
        except Exception:
            self._goodput_failed_tick[name] = self._tick_count
            return {}
        self._goodput_failed_tick.pop(name, None)
        merged = t.get("merged") or {}
        steps = (merged.get("counters") or {}).get("edl_steps_total") or {}
        goodput = t.get("goodput") or {}
        obs = {
            "step_rate": t.get("step_rate"),
            "resize_cost_seconds": t.get("resize_cost_seconds"),
            "steps_total": sum(steps.values()),
            # The goodput ledger's job-level read: the wall-clock
            # fraction actually spent stepping, plus its decomposition
            # (resizing[:phase] / holding / replaying / broken ...) —
            # the signal a step RATE alone cannot carry.
            "goodput_frac": goodput.get("frac"),
            "goodput_seconds": goodput.get("seconds"),
        }
        if obs["step_rate"] is not None:
            self._g_step_rate.set(obs["step_rate"], job=name)
        if obs["resize_cost_seconds"] is not None:
            self._g_resize_cost.set(obs["resize_cost_seconds"], job=name)
        return obs

    def _record_decisions(
        self, candidates, diff, targets, have_pending, applied,
        stop_steps=None, traces=None,
    ) -> List[dict]:
        """One structured decision entry per candidate: the dry-run
        trace (current -> proposed), the observed goodput inputs, and
        the reason the tick did or didn't actuate.  ``applied``: the
        per-job actuation outcome from ``_actuate``; ``stop_steps``:
        the coordinator-stamped stop step read back after a scale-down
        retarget (None otherwise); ``traces``: the per-job causal-trace
        id this decision minted — with the trainers' flight events
        carrying the same id, the whole decision-to-first-step chain
        reconstructs from the journal alone (``edl trace``).  Appended
        to the bounded ``decision_log`` and journaled to the flight
        recorder (the trace id in the NON-identity trace field, so
        chaos-soak digests stay deterministic)."""
        decisions = []
        for v in candidates:
            d = diff.get(v.name, 0)
            obs = self._observe_goodput(v.name)
            if d > 0:
                reason = f"dry run found headroom: +{d} replicas"
            elif d < 0:
                reason = (
                    "shed for pending demand"
                    if have_pending
                    else f"dry run sheds {-d} replicas"
                )
            else:
                reason = "dry run at fixed point (no diff)"
            outcome = applied.get(v.name)
            if v.name in targets and outcome != "applied":
                reason += f"; actuation {outcome or 'not attempted'}"
            trace_id = (traces or {}).get(v.name, "")
            entry = {
                "job": v.name,
                "dry_run": {
                    "current": v.parallelism,
                    "diff": d,
                    "proposed": targets.get(v.name, v.parallelism),
                },
                "observed": obs,
                "have_pending": have_pending,
                "actuated": outcome == "applied",
                "reason": reason,
                "stop_step": (stop_steps or {}).get(v.name),
                "trace_id": trace_id,
            }
            decisions.append(entry)
            self.decision_log.append(entry)
            data = {k: v2 for k, v2 in entry.items() if k != "trace_id"}
            self._recorder.record(
                "autoscaler.decision", data, trace=trace_id
            )
        del self.decision_log[: -self.decision_log_max]
        return decisions

    def _actuate(
        self, targets: Dict[str, int], diff: Dict[str, int]
    ) -> tuple:
        """ref scaleAllJobs (:339-376); the 5-retry conflict loop lives
        in Cluster.update_parallelism.  Beyond the reference: each PUT
        is paired with the coordinator handshake (SURVEY §7.1 row 4) —
        **retarget-then-PUT on scale-down** so survivors re-form the
        world before the kube Job controller kills pods, PUT-then-
        retarget on scale-up so the target grows once pods can exist.
        Scale-down additionally deletes the *specific* pods the
        coordinator dropped from the plan (pod name == EDL_POD_NAME ==
        member id) before the PUT: the reference let the kube Job
        controller choose its own victims (``pkg/autoscaler.go:
        339-376``), which can kill an active-world member and turn a
        graceful resize into a lease-timeout + replay."""
        import sys

        from edl_tpu import telemetry
        from edl_tpu.cluster.cluster import ParallelismUpdateError

        applied: Dict[str, str] = {}
        #: job -> the stop_step the coordinator stamped into the
        #: retargeted plan (scale-downs; read back for the decision log)
        stop_steps: Dict[str, Optional[int]] = {}
        #: job -> the causal-trace id THIS decision minted; it rides
        #: the prewarm hint and the retarget into ElasticPlan.trace_id,
        #: so every member journals the whole resize under it
        traces: Dict[str, str] = {}
        for name, parallelism in targets.items():
            job = self.jobs.get(name)
            if job is None:
                applied[name] = "job gone"
                continue
            trace_id = telemetry.new_trace_id()
            traces[name] = trace_id
            # Prewarm announcement FIRST — before any retarget or PUT:
            # trainers AOT-compile the incoming world size's step while
            # still stepping at the current one, so the resize window
            # this actuation triggers contains zero cold compiles
            # (zero-stall resize).  Purely advisory and best-effort: a
            # lost hint only costs the overlapped cold compile.
            self._announce_prewarm(job, parallelism, trace_id)
            scale_down = diff.get(name, 0) < 0
            if scale_down:
                client = self._retarget(job, parallelism, trace_id)
                if client is not None:
                    # ONE plan fetch serves both the decision-log stamp
                    # and the victim choice: the journaled stop_step and
                    # the deleted pods must come from the SAME plan (a
                    # rebuild during the quiesce wait would otherwise
                    # desync them), and the coordinator round-trip isn't
                    # paid twice.
                    plan = None
                    try:
                        plan = client.plan()
                    except Exception:
                        pass  # decision still logs, without the stamp
                    if plan is not None:
                        stop_steps[name] = getattr(
                            plan, "stop_step", None
                        )
                    self._delete_dropped_members(job, client, plan=plan)
            try:
                self.cluster.update_parallelism(job, parallelism)
            except ParallelismUpdateError as e:
                # Conflict storm outlasted the bounded retry policy:
                # skip THIS job this tick (the dry run recomputes from
                # live state in 5s) instead of crashing the whole tick.
                print(
                    f"[edl-autoscaler] parallelism PUT for {name} -> "
                    f"{parallelism} gave up ({e}); retrying next tick",
                    file=sys.stderr,
                )
                applied[name] = "PUT gave up (retrying next tick)"
                continue
            applied[name] = "applied"
            self._m_actuations.inc(
                direction="down" if scale_down else "up"
            )
            if not scale_down:
                self._retarget(job, parallelism, trace_id)
        return applied, stop_steps, traces

    def _announce_prewarm(
        self, job: TrainingJob, world: int, trace_id: str = ""
    ) -> None:
        """POST the planned next parallelism to the job's coordinator
        (``/prewarm``) so trainers warm exactly the incoming world
        size — carrying this decision's causal-trace id, so even the
        warm-ahead compile journals under it.  Tolerates clients
        without the endpoint (injected test doubles, older
        coordinators) — the hint is an optimization, a failure to
        deliver it must never block the actuation."""
        try:
            client = self._coord_client(job)
            hint = getattr(client, "set_prewarm", None)
            if hint is not None:
                try:
                    hint(world, trace_id=trace_id)
                except TypeError:
                    hint(world)  # pre-tracing client/double
        except Exception:
            pass  # the resize still works, with an overlapped cold compile

    def _retarget(self, job: TrainingJob, world: int, trace_id: str = ""):
        """POST the new target world to the job's coordinator.  Returns
        the client on success, None on failure.  Failure is tolerated
        (the coordinator may still be scheduling) but LOGGED — a
        persistently unreachable coordinator (bad Service, NetworkPolicy)
        must be visible; the controller's level-triggered
        ``reconcile_targets`` converges the handshake on a later tick.
        ``trace_id`` stamps the retargeted plan (ElasticPlan.trace_id)."""
        import sys

        try:
            client = self._coord_client(job)
            try:
                client.set_target_world(world, trace_id=trace_id)
            except TypeError:
                client.set_target_world(world)  # pre-tracing double
            return client
        except Exception as e:
            print(
                f"[edl-autoscaler] retarget {job.name} -> world {world} "
                f"failed (coordinator unreachable?): {e}",
                file=sys.stderr,
            )
            return None

    def _wait_for_quiesce(self, client) -> None:
        """See ``wait_for_world_ack`` (module level, shared with the
        fleet arbiter); a timeout proceeds to deletion."""
        wait_for_world_ack(client, self.victim_drain_timeout)

    def _delete_dropped_members(
        self, job: TrainingJob, client, plan=None
    ) -> List[str]:
        """Delete the pods whose member ids are registered but no
        longer in the plan's rank order (the scale-down victims the
        coordinator just chose).  Sequenced AFTER the retargeted world
        quiesces (``_wait_for_quiesce``) so the victims leave the old
        world at the consensus-agreed stop boundary before their pods
        are SIGTERMed.  ``plan``: the retargeted plan the caller
        already fetched (victims and the journaled stop_step must come
        from the same plan).  Best effort: a failure here only degrades
        to the reference's behavior (kube picks the victim)."""
        import sys

        self._wait_for_quiesce(client)
        try:
            if plan is None:
                plan = client.plan()
            members = client.members()
        except Exception as e:
            print(
                f"[edl-autoscaler] victim query for {job.name} failed: {e}",
                file=sys.stderr,
            )
            return []
        active = set(plan.members) if plan is not None else set()
        victims = sorted(m for m in members if m not in active)
        deleted = []
        for v in victims:
            try:
                if self.cluster.delete_pod(v):
                    deleted.append(v)
            except Exception as e:
                print(
                    f"[edl-autoscaler] deleting victim pod {v} failed: {e}",
                    file=sys.stderr,
                )
        return deleted

    # -- the loop (ref Run, :451-485) ----------------------------------------
    def run(self):
        while not self._stop.is_set():
            try:
                self.run_once()
            except Exception:  # keep the loop alive, like the ref's log+continue
                import traceback

                traceback.print_exc()
            self._stop.wait(self.loop_seconds)

    def stop(self):
        self._stop.set()
