"""The slice-quantized fixed-point scaling algorithm (pure functions).

TPU-native rework of the reference's decision core
(``pkg/autoscaler.go``): the same shape — ascending-fulfillment sort
(ref ``:54-64``, ``:97-129``), per-job dry run against a mutable
simulated ``ClusterResource`` (ref ``:201-291``), iterate to a fixed
point (ref ``:296-337``) — with the deltas the reference could never
have:

- **Slice quantization.** A trainer replica owns a whole TPU slice, and
  a job may additionally be limited to world sizes that divide its
  global batch (``TrainingJob.legal_world_sizes``).  So a scaling step
  is "to the next/previous *legal* world size", not ±1 pod
  (SURVEY.md §7.4 "slice-quantized autoscaling").
- **Pending-demand shedding.** The reference made room for pending jobs
  only indirectly (shed when cluster load exceeds ``max_load_desired``,
  ref ``:235-246``) — with device chips at 100% and a pending job
  queued, nothing ever shed.  Here the dry run takes the pending jobs'
  aggregate demand (chips, CPU, memory) explicitly: while free capacity
  is short of it on an axis, scale-ups of jobs competing on that axis
  pause and the least-deserving elastic jobs shed toward min; growth
  always leaves the demand reserved.
- **No livelock.** The reference scales device use up to 100% (ref
  ``:276``) but sheds when above ``max_load_desired`` (ref ``:235``) —
  at full utilization those fight forever.  Our up/down conditions are
  complementary (up to 100% of chips, shed only on oversubscription or
  pending demand), and the fixed point is additionally capped.

Deliberate reference-quirk fixes (SURVEY.md §2.1 "fix, don't
replicate"): node idle resources are *subtracted* on simulated
scale-up (the reference added them back, ``:213-216``), and scale-down
returns capacity to the shed pods' *nodes* (``JobView.pod_nodes``,
victim-first from real pod inspection) so the same fixed-point pass
can re-place a freed slice — the reference returned it to cluster
totals only (ref ``:230-249``), which with slice-quantized jumps
would strand a whole freed v5e-16.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

from edl_tpu.cluster.resources import ClusterResource
from edl_tpu.resource.training_job import TrainingJob


@dataclass
class PendingDemand:
    """Aggregate resources fully-pending jobs need to start (their
    min_instance worth).  The reference had no such notion — pending
    jobs got room only when cluster load happened to cross
    ``max_load_desired`` (ref ``pkg/autoscaler.go:235-246``), which
    never fires when chips are at 100% or the pressure is on an
    uncharged axis.  The dry run treats unmet demand as *starvation*:
    sheds fire and competing scale-ups pause until free capacity covers
    it."""

    tpu_chips: int = 0
    cpu_milli: int = 0
    mem_mega: int = 0

    def __bool__(self) -> bool:
        return bool(self.tpu_chips or self.cpu_milli or self.mem_mega)


def _starved_axes(
    r: ClusterResource, demand: PendingDemand, max_load_desired: float
) -> set:
    """Axes whose free capacity cannot cover the pending demand."""
    axes = set()
    if demand.tpu_chips and r.tpu_total - r.tpu_limit < demand.tpu_chips:
        axes.add("tpu")
    if (
        demand.cpu_milli
        and r.cpu_total_milli * max_load_desired - r.cpu_request_milli
        < demand.cpu_milli
    ):
        axes.add("cpu")
    if (
        demand.mem_mega
        and r.memory_total_mega - r.memory_request_mega < demand.mem_mega
    ):
        axes.add("mem")
    return axes


def _competes_on(j: JobView, axes: set) -> bool:
    return (
        ("tpu" in axes and j.tpu_per_trainer > 0)
        or ("cpu" in axes and j.cpu_request_milli > 0)
        or ("mem" in axes and j.mem_request_mega > 0)
    )


@dataclass
class JobView:
    """The autoscaler's read-model of one job — the analog of the
    reference's ``job`` struct (config + actuated trainer workload,
    ref ``pkg/autoscaler.go:34-37``) flattened to plain numbers so the
    algorithm stays pure and trivially testable."""

    name: str
    min_instance: int
    max_instance: int
    #: current actuated parallelism (ref ``*TrainerJob.Spec.Parallelism``)
    parallelism: int
    cpu_request_milli: int = 0
    mem_request_mega: int = 0
    #: TPU chips per trainer replica (0 = CPU-only job)
    tpu_per_trainer: int = 0
    #: Replica slice topology name (e.g. "v5e-16"); "" = any chip pool.
    slice_topology: str = ""
    #: ascending legal world sizes within [min, max]; empty = every size
    legal_sizes: List[int] = field(default_factory=list)
    elastic: bool = True
    #: fleet-arbiter scheduling priority (``TrainingJobSpec.priority``,
    #: higher = more important); the single-cluster fixed point here
    #: ignores it, the multi-job market (``edl_tpu.fleet``) orders
    #: growth by it and preempts the lowest tier first
    priority: int = 0
    #: host pods per replica (>1 for multi-host slices: the replica's
    #: pods land on `hosts` DISTINCT nodes of the slice's pool, each
    #: consuming per-pod cpu/mem and chips-per-host)
    hosts: int = 1
    #: node names of the job's CURRENT pods, victim-first (newest pod
    #: first — the coordinator drops newest-joined members on
    #: scale-down, and the multi-host path deletes highest-indexed
    #: replica Jobs).  Lets a dry-run shed return the replica's
    #: capacity to the node maps it actually occupies, so the same
    #: fixed-point pass can re-place the freed slice (the reference —
    #: and our r3 — returned it to cluster totals only, ref
    #: ``pkg/autoscaler.go:230-249``).
    pod_nodes: List[str] = field(default_factory=list)
    #: per-pod nodes THIS dry run placed on simulated scale-ups (so a
    #: later shed of a not-yet-real replica frees the simulated nodes,
    #: not a live pod's)
    _sim_placed: List[str] = field(default_factory=list)

    @staticmethod
    def from_job(
        job: TrainingJob,
        parallelism: Optional[int] = None,
        pod_nodes: Optional[List[str]] = None,
    ) -> "JobView":
        t = job.spec.trainer
        return JobView(
            pod_nodes=list(pod_nodes or []),
            name=job.name,
            min_instance=t.min_instance,
            max_instance=t.max_instance,
            parallelism=(
                parallelism if parallelism is not None else job.status.parallelism
            )
            or t.min_instance,
            cpu_request_milli=t.resources.cpu_request_milli(),
            mem_request_mega=t.resources.mem_request_mega(),
            tpu_per_trainer=job.tpu_per_trainer(),
            slice_topology=t.slice_topology if job.tpu_per_trainer() else "",
            legal_sizes=job.legal_world_sizes(),
            elastic=job.elastic(),
            priority=job.spec.priority,
            hosts=job.hosts_per_replica(),
        )

    # -- per-pod / per-replica views ----------------------------------------
    @property
    def tpu_per_pod(self) -> int:
        """Chips one POD consumes (a replica's chips split over hosts)."""
        return self.tpu_per_trainer // max(1, self.hosts)

    @property
    def cpu_per_replica(self) -> int:
        """cpu_request_milli is per POD; a replica runs ``hosts`` pods."""
        return self.cpu_request_milli * max(1, self.hosts)

    @property
    def mem_per_replica(self) -> int:
        return self.mem_request_mega * max(1, self.hosts)

    # -- legal-size stepping ------------------------------------------------
    def _sizes(self) -> List[int]:
        if self.legal_sizes:
            return self.legal_sizes
        return list(range(self.min_instance, self.max_instance + 1))

    def next_size_up(self, planned: int) -> Optional[int]:
        """Smallest legal world size strictly above ``planned``."""
        for s in self._sizes():
            if s > planned:
                return s
        return None

    def next_size_down(self, planned: int) -> Optional[int]:
        """Largest legal world size strictly below ``planned``."""
        for s in reversed(self._sizes()):
            if s < planned:
                return s
        return None

    def clamp_size(self, planned: int) -> int:
        """Largest legal size <= planned (used to clamp over-max plans)."""
        best = self._sizes()[0]
        for s in self._sizes():
            if s <= planned:
                best = s
        return best


def fulfillment(j: JobView) -> float:
    """(cur - min) / (max - min); 1.0 when min == max
    (ref ``Fulfillment()``, ``pkg/autoscaler.go:54-64``)."""
    if j.min_instance == j.max_instance:
        return 1.0
    return (j.parallelism - j.min_instance) / (j.max_instance - j.min_instance)


def sorted_jobs(
    jobs: Iterable[JobView], *filters
) -> List[JobView]:
    """Ascending by fulfillment; ties broken by TPU chips, then CPU
    request, then memory request, all ascending — smaller jobs first
    (ref ``jobs.Less`` + ``sortedJobs``, ``pkg/autoscaler.go:97-129,
    175-189``; device axis is chips instead of the nvidia quantity)."""
    out = [j for j in jobs if all(f(j) for f in filters)]
    out.sort(
        key=lambda j: (
            fulfillment(j),
            j.tpu_per_trainer,
            j.cpu_request_milli,
            j.mem_request_mega,
        )
    )
    return out


def elastic(j: JobView) -> bool:
    """ref ``elastic`` filter (``pkg/autoscaler.go:132-134``)."""
    return j.elastic


def needs_tpu(j: JobView) -> bool:
    """ref ``gpu`` filter (``pkg/autoscaler.go:137-139``)."""
    return j.tpu_per_trainer > 0


def _slice_fits_pool(r: ClusterResource, name: str, j: JobView) -> bool:
    """Shape-aware slice placement: a replica's whole slice must come
    from ONE pool of the matching topology (ICI is wired per slice —
    chips across pools are not interchangeable).  Pools that declare no
    topology stay chip-counted (tests, CPU pools, pre-labeled clusters).

    With this check, 16 free chips split across two v5e-8 pools
    correctly refuse a v5e-16 replica (SURVEY.md §7.1 row 2)."""
    pool_topo = r.nodes.pool_topology.get(name)
    if not pool_topo:
        return True
    from edl_tpu.cluster.tpu_topology import normalize_topology

    pool = normalize_topology(pool_topo)
    if pool is None:
        return True  # unrecognized label: fall back to chip counting
    if j.slice_topology:
        job_topo = normalize_topology(j.slice_topology)
        if job_topo is not None:
            return job_topo.name == pool.name
    # Untyped job (hand-built JobView): require the pool's slice unit
    # to be exactly the replica's chip count (hosts follow the shape).
    return j.tpu_per_trainer == pool.chips


def search_assignable_nodes(
    r: ClusterResource, j: JobView
) -> Optional[List[str]]:
    """Nodes for ONE replica's pods — ``j.hosts`` DISTINCT nodes, each
    with room for one pod (per-pod cpu/mem/chips) on a pool of the
    replica's slice topology.  Single-host replicas reduce to the
    reference's one-node check (``searchAssignableNode``,
    ``pkg/autoscaler.go:191-199``, extended: the chip check requires
    slice-shaped capacity, not loose chips).

    Multi-host replicas must take ALL their nodes from ONE nodepool
    (one physical slice — ICI does not span pools): free host-nodes on
    two different slices are not a slice, and admitting them would plan
    replicas GKE can never schedule.  Nodes without a pool identity
    cannot prove slice co-location, so a hosts>1 replica refuses them.
    Deterministic order so plans are reproducible (the reference
    iterated a Go map)."""
    hosts = max(1, j.hosts)

    def fits(name: str) -> bool:
        if j.cpu_request_milli > r.nodes.cpu_idle_milli[name]:
            return False
        if j.mem_request_mega > r.nodes.memory_free_mega.get(name, 0):
            return False
        if j.tpu_per_trainer > 0:
            if j.tpu_per_pod > r.nodes.tpu_free.get(name, 0):
                return False
            if not _slice_fits_pool(r, name, j):
                return False
        return True

    if hosts == 1:
        for name in sorted(r.nodes.cpu_idle_milli):
            if fits(name):
                return [name]
        return None

    by_pool: Dict[str, List[str]] = {}
    for name in sorted(r.nodes.cpu_idle_milli):
        pool = r.nodes.node_pool.get(name, "")
        if not pool:
            continue  # co-location unprovable without pool identity
        if fits(name):
            by_pool.setdefault(pool, []).append(name)
    for pool in sorted(by_pool):
        if len(by_pool[pool]) >= hosts:
            return by_pool[pool][:hosts]
    return None


def search_assignable_node(r: ClusterResource, j: JobView) -> Optional[str]:
    """Single-node view of ``search_assignable_nodes`` (the reference's
    shape; still the right call for hosts == 1 replicas)."""
    nodes = search_assignable_nodes(r, j)
    return nodes[0] if nodes else None


def _apply(r: ClusterResource, j: JobView, delta_replicas: int, nodes: Sequence[str]):
    """Mutate the simulated inventory for ``delta_replicas`` more (or
    fewer) replicas of ``j`` (the reference did this in a defer,
    ``pkg/autoscaler.go:209-217`` — with the idle-adjustment sign
    inverted, which we fix).  ``nodes``: per-POD placements (one entry
    per host pod)."""
    r.tpu_limit += j.tpu_per_trainer * delta_replicas
    r.cpu_request_milli += j.cpu_per_replica * delta_replicas
    r.memory_request_mega += j.mem_per_replica * delta_replicas
    for name in nodes:
        r.nodes.cpu_idle_milli[name] -= j.cpu_request_milli
        r.nodes.memory_free_mega[name] -= j.mem_request_mega
        if j.tpu_per_trainer > 0:
            r.nodes.tpu_free[name] = (
                r.nodes.tpu_free.get(name, 0) - j.tpu_per_pod
            )


def _free_replicas(r: ClusterResource, j: JobView, n_replicas: int):
    """Return ``n_replicas`` shed replicas' per-pod capacity to the
    node maps.  Prefers nodes this dry run itself placed (a simulated
    grow later shed), then the job's real pod placements, victim-first.
    Pods whose placement is unknown (no ``pod_nodes`` info — e.g. a
    hand-built ``JobView``) or whose node has left the inventory free
    cluster totals only, the reference's behavior (crediting a vanished
    node would fabricate schedulable capacity)."""
    for _ in range(n_replicas * max(1, j.hosts)):
        if j._sim_placed:
            name = j._sim_placed.pop()
        elif j.pod_nodes:
            name = j.pod_nodes.pop(0)
        else:
            return
        if name not in r.nodes.cpu_idle_milli:
            continue  # node gone from inventory: totals-only freeing
        r.nodes.cpu_idle_milli[name] = (
            r.nodes.cpu_idle_milli.get(name, 0) + j.cpu_request_milli
        )
        r.nodes.memory_free_mega[name] = (
            r.nodes.memory_free_mega.get(name, 0) + j.mem_request_mega
        )
        if j.tpu_per_trainer > 0:
            r.nodes.tpu_free[name] = (
                r.nodes.tpu_free.get(name, 0) + j.tpu_per_pod
            )


def scale_dry_run(
    r: ClusterResource,
    j: JobView,
    cur_diff: int,
    max_load_desired: float = 0.97,
    scale_down: bool = False,
    pending: Optional[PendingDemand] = None,
) -> int:
    """Decide one scaling step for one job against the simulated
    inventory, mutating ``r`` by whatever is decided.  Returns the
    replica delta (ref ``scaleDryRun``, ``pkg/autoscaler.go:201-291``).

    Steps move between *legal* world sizes (slice + batch quantization);
    feasibility is checked for the whole step, per replica, against the
    per-node maps.
    """
    planned = j.parallelism + cur_diff
    pending = pending or PendingDemand()
    starved = _starved_axes(r, pending, max_load_desired)

    # ======================= scale down =======================
    if scale_down:
        if planned > j.max_instance:
            # Over max (e.g. spec shrank): clamp down to the largest
            # legal size (ref ``:231-234`` stepped -1; we jump).
            target = j.clamp_size(min(planned, j.max_instance))
            delta = target - planned
            _free_replicas(r, j, -delta)
            _apply(r, j, delta, ())
            return delta
        cpu_hot = r.cpu_request_milli > r.cpu_total_milli * max_load_desired
        # Oversubscription: inventory shrank under running pods.
        tpu_over = r.tpu_limit > r.tpu_total
        mem_over = r.memory_request_mega > r.memory_total_mega
        if cpu_hot or tpu_over or mem_over or _competes_on(j, starved):
            if planned > j.min_instance:
                target = j.next_size_down(planned)
                if target is not None and target >= j.min_instance:
                    delta = target - planned
                    _free_replicas(r, j, -delta)
                    _apply(r, j, delta, ())
                    return delta
        return 0

    # ======================= scale up =========================
    if planned >= j.max_instance:
        # At (or erroneously above) max: clamp back to the largest
        # *legal* size <= max, never grow (ref ``:252-257``; plain
        # max_instance could pin an over-max job on an illegal size
        # when max itself isn't in legal_sizes).
        delta = min(0, j.clamp_size(j.max_instance) - planned)
        _free_replicas(r, j, -delta)
        _apply(r, j, delta, ())
        return delta
    if _competes_on(j, starved):
        # Free capacity doesn't yet cover the pending jobs' demand on an
        # axis this job consumes: pause its growth so sheds aren't
        # immediately re-eaten.  (Once free >= demand, growth resumes —
        # a job pending for non-capacity reasons can't freeze the
        # cluster.)
        return 0

    target = j.next_size_up(planned)
    if target is None or target > j.max_instance:
        return 0
    step = target - planned

    # Whole-step feasibility, with the pending jobs' demand reserved so
    # growth never consumes room a queued job is waiting for (otherwise
    # the fixed point would grow/shed in a loop).
    if (
        r.memory_total_mega - r.memory_request_mega - pending.mem_mega
        < j.mem_per_replica * step
    ):
        return 0  # insufficient memory (ref ``:259-263``)
    if (
        r.cpu_total_milli * max_load_desired
        - r.cpu_request_milli
        - pending.cpu_milli
        < j.cpu_per_replica * step
    ):
        return 0  # would push CPU above max_load_desired (ref ``:269-273``)
    if j.tpu_per_trainer > 0 and (
        r.tpu_total - r.tpu_limit - pending.tpu_chips
        < j.tpu_per_trainer * step
    ):
        return 0  # not enough free chips; chips may go to 100% (ref ``:275-278``)

    # Per-replica node placement (ref ``:264-267`` checked one replica
    # on one node; a quantized step places each new replica — `hosts`
    # pods on distinct nodes for multi-host slices).
    placed: List[str] = []
    for _ in range(step):
        nodes = search_assignable_nodes(r, j)
        if nodes is None:
            # Roll back trial placements and refuse the step.
            for n in placed:
                r.nodes.cpu_idle_milli[n] += j.cpu_request_milli
                r.nodes.memory_free_mega[n] += j.mem_request_mega
                if j.tpu_per_trainer > 0:
                    r.nodes.tpu_free[n] += j.tpu_per_pod
            return 0
        # Reserve on the node map immediately so the next pod sees it.
        for node in nodes:
            r.nodes.cpu_idle_milli[node] -= j.cpu_request_milli
            r.nodes.memory_free_mega[node] -= j.mem_request_mega
            if j.tpu_per_trainer > 0:
                r.nodes.tpu_free[node] = (
                    r.nodes.tpu_free.get(node, 0) - j.tpu_per_pod
                )
            placed.append(node)

    # Cluster-level totals (node maps already adjusted above); remember
    # the placements so a later shed of this simulated replica frees
    # these nodes rather than a live pod's.
    j._sim_placed.extend(placed)
    _apply(r, j, step, ())
    return step


def scale_all_jobs_dry_run(
    jobs: Sequence[JobView],
    r: ClusterResource,
    max_load_desired: float = 0.97,
    pending: Optional[PendingDemand] = None,
    max_iters: int = 100,
) -> Dict[str, int]:
    """Iterate per-job dry runs to a fixed point; returns name -> replica
    delta (ref ``scaleAllJobsDryRun``, ``pkg/autoscaler.go:296-337``).

    Forward pass scales up from the least-fulfilled job; reverse pass
    scales down from the most-fulfilled.  ``r`` is mutated (pass a
    ``deepcopy`` to keep the real inventory).  ``max_iters`` bounds the
    loop (the reference had no bound and could livelock at full device
    utilization)."""
    diff: Dict[str, int] = {j.name: 0 for j in jobs}
    sim = r  # mutated in place, like the reference's value copy
    for _ in range(max_iters):
        no_change = True
        ordered = sorted_jobs(jobs, elastic)
        for j in ordered:  # scale up, neediest first
            add = scale_dry_run(
                sim, j, diff[j.name], max_load_desired, False, pending
            )
            diff[j.name] += add
            if add != 0:
                no_change = False
        for j in reversed(ordered):  # scale down, most-fulfilled first
            add = scale_dry_run(
                sim, j, diff[j.name], max_load_desired, True, pending
            )
            diff[j.name] += add
            if add != 0:
                no_change = False
        if no_change:
            break
    return {k: v for k, v in diff.items() if v != 0}
