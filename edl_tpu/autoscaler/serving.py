"""The autoscaler's serving lane: scale inference replicas on observed
p95 latency and queue depth.

The training lane (``scaler.Autoscaler``) scales on cluster headroom
and goodput; serving load is a different signal with the same
actuation shape.  Each tick reads the serving coordinator's merged
``/telemetry`` (the PR 4/7 plumbing — replicas ship their registry
snapshots on the heartbeat cadence), derives:

- ``p95``: the 95th percentile of ``edl_serve_latency_seconds`` over a
  sliding window of merged snapshots (cumulative histograms are
  monotone, so the WINDOW DELTA is the recent-traffic histogram — a
  cold morning's backlog must not pin p95 high all day),
- ``queue_depth``: the max ``edl_serve_queue_depth`` gauge across
  replicas,

and actuates through the SAME handshake as training: mint a trace id,
announce the incoming replica count via ``/prewarm`` (a joining
replica warms its bucketed forwards before taking traffic —
``ServingReplica.start``'s warm-before-register honors the hint's
contract), then retarget.  Every decision journals into the bounded
``decision_log`` and the flight recorder under the minted id, so
``edl trace`` reconstructs decision -> plan -> replica-registered ->
first-request chains exactly like training resizes.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from edl_tpu.telemetry.aggregate import histogram_quantile


def post_drain(
    address: str,
    budget_s: float,
    timeout: Optional[float] = None,
    migrate_to: Optional[str] = None,
    trace: Optional[str] = None,
) -> dict:
    """POST /drain to one serving replica and block for its ack (the
    reply carries ``drained``).  The scale-down actuators call this
    per victim BEFORE touching the Deployment — drain-victim-ack-then-
    patch, mirroring training's consensus victim-drain wait.
    ``migrate_to`` names a surviving replica: the victim hands its
    live KV sequences over instead of waiting them out, so the ack
    arrives in O(KV transfer) rather than O(longest generation)."""
    import json
    import urllib.request

    if "://" not in address:
        address = f"http://{address}"
    body = {"budget_ms": int(budget_s * 1000.0), "wait": True}
    if migrate_to:
        body["migrate_to"] = migrate_to
    if trace:
        # the decision's causal-trace id: the victim journals its
        # drain under it, so decision -> route steer -> drain ack
        # reads as ONE chain in the merged timeline
        body["trace"] = trace
    req = urllib.request.Request(
        address.rstrip("/") + "/drain",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(
        req, timeout=timeout if timeout is not None else budget_s + 5.0
    ) as r:
        return json.loads(r.read())


class ServingLane:
    """One serving fleet's scaling loop (drive ``run_once`` from the
    controller tick, or ``run`` on a thread).

    ``coordinator``: the serving world's coordinator client (Local or
    HTTP — anything with ``telemetry``/``metrics``/``set_prewarm``/
    ``set_target_world``).  ``on_scale``: optional hook called with
    (old, new) after a successful retarget — the kube glue point where
    a Deployment's replica count follows the coordinator target (tests
    and local sim drive replica processes directly)."""

    def __init__(
        self,
        coordinator,
        min_replicas: int = 1,
        max_replicas: int = 4,
        p95_high_s: float = 0.5,
        p95_low_s: float = 0.05,
        queue_high: int = 8,
        hold_ticks: int = 2,
        on_scale=None,
        ttft_high_s: Optional[float] = None,
        victim_drain_timeout: float = 10.0,
        router=None,
    ):
        if min_replicas < 1 or max_replicas < min_replicas:
            raise ValueError(
                f"bad replica bounds [{min_replicas}, {max_replicas}]"
            )
        self.coordinator = coordinator
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.p95_high_s = p95_high_s
        self.p95_low_s = p95_low_s
        self.queue_high = queue_high
        #: consecutive low-load ticks required before shedding a
        #: replica (scale-down hysteresis: one quiet tick must not
        #: thrash the fleet a request burst will want back)
        self.hold_ticks = max(1, hold_ticks)
        self.on_scale = on_scale
        #: decode-path overload threshold on the TTFT p95 window delta
        #: (None = TTFT is observed/journaled but does not actuate —
        #: single-shot fleets have no TTFT series at all)
        self.ttft_high_s = ttft_high_s
        #: drain budget per scale-down victim (the serving analog of
        #: the training scaler's victim_drain_timeout): a victim gets
        #: this long to finish its in-flight generations before the
        #: lane gives up for this tick and retries next tick
        self.victim_drain_timeout = victim_drain_timeout
        #: the fleet front door (ISSUE 20): a RequestRouter-shaped
        #: object (``mark_draining(ids, trace=)``) or a routerd
        #: ``host:port`` string.  The lane publishes drain INTENTS to
        #: it BEFORE POSTing /drain to the victims, so new admissions
        #: steer off a victim before it can 503 a single one — the
        #: drain ack then implies the router stopped sending first.
        self.router = router
        self._low_ticks = 0
        #: cumulative rejected-request count at the previous tick: the
        #: overload signal is the per-tick DELTA, not the lifetime
        #: total (one historical 429 must not pin the fleet at max)
        self._last_rejected: Optional[float] = None
        #: sliding windows of cumulative histogram snapshots, one per
        #: metric name — p95 is computed over the window DELTA
        self._hist_windows: Dict[str, List[dict]] = {}
        self.hist_window_len = 8
        self.decision_log: List[dict] = []
        self.decision_log_max = 256

        from edl_tpu import telemetry

        self._recorder = telemetry.get_recorder()
        reg = telemetry.get_registry()
        self._m_ticks = reg.counter("edl_autoscaler_ticks_total")
        self._m_actuations = reg.counter("edl_autoscaler_actuations_total")

    # -- observation --------------------------------------------------------
    def _window_p95(
        self, hist: Optional[dict], name: str = "edl_serve_latency_seconds"
    ) -> Optional[float]:
        """p95 over the recent window: cumulative histogram now minus
        the oldest snapshot in the window (falls back to the full
        cumulative series until the window fills).  ``name`` keys the
        sliding window (latency and TTFT each keep their own)."""
        if not hist:
            return None
        merged = {"": hist} if "counts" in hist else hist
        # Collapse label-keyed series into one (unlabeled in practice).
        base = None
        for h in merged.values():
            if base is None:
                base = {
                    "buckets": list(h["buckets"]),
                    "counts": list(h["counts"]),
                    "count": h["count"],
                }
            elif list(h["buckets"]) == base["buckets"]:
                base["counts"] = [
                    a + b for a, b in zip(base["counts"], h["counts"])
                ]
                base["count"] += h["count"]
        if base is None:
            return None
        window = self._hist_windows.setdefault(name, [])
        window.append(base)
        del window[: -self.hist_window_len]
        oldest = window[0]
        if oldest is base or list(oldest["buckets"]) != base["buckets"]:
            return histogram_quantile(base, 0.95)
        delta = {
            "buckets": base["buckets"],
            "counts": [
                max(0.0, a - b)
                for a, b in zip(base["counts"], oldest["counts"])
            ],
        }
        delta["count"] = sum(delta["counts"])
        if not delta["count"]:
            return None  # no recent traffic: latency says nothing
        return histogram_quantile(delta, 0.95)

    def observe(self) -> Dict[str, Optional[float]]:
        """One read of the serving coordinator's merged telemetry."""
        tel = self.coordinator.telemetry() or {}
        merged = tel.get("merged") or {}
        hists = merged.get("histograms") or {}
        gauges = merged.get("gauges") or {}
        counters = merged.get("counters") or {}
        depth_series = gauges.get("edl_serve_queue_depth") or {}
        req_series = counters.get("edl_serve_requests_total") or {}
        rejected_cum = sum(
            v for k, v in req_series.items() if "status=rejected" in k
        )
        # Rejections since the LAST tick: the cumulative counter only
        # grows, so its lifetime value says nothing about load NOW.
        # The FIRST tick only records the baseline (a restarted lane
        # reading a fleet's lifetime total must not actuate a spurious
        # scale-up for a burst that happened hours ago).
        rejected_new = (
            max(0.0, rejected_cum - self._last_rejected)
            if self._last_rejected is not None
            else 0.0
        )
        self._last_rejected = rejected_cum
        # Decode-path signals: requests waiting for a decode slot are
        # queue pressure exactly like single-shot depth (max of both
        # drives the band), TTFT keeps its own p95 window, and KV
        # occupancy rides along for the journal/operators.
        decode_depth = gauges.get("edl_serve_decode_queue_depth") or {}
        kv = gauges.get("edl_serve_kv_occupancy") or {}
        depths = list(depth_series.values()) + list(decode_depth.values())
        return {
            "p95_latency_s": self._window_p95(
                hists.get("edl_serve_latency_seconds")
            ),
            "ttft_p95_s": self._window_p95(
                hists.get("edl_serve_ttft_seconds"),
                name="edl_serve_ttft_seconds",
            ),
            "queue_depth": max(depths) if depths else None,
            "decode_queue_depth": (
                max(decode_depth.values()) if decode_depth else None
            ),
            "kv_occupancy": max(kv.values()) if kv else None,
            "requests_total": sum(req_series.values()) or None,
            "rejected_total": rejected_new or None,
        }

    def desired_replicas(self, obs, current: int) -> tuple:
        """The band decision — (proposed, reason) from one observation.
        Mutates only the hysteresis counter.  Factored out of
        ``run_once`` so the fleet market can run the SAME p95-window-
        delta / queue / rejection signals as a bidder's hard
        requirement (``edl_tpu.fleet.bidders.ServingBidder``) while the
        arbiter owns the actuation."""
        p95 = obs.get("p95_latency_s")
        ttft = obs.get("ttft_p95_s")
        depth = obs.get("queue_depth") or 0
        rejected = obs.get("rejected_total")
        ttft_high = (
            self.ttft_high_s is not None
            and ttft is not None
            and ttft > self.ttft_high_s
        )
        overloaded = (
            (p95 is not None and p95 > self.p95_high_s)
            or ttft_high
            or depth >= self.queue_high
            or bool(rejected)
        )
        idle = (
            not overloaded
            and depth == 0
            and (p95 is None or p95 < self.p95_low_s)
        )
        proposed = current
        if overloaded:
            proposed = min(current + 1, self.max_replicas)
            self._low_ticks = 0
            reason = (
                f"overloaded (p95={p95 if p95 is None else round(p95, 4)}s"
                f" ttft={ttft if ttft is None else round(ttft, 4)}s"
                f" queue={depth} rejected={rejected or 0})"
            )
        elif idle:
            self._low_ticks += 1
            if self._low_ticks >= self.hold_ticks:
                proposed = max(current - 1, self.min_replicas)
                reason = (
                    f"idle {self._low_ticks} ticks "
                    f"(p95={p95 if p95 is None else round(p95, 4)}s)"
                )
            else:
                reason = (
                    f"idle tick {self._low_ticks}/{self.hold_ticks} "
                    "(hysteresis hold)"
                )
        else:
            self._low_ticks = 0
            reason = "within band"
        return proposed, reason

    def current_replicas(self) -> int:
        """The fleet's actuated replica target (coordinator view)."""
        snap = self.coordinator.metrics() or {}
        return int(
            snap.get("target_world") or snap.get("world_size") or 0
        ) or self.min_replicas

    # -- graceful scale-down (ISSUE 15) --------------------------------------
    def _publish_drain_intent(
        self, victim_ids: List[str], trace: str
    ) -> None:
        """Tell the router who is leaving, before anyone tells the
        victims.  Best-effort on the ROUTER side (a dark router must
        not block a scale-down — the victims' own 503s are the
        fallback steer signal), but ordered strictly BEFORE the
        drains so the victim-ack implies steering already happened."""
        if self.router is None or not victim_ids:
            return
        try:
            if isinstance(self.router, str):
                import json as _json
                import urllib.request as _rq

                addr = self.router
                if "://" not in addr:
                    addr = f"http://{addr}"
                req = _rq.Request(
                    addr.rstrip("/") + "/drain_intent",
                    data=_json.dumps(
                        {"replicas": victim_ids, "trace": trace}
                    ).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST",
                )
                with _rq.urlopen(req, timeout=5.0):
                    pass
            else:
                self.router.mark_draining(victim_ids, trace=trace or None)
        except Exception:
            pass

    def drain_victims(
        self, current: int, proposed: int, trace: str = ""
    ) -> dict:
        """Drain-victim-ack-then-patch: before a scale-down's retarget
        (and long before its Deployment patch), POST /drain to every
        victim replica and wait for the ack — so the patch can never
        yank a replica with live generations.  Victims are the plan's
        rank-order tail (the members the coordinator drops when the
        target shrinks); a victim with no address (in-process tests,
        pre-drain fleets) or an UNREACHABLE one (already dead — there
        is nothing live to yank) counts as acked.  A reachable victim
        that could NOT finish inside ``victim_drain_timeout`` does
        not: the caller skips the actuation this tick and retries —
        the drain it started keeps running, so the retry usually
        finds it finished."""
        info: dict = {"victims": [], "acked": True}
        if proposed >= current:
            return info
        plan_fn = getattr(self.coordinator, "plan", None)
        plan = plan_fn() if callable(plan_fn) else None
        if plan is None:
            return info
        members = list(plan.members)
        addresses = list(plan.addresses)
        addresses += [""] * (len(members) - len(addresses))
        # Survivor for live KV migration: the first addressed member
        # that STAYS in the plan.  Victims hand their in-flight
        # generations to it instead of waiting them out — the ack
        # latency becomes O(KV transfer); a fleet with no addressed
        # survivor (in-process tests) falls back to the bounded wait.
        migrate_to = next(
            (a for _, a in list(zip(members, addresses))[:proposed] if a),
            None,
        )
        if migrate_to:
            info["migrate_to"] = migrate_to
        victims = list(zip(members, addresses))[proposed:]
        # Front-door ordering (ISSUE 20): the router hears the drain
        # intent before any victim hears the drain.
        self._publish_drain_intent([rid for rid, _ in victims], trace)
        for rid, addr in victims:
            entry = {"replica": rid, "address": addr, "acked": True}
            if addr:
                try:
                    r = post_drain(
                        addr,
                        self.victim_drain_timeout,
                        migrate_to=migrate_to,
                        trace=trace or None,
                    )
                    entry["acked"] = bool(r.get("drained"))
                    if "migrate" in r:
                        entry["migrated"] = r.get("progress", {}).get(
                            "migrated", 0
                        )
                except Exception as e:
                    # ONLY connection-refused is evidence of death
                    # (nothing listening -> nothing live to yank; the
                    # lease reaper will drop it from the plan).  A
                    # TIMEOUT is evidence of the opposite — a live
                    # replica still draining — and any other error is
                    # unknown: both fail CLOSED (not acked, patch
                    # blocked, retried next tick; a genuinely dead
                    # victim leaves the plan via lease eviction, so
                    # blocking converges either way).
                    reason = getattr(e, "reason", e)
                    entry["acked"] = isinstance(
                        reason, ConnectionRefusedError
                    ) or isinstance(e, ConnectionRefusedError)
                    entry["unreachable"] = True
                    entry["error"] = type(e).__name__
            info["victims"].append(entry)
        info["acked"] = all(v["acked"] for v in info["victims"])
        return info

    # -- one decision cycle -------------------------------------------------
    def run_once(self) -> Optional[dict]:
        """Observe -> propose -> actuate -> journal.  Returns the
        decision entry (None when the coordinator is unreachable)."""
        try:
            obs = self.observe()
            current = self.current_replicas()
        except Exception:
            return None
        self._m_ticks.inc()
        proposed, reason = self.desired_replicas(obs, current)
        actuated = False
        trace_id = ""
        drain = None
        if proposed != current:
            from edl_tpu import telemetry

            trace_id = telemetry.new_trace_id()
            blocked = False
            if proposed < current:
                # Scale-down: drain-victim-ack-then-patch.  Victims
                # close admission and finish their generations BEFORE
                # the retarget drops them from the plan and the
                # Deployment patch deletes their pods.  No ack inside
                # the budget -> no actuation this tick (the started
                # drain keeps running; next tick retries and patches).
                try:
                    drain = self.drain_victims(
                        current, proposed, trace=trace_id
                    )
                except Exception as e:
                    # A safety interlock fails CLOSED: if the drain
                    # handshake itself broke (plan fetch raised, a
                    # bug), the patch is blocked this tick — never
                    # "drain skipped, delete anyway".
                    drain = {"victims": [], "acked": False,
                             "error": str(e)}
                if not drain["acked"]:
                    reason += "; victim drain not acked (retry next tick)"
                    blocked = True
            if not blocked:
                # Prewarm FIRST (same ordering as the training lane's
                # zero-stall handshake): a joining replica warms its
                # bucketed forwards before the retarget routes traffic.
                try:
                    self.coordinator.set_prewarm(
                        proposed, trace_id=trace_id
                    )
                except Exception:
                    pass  # advisory; the retarget still scales
                try:
                    self.coordinator.set_target_world(
                        proposed, trace_id=trace_id
                    )
                    actuated = True
                    self._m_actuations.inc(
                        direction="up" if proposed > current else "down"
                    )
                    if self.on_scale is not None:
                        try:
                            self.on_scale(current, proposed)
                        except Exception:
                            pass  # kube glue best-effort; journal stands
                except Exception as e:
                    reason += f"; retarget failed ({e})"
        entry = {
            "lane": "serving",
            "dry_run": {
                "current": current,
                "proposed": proposed,
                "diff": proposed - current,
            },
            "observed": obs,
            "actuated": actuated,
            "reason": reason,
            "trace_id": trace_id,
        }
        if drain is not None:
            entry["drain"] = drain
        self.decision_log.append(entry)
        del self.decision_log[: -self.decision_log_max]
        data = {k: v for k, v in entry.items() if k != "trace_id"}
        self._recorder.record("autoscaler.decision", data, trace=trace_id)
        return entry

    def run(self, stop_event, loop_seconds: float = 5.0) -> None:
        """Tick until ``stop_event`` is set (thread entry)."""
        while not stop_event.wait(loop_seconds):
            try:
                self.run_once()
            except Exception:
                import traceback

                traceback.print_exc()


def kube_replica_glue(cluster, job):
    """``ServingLane.on_scale`` glue for a deployed fleet: push the
    decided replica count into the serving replica Deployment through
    ``Cluster.update_serving_replicas`` (the bounded-conflict-retry
    ``update_parallelism`` idiom), closing the ROADMAP item 2 residue
    where a retarget only moved the coordinator target and the pods
    never followed.  Best-effort by the lane's contract (on_scale
    failures are swallowed there; the journal entry stands either
    way), but a retry exhaustion is still logged here so a wedged
    Deployment is visible."""

    def on_scale(old: int, new: int) -> None:
        try:
            if not cluster.update_serving_replicas(job, new):
                print(
                    f"[edl-serving] no serving Deployment for "
                    f"{job.name!r}; replica retarget {old}->{new} only "
                    "moved the coordinator target"
                )
        except Exception as e:
            print(
                f"[edl-serving] serving replica PUT {old}->{new} for "
                f"{job.name!r} failed: {e}"
            )

    return on_scale


def attach_serving_lane(autoscaler, lane: ServingLane) -> ServingLane:
    """Ride a ServingLane on a training ``Autoscaler``'s tick: every
    ``run_once`` of the training lane also ticks the serving lane, so
    one control loop owns both workloads (the Pathways posture —
    training and serving as one substrate).  Decisions flow into the
    AUTOSCALER's decision log too, so ``edl trace`` and operators read
    one journal."""
    lanes = getattr(autoscaler, "serving_lanes", None)
    if lanes is None:
        lanes = autoscaler.serving_lanes = []
        orig = autoscaler.run_once

        def run_once(*args, **kwargs):
            plan = orig(*args, **kwargs)
            for sl in list(autoscaler.serving_lanes):
                try:
                    entry = sl.run_once()
                except Exception:
                    entry = None
                if entry is not None:
                    autoscaler.decision_log.append(entry)
                    del autoscaler.decision_log[
                        : -autoscaler.decision_log_max
                    ]
            return plan

        autoscaler.run_once = run_once
    lanes.append(lane)
    return lane
