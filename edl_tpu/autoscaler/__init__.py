"""L3 autoscaler: the decision plane.

Pure algorithm in ``algorithm`` (ref ``pkg/autoscaler.go:201-337``),
event-driven control loop in ``scaler`` (ref ``:451-485``).
"""

from edl_tpu.autoscaler.algorithm import (
    JobView,
    PendingDemand,
    fulfillment,
    sorted_jobs,
    search_assignable_node,
    scale_dry_run,
    scale_all_jobs_dry_run,
)
from edl_tpu.autoscaler.scaler import Autoscaler, ScalePlan
from edl_tpu.autoscaler.serving import (
    ServingLane,
    attach_serving_lane,
    kube_replica_glue,
)

__all__ = [
    "ServingLane",
    "attach_serving_lane",
    "kube_replica_glue",
    "JobView",
    "PendingDemand",
    "fulfillment",
    "sorted_jobs",
    "search_assignable_node",
    "scale_dry_run",
    "scale_all_jobs_dry_run",
    "Autoscaler",
    "ScalePlan",
]
