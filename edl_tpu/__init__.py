"""edl_tpu — a TPU-native elastic deep-learning framework.

A from-scratch rebuild of the capabilities of PaddlePaddle EDL
(reference: qizheng09/edl — a Kubernetes TrainingJob controller +
cluster autoscaler for elastic distributed training), redesigned
TPU-first around JAX/XLA:

- The controller/autoscaler plane (reference ``pkg/controller.go``,
  ``pkg/autoscaler.go``, ``pkg/cluster.go``) schedules TrainingJobs
  against TPU pod-slice resources instead of ``nvidia.com/gpu``.
- The parameter-server gradient sync (reference ``pkg/jobparser.go:74-112``,
  external PaddlePaddle pserver processes) is eliminated entirely:
  gradient sync is a resizable allreduce over ICI inside a ``jit``-ed
  data-parallel step on a ``jax.sharding.Mesh``.
- Fault tolerance / elasticity (reference: external master + etcd,
  ``pkg/jobparser.go:174-191``) is native: a coordinator tracks trainer
  membership generations; on join/leave the runtime re-shards the
  device mesh and resumes from asynchronous host-DRAM checkpoints
  without restarting the job.

Package map:

- ``edl_tpu.resource``   — L0 TrainingJob API types + validation
- ``edl_tpu.cluster``    — L1 cluster abstraction (TPU slice inventory)
- ``edl_tpu.parser``     — L2 spec -> pod/job manifest translation
- ``edl_tpu.autoscaler`` — L3 fixed-point dry-run scaling algorithm
- ``edl_tpu.controller`` — L4 watch loop + wired job lifecycle
- ``edl_tpu.runtime``    — trainer runtime: mesh, elastic step loop
- ``edl_tpu.checkpoint`` — async host-DRAM checkpoints w/ resharding
- ``edl_tpu.parallel``   — dp/fsdp/tp/pp/sp/ep mesh + collectives
- ``edl_tpu.models``     — fit_a_line, MNIST, ResNet-50, Transformer
- ``edl_tpu.ops``        — pallas kernels (ring attention, fused ops)
- ``edl_tpu.cli``        — edl submit / list / kill / scale / local-run
"""

__version__ = "0.1.0"
