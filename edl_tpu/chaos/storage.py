"""Checkpoint-store chaos helpers.

The in-store injection points (``checkpoint.save_thread``,
``checkpoint.spill``) live inside ``HostDRAMStore`` itself (pass the
``FaultSchedule`` as its ``chaos``).  What lives here is the fault
that by nature strikes from OUTSIDE the save path: silent corruption
of an already-stored snapshot (DRAM bit flip, torn durable write that
round-tripped).  The flip deliberately does NOT refresh the recorded
digest — that is the whole point: ``HostCheckpoint.verify()`` /
``HostDRAMStore.latest_verified()`` must catch the mismatch at restore
time and fall back to the next-oldest snapshot.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from edl_tpu.checkpoint.hostdram import HostCheckpoint, HostDRAMStore


def corrupt_checkpoint(ckpt: HostCheckpoint) -> None:
    """Flip one byte in the first non-empty leaf, leaving the recorded
    digest stale (silent corruption)."""
    for i, leaf in enumerate(ckpt.leaves):
        if leaf.nbytes == 0:
            continue
        bad = np.array(leaf, copy=True)
        flat = bad.reshape(-1).view(np.uint8)
        flat[0] ^= 0xFF
        ckpt.leaves[i] = bad
        return
    raise ValueError("checkpoint has no bytes to corrupt")


def corrupt_newest(store: HostDRAMStore) -> Optional[int]:
    """Corrupt the newest materialized checkpoint in ``store``;
    returns its step (None when the store is empty).  Callers that
    need the newest *interval* save to be the victim should
    ``store.wait()`` first."""
    ckpt = store.latest()
    if ckpt is None:
        return None
    # Force the digest to be recorded BEFORE the flip (normally the
    # save worker already did this; put() too) so verify() has a
    # pre-corruption fingerprint to disagree with.
    ckpt.digest()
    corrupt_checkpoint(ckpt)
    return ckpt.step
