"""HTTP transport chaos: the coord_service wire under fault injection.

Wraps ``HTTPCoordinator`` at its single raw-I/O seam (``_open``) so
every fault passes through the PRODUCTION retry path
(``utils.retry.RetryPolicy``) — nothing is mocked above the socket.

Injection points (all step-indexed, one-shot, budget-style: the event
arg is "how many of the next requests fault"):

- ``transport.refuse``: connection refused (coordinator pod gone /
  Service not yet routing).
- ``transport.timeout``: socket timeout (network partition, GC pause).
- ``transport.slow``: the next request is delayed ``arg`` seconds
  (slow response — exercises caller deadlines, not correctness).
- ``transport.torn``: the response body is truncated mid-JSON (torn
  write / proxy reset) — must be treated as transient and retried.

Faults budgeted below the client's ``retries`` are invisible to
training state (the retry absorbs them), which is what keeps a seeded
soak bit-reproducible even though retry counts vary with wall clock.
"""

from __future__ import annotations

import errno
import socket
import threading
import time
import urllib.error

from edl_tpu.chaos.schedule import FaultSchedule
from edl_tpu.runtime.coord_service import HTTPCoordinator


class ChaosHTTPCoordinator(HTTPCoordinator):
    """Drop-in ``HTTPCoordinator`` whose wire faults come from a
    ``FaultSchedule``.  Interface-identical, so ``ElasticTrainer`` and
    the control plane take it unchanged.

    Budget mutations are locked: the trainer's heartbeat thread and the
    step loop share one client, and a budget of N must inject exactly N
    faults regardless of thread interleaving (the soak asserts exact
    injection counts)."""

    def __init__(self, address: str, schedule: FaultSchedule, **kwargs):
        super().__init__(address, **kwargs)
        self.schedule = schedule
        self._budget_lock = threading.Lock()
        self._refuse_budget = 0
        self._timeout_budget = 0
        self._torn_budget = 0
        self._slow_for = 0.0
        self.injected = {
            "refuse": 0, "timeout": 0, "slow": 0, "torn": 0
        }  # observability: the soak asserts faults actually fired

    def _pull_events(self) -> None:
        """Pull due transport events and decide THIS request's fate
        under one lock (pre-request faults only)."""
        for ev in self.schedule.due("transport.refuse"):
            self._refuse_budget += int(ev.arg or 1)
        for ev in self.schedule.due("transport.timeout"):
            self._timeout_budget += int(ev.arg or 1)
        for ev in self.schedule.due("transport.torn"):
            self._torn_budget += int(ev.arg or 1)
        for ev in self.schedule.due("transport.slow"):
            self._slow_for = max(self._slow_for, float(ev.arg or 0.05))

    def _open(self, req) -> bytes:
        with self._budget_lock:
            self._pull_events()
            refuse = timeout = False
            slow = 0.0
            if self._refuse_budget > 0:
                self._refuse_budget -= 1
                self.injected["refuse"] += 1
                refuse = True
            elif self._timeout_budget > 0:
                self._timeout_budget -= 1
                self.injected["timeout"] += 1
                timeout = True
            elif self._slow_for > 0:
                slow, self._slow_for = self._slow_for, 0.0
                self.injected["slow"] += 1
        if refuse:
            raise urllib.error.URLError(
                OSError(errno.ECONNREFUSED, "chaos: connection refused")
            )
        if timeout:
            raise socket.timeout("chaos: request timed out")
        if slow > 0:
            time.sleep(slow)
        body = super()._open(req)
        with self._budget_lock:
            if self._torn_budget > 0:
                self._torn_budget -= 1
                self.injected["torn"] += 1
                # Truncate mid-payload: json.loads fails, the retry
                # policy classifies it transient and re-requests.
                return body[: max(1, len(body) // 2)]
        return body
