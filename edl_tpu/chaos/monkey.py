"""The chaos driver: applies a ``FaultSchedule`` to a live world.

``ChaosMonkey`` binds the schedule to the objects that make up one
training world — the trainer, its coordinator (possibly wrapped in
``ChaosCoordinator``, possibly reached over a chaos HTTP transport),
and its checkpoint store — and delivers the *driver-verb* events at
step boundaries via ``ElasticTrainer.run(on_step=monkey.on_step)``:

- ``scale.target``: the autoscaler's retarget (arg: new world size).
- ``member.kill``: a trainer pod dies (graceful from the survivors'
  view: their state is intact, the resize flushes).  arg: trainer id.
- ``member.die_with_state``: a death that takes the live device state
  with it (host loss mid-step): the next resize must fall back to the
  last async checkpoint and REPLAY — deterministically, because data
  is a pure function of (seed, step) (``runtime/data.py``).
- ``member.restart``: a killed trainer rejoins.  arg: trainer id.
- ``checkpoint.corrupt``: silently corrupt the newest stored snapshot
  (see ``chaos.storage``); restore must detect via CRC and fall back.
- ``coord.restart``: the coordinator loses all state; the monkey
  re-registers the members it knows are live (the pods' own
  re-register path, exercised separately, is timing-driven).

Transport and in-store faults fire at their own injection points; the
monkey only advances the shared chaos clock they read.

Kills deregister through the coordinator's public API (the graceful-
leave path).  Eviction-by-lease-timeout is real-time-driven and
therefore lives in the non-deterministic chaos tests, not in the
bit-reproducible soak.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from edl_tpu.chaos.schedule import FaultSchedule
from edl_tpu.chaos.storage import corrupt_newest


class ChaosMonkey:
    """Step-boundary fault applier.  Pass ``on_step`` to
    ``ElasticTrainer.run``; call ``live_members`` to seed the initial
    membership it tracks."""

    def __init__(
        self,
        schedule: FaultSchedule,
        trainer,
        coordinator=None,
        store=None,
        coordinator_factory: Optional[Callable[[], object]] = None,
    ):
        """``coordinator``: the handle the monkey kills/registers
        through (defaults to ``trainer.coordinator``).
        ``coordinator_factory``: builds a fresh inner coordinator for
        ``coord.restart`` events (requires ``coordinator`` — or the
        object it reaches — to be a ``ChaosCoordinator``)."""
        self.schedule = schedule
        self.trainer = trainer
        self.coordinator = (
            coordinator if coordinator is not None else trainer.coordinator
        )
        self.store = store if store is not None else trainer.store
        self.coordinator_factory = coordinator_factory
        self.live: List[str] = []
        self.log: List[tuple] = []  # (step, verb, arg) as applied

    def track(self, member_ids) -> "ChaosMonkey":
        self.live = list(member_ids)
        return self

    # -- the hook ------------------------------------------------------------
    def on_step(self, rec) -> None:
        """ElasticTrainer.run on_step callback: advance the chaos clock
        and apply every membership/storage event now due."""
        step = rec.step
        self.schedule.advance(step)
        for ev in self.schedule.due("scale.target"):
            self.coordinator.set_target_world(int(ev.arg))
            self.log.append((step, "scale.target", ev.arg))
        for ev in self.schedule.due("member.kill"):
            self._kill(ev.arg)
            self.log.append((step, "member.kill", ev.arg))
        for ev in self.schedule.due("member.die_with_state"):
            # Quiesce in-flight saves first so the restore point is
            # the deterministic latest interval snapshot (the soak's
            # bit-reproducibility contract); the "save still in flight
            # at death" variant is non-deterministic by nature and is
            # exercised by the save-thread chaos unit tests instead.
            self.store.wait()
            self.trainer.inject_failure()
            self._kill(ev.arg)
            self.log.append((step, "member.die_with_state", ev.arg))
        for ev in self.schedule.due("member.restart"):
            if ev.arg not in self.live:
                self.live.append(ev.arg)
            self.coordinator.register(ev.arg)
            self.log.append((step, "member.restart", ev.arg))
        for ev in self.schedule.due("checkpoint.corrupt"):
            # Let in-flight saves land so the newest INTERVAL snapshot
            # is the victim (deterministic: saves are step-indexed).
            self.store.wait()
            victim = corrupt_newest(self.store)
            self.log.append((step, "checkpoint.corrupt", victim))
        for ev in self.schedule.due("coord.restart"):
            self._restart_coordinator()
            self.log.append((step, "coord.restart", None))

    # -- verbs ---------------------------------------------------------------
    def _kill(self, member_id: str) -> None:
        if member_id in self.live:
            self.live.remove(member_id)
        # The dead pod stops beating before it stops being registered
        # (a kill is not a lease timeout here — see module docstring).
        if member_id in getattr(self.trainer, "heartbeat_ids", ()):
            self.trainer.heartbeat_ids.remove(member_id)
        self.coordinator.deregister(member_id)

    def _restart_coordinator(self) -> None:
        if self.coordinator_factory is None:
            raise ValueError(
                "coord.restart scheduled but no coordinator_factory given"
            )
        target = self.coordinator
        # The restart verb lives on ChaosCoordinator; reach it through
        # an HTTP client is not possible — the soak hands the monkey
        # the server-side wrapper in that case.
        restart = getattr(target, "restart", None)
        if restart is None:
            raise TypeError(
                "coord.restart needs a ChaosCoordinator (got "
                f"{type(target).__name__})"
            )
        restart(self.coordinator_factory)
        # Surviving pods re-register (their heartbeat KeyError path
        # does this in deployment; the monkey does it synchronously so
        # the soak stays step-deterministic).
        for tid in self.live:
            target.register(tid)
