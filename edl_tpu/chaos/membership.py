"""Coordinator membership chaos: lossy heartbeats, coordinator restart.

``ChaosCoordinator`` wraps any coordinator implementation
(``LocalCoordinator`` or the HTTP client) and perturbs exactly the
membership signals the real world perturbs:

- ``coord.heartbeat.drop``: the next N heartbeats are silently lost in
  flight (the trainer believes it beat; the lease keeps aging) —
  distinct from transport.refuse, where the CLIENT sees the failure.
- ``coord.heartbeat.delay``: a heartbeat lands, but the member's lease
  is back-dated by ``arg`` seconds (slow network: the beat that
  arrives is old news).  Requires the inner coordinator to be a
  ``LocalCoordinator`` (lease state is server-side).
- ``restart()``: swap the inner coordinator for a fresh one — the
  coordinator pod restarted and lost ALL membership state.  Servers
  holding this wrapper (``CoordinatorServer`` takes any coordinator-
  shaped object) keep serving across the swap, exactly like a
  restarted pod behind a stable Service DNS name.

Trainer kill/restart events are *driver* verbs (``chaos.monkey``):
they act on the wrapped coordinator through its public API.
"""

from __future__ import annotations

from typing import Callable

from edl_tpu.chaos.schedule import FaultSchedule


class ChaosCoordinator:
    """Delegating membership-chaos wrapper; interface-identical to the
    coordinator it wraps (explicit intercepts + ``__getattr__``)."""

    def __init__(self, inner, schedule: FaultSchedule):
        self._inner = inner
        self.schedule = schedule
        self._drop_budget = 0
        self.dropped_heartbeats = 0
        self.restarts = 0

    # -- chaos verbs ---------------------------------------------------------
    def restart(self, factory: Callable[[], object]) -> None:
        """Coordinator process restart: all membership state is lost.
        ``factory`` builds the replacement (same config, empty state).
        Live trainers must re-register — either via the driver (soak)
        or the heartbeat KeyError -> re-register path in
        ``ElasticTrainer._beat_once``."""
        self._inner = factory()
        self.restarts += 1

    # -- intercepted coordinator surface -------------------------------------
    def heartbeat(self, trainer_id: str, step: int = -1):
        for ev in self.schedule.due("coord.heartbeat.drop"):
            self._drop_budget += int(ev.arg or 1)
        if self._drop_budget > 0:
            self._drop_budget -= 1
            self.dropped_heartbeats += 1
            return  # lost in flight: caller sees success, lease ages
        result = self._inner.heartbeat(trainer_id, step=step)
        # Backdate AFTER the beat lands (the beat that arrives is old
        # news: the lease reads "last heard arg seconds ago").
        for ev in self.schedule.due("coord.heartbeat.delay"):
            self._backdate(trainer_id, float(ev.arg or 0.0))
        return result

    def _backdate(self, trainer_id: str, seconds: float) -> None:
        """Age a member's lease: the next beats land ``seconds`` late.
        Reaches into LocalCoordinator internals on purpose — the lease
        clock is server-side state with no public mutator."""
        inner = self._inner
        members = getattr(inner, "_members", None)
        if members is None:
            raise TypeError(
                "coord.heartbeat.delay needs a LocalCoordinator inner "
                "(lease state is server-side)"
            )
        with inner._lock:
            m = members.get(trainer_id)
            if m is not None:
                m.last_heartbeat -= seconds

    # -- everything else delegates -------------------------------------------
    def __getattr__(self, name):
        return getattr(self._inner, name)
