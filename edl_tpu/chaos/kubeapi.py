"""Kube actuation chaos: ConflictError storms and scheduling holds.

``ChaosKube`` wraps any ``KubeAPI`` (normally ``FakeKube``) so the
control plane's actuation path — ``Cluster.update_parallelism`` and
the autoscaler tick above it — runs against the failure modes a real
API server produces:

- ``kube.conflict``: the next N ``update_workload`` calls raise
  ``ConflictError`` (optimistic-concurrency storm: a hot controller
  fighting over the same Job object).  Budgets above the retry
  policy's attempts exercise the typed give-up path
  (``cluster.ParallelismUpdateError``) the autoscaler must log-and-skip.
- ``kube.hold`` / ``kube.release``: a job's pods stick ``Pending``
  (scheduling hold — capacity crunch, taints) and later release.
  Requires a ``FakeKube`` inner (uses its ``hold_pending`` knob).
"""

from __future__ import annotations

from edl_tpu.chaos.schedule import FaultSchedule
from edl_tpu.cluster.kube import ConflictError


class ChaosKube:
    """Delegating ``KubeAPI`` wrapper; pass anywhere a ``KubeAPI``
    goes (``Cluster(ChaosKube(FakeKube(...), schedule))``)."""

    def __init__(self, inner, schedule: FaultSchedule):
        self._inner = inner
        self.schedule = schedule
        self._conflict_budget = 0
        self.injected_conflicts = 0

    def _pull_events(self) -> None:
        for ev in self.schedule.due("kube.conflict"):
            self._conflict_budget += int(ev.arg or 1)
        for ev in self.schedule.due("kube.hold"):
            self._inner.hold_pending.add(ev.arg)
        released = False
        for ev in self.schedule.due("kube.release"):
            self._inner.hold_pending.discard(ev.arg)
            released = True
        if released and hasattr(self._inner, "retry_scheduling"):
            self._inner.retry_scheduling()

    def update_workload(self, w):
        self._pull_events()
        if self._conflict_budget > 0:
            self._conflict_budget -= 1
            self.injected_conflicts += 1
            raise ConflictError(
                f"chaos: conflict storm (step {self.schedule.now})"
            )
        return self._inner.update_workload(w)

    def list_pods(self):
        self._pull_events()  # holds/releases land on the read path too
        return self._inner.list_pods()

    def __getattr__(self, name):
        return getattr(self._inner, name)
