"""Deterministic chaos injection for the EDL-TPU stack.

One seeded ``FaultSchedule`` drives named injection points through the
four layers where real failures happen — coordinator membership, the
coord_service HTTP transport, the checkpoint store, and kube actuation
— so every chaos run is bit-reproducible and every robustness claim
has a test (``tests/test_chaos.py``).  See README.md "Fault model &
chaos harness".
"""

from edl_tpu.chaos.schedule import KNOWN_POINTS, FaultEvent, FaultSchedule
from edl_tpu.chaos.membership import ChaosCoordinator
from edl_tpu.chaos.transport import ChaosHTTPCoordinator
from edl_tpu.chaos.kubeapi import ChaosKube
from edl_tpu.chaos.storage import corrupt_checkpoint, corrupt_newest
from edl_tpu.chaos.monkey import ChaosMonkey

__all__ = [
    "KNOWN_POINTS",
    "FaultEvent",
    "FaultSchedule",
    "ChaosCoordinator",
    "ChaosHTTPCoordinator",
    "ChaosKube",
    "ChaosMonkey",
    "corrupt_checkpoint",
    "corrupt_newest",
]
