"""Deterministic, seeded fault schedule — the clock of every chaos run.

The reference EDL contract is "I will add and remove trainers at any
time; you must tolerate membership churn" (PAPER.md §0).  Testing that
contract with ad-hoc monkeypatching (the pre-chaos state of this repo,
e.g. ``tests/test_elastic.py``'s hand-rolled "simulated collective
failure") gives one-off, unreproducible failures.  This module gives
every failure a **name**, a **step**, and a **seed**:

- A ``FaultEvent`` is (step, point, arg): at/after global training step
  ``step``, injection point ``point`` fires once with payload ``arg``.
- A ``FaultSchedule`` holds the seed, the event list, and the current
  step (advanced by the driver at step boundaries).  Consumers pull
  their due events with ``due(point)``; one-shot semantics make a
  replayed schedule fire the identical faults at the identical steps.
- ``roll``/``rng`` derive per-point deterministic randomness from the
  seed for rate-based faults (each point keeps its own draw counter, so
  two points never share a stream).

Injection points are free-form dotted names; the ones wired through
the stack are listed in ``KNOWN_POINTS`` (docs + typo guard).
"""

from __future__ import annotations

import random
import threading
import zlib
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Type

#: every injection point threaded through the four layers (see the
#: chaos wrappers); ``FaultSchedule(strict=True)`` rejects events
#: naming anything else.
KNOWN_POINTS = (
    # (1) coordinator membership (chaos.membership + chaos.monkey)
    "coord.heartbeat.drop",      # swallow the next N heartbeats
    "coord.heartbeat.delay",     # back-date a member's lease by arg s
    "coord.restart",             # coordinator process restart (state loss)
    "member.kill",               # trainer pod dies mid-step (arg: id)
    "member.die_with_state",     # kill + device state loss -> replay
    "member.restart",            # killed trainer rejoins (arg: id)
    "scale.target",              # autoscaler retarget (arg: world)
    # (2) coord_service HTTP transport (chaos.transport)
    "transport.refuse",          # next N requests: connection refused
    "transport.timeout",         # next N requests: socket timeout
    "transport.slow",            # next request delayed arg seconds
    "transport.torn",            # next N responses: truncated JSON
    # (3) checkpoint store (chaos.storage + hostdram hooks)
    "checkpoint.save_thread",    # async save worker dies
    "checkpoint.corrupt",        # flip bytes in the newest snapshot
    "checkpoint.spill",          # spill-dir I/O error
    "flush.spill.slow",          # resize flush's bg hash/spill stalls arg s
    # (3b) streaming restore transfer (checkpoint.transfer)
    "transfer.chunk.torn",       # flip a byte in one received chunk
    "transfer.chunk.slow",       # stall the source arg s before a send
    # (3c) sharded p2p checkpoint fabric (checkpoint.fabric)
    "fabric.replica.torn",       # a served shard rotted after its crc
                                 # was advertised (reference-digest
                                 # check must catch it; per-shard
                                 # fallback to another holder)
    "fabric.peer.lost",          # a source peer dies mid-pull
    "fabric.replica.lost",       # a stage-B replica push is dropped
    "fabric.pull.slow",          # serving peer stalls arg s pre-chunk
    # (4) kube actuation (chaos.kubeapi)
    "kube.conflict",             # next N update_workload: ConflictError
    "kube.hold",                 # job's pods stick Pending (arg: job)
    "kube.release",              # release a held job (arg: job)
    # (5) AOT prewarm (runtime.elastic._maybe_prewarm)
    "prewarm.hint.dropped",      # autoscaler prewarm hint lost en route
    # (6) steady-state batch stager (runtime.data.BatchStager)
    "stage.batch.slow",          # background stager stalls arg seconds
    "stage.batch.failed",        # stager worker fails one batch (the
                                 # consumer must fall back to staging
                                 # synchronously, not lose the step)
    # (7) data-plane step agreement (edl_tpu.consensus + elastic)
    "consensus.vote.delayed",    # member's plan poll suppressed arg s
                                 # at a retarget (the poll-skew race the
                                 # step bus exists to make harmless)
    "consensus.watchdog.trip",   # next guarded device fetch treated as
                                 # a wedged collective (deadline expiry
                                 # without the wait)
    # (8) elastic inference serving (edl_tpu.serving)
    "serve.swap.torn",           # corrupt the hot-swap candidate's bytes
                                 # (latest_verified must reject it and
                                 # the engine keep serving old weights)
    "serve.request.slow",        # batcher worker stalls arg s before a
                                 # dispatch (latency-histogram / p95
                                 # scale-up signal under test control)
    "serve.queue.full",          # force one admission rejection (the
                                 # reject-with-retry-after backpressure
                                 # path, independent of real depth)
    # (8b) serving-plane fault tolerance (ISSUE 15)
    "serve.replica.die",         # replica killed mid-generation, no
                                 # drain (SIGKILL shape: in-flight
                                 # requests fail and clients must
                                 # retry against survivors)
    "serve.dispatch.wedged",     # next prefill/chunk/decode dispatch
                                 # treated as wedged (the serving
                                 # watchdog's deterministic trip into
                                 # pool-rebuild + re-prefill recovery)
    "serve.drain.slow",          # drain wait stalls arg s per poll
                                 # (exercises the bounded drain budget)
    "serve.coord.unreachable",   # replica's serving coordinator
                                 # vanishes for arg seconds — it must
                                 # keep serving last-verified weights
                                 # and reconverge on return
    # (8c) live KV sequence migration (ISSUE 16)
    "serve.migrate.kill",        # source dies mid-push: the socket is
                                 # torn down before DONE and the dest
                                 # must free its granted blocks while
                                 # the source walks the fallback ladder
    "serve.migrate.torn",        # one received KV chunk is corrupted
                                 # in flight (per-chunk crc catches it,
                                 # dest refuses, source re-prefills the
                                 # sequence cold on the survivor)
    "serve.migrate.exhausted",   # dest KV pool reports exhaustion at
                                 # the offer (refused grant: the source
                                 # falls back to a cold re-prefill)
    "serve.migrate.swap",        # a hot swap lands on the dest between
                                 # block grant and batcher adoption —
                                 # the generation-key check must route
                                 # the sequence to re-prefill, never
                                 # mix weights generations
    # (8d) content-addressed KV prefix cache (ISSUE 17)
    "serve.prefix.evicted",      # force-evict arg (default 1) LRU
                                 # cached prefix blocks as if under
                                 # allocation pressure — a subsequent
                                 # same-prefix admission must prefill
                                 # the evicted blocks cold, correctly
    "serve.prefix.hash.skew",    # a lookup's chain hash is treated as
                                 # colliding: the stored (h_prev,
                                 # tokens) verification must reject
                                 # the entry (miss, never wrong K/V)
    # (8e) fleet front door request router (ISSUE 20)
    "route.backend.refused",     # the router's next proxy attempt is
                                 # treated as connection-refused
                                 # without contacting the backend (the
                                 # passive-health / retry-absorption
                                 # path under test control)
    "route.probe.fail",          # the next active /healthz probe of an
                                 # ejected replica is forced to fail
                                 # (it must STAY ejected until a real
                                 # probe succeeds)
    "route.stream.cut",          # the router tears down one relayed
                                 # /generate stream after its next
                                 # token line (the replica-kill shape
                                 # from the router's seat: re-drive on
                                 # a survivor, no token dup/drop)
)


@dataclass(frozen=True)
class FaultEvent:
    """One scheduled fault: at/after step ``step``, point ``point``
    fires once with payload ``arg`` (trainer id, duration, count...)."""

    step: int
    point: str
    arg: Any = None


class FaultSchedule:
    """Seed + step-indexed event list; every chaos run driven by the
    same schedule is bit-reproducible.

    Thread-safe: transport wrappers consult it from retry loops and the
    checkpoint store from its save threads while the driver advances
    the step from the training loop."""

    def __init__(
        self,
        seed: int = 0,
        events: Sequence[FaultEvent] = (),
        strict: bool = True,
    ):
        self.seed = seed
        if strict:
            for ev in events:
                if ev.point not in KNOWN_POINTS:
                    raise ValueError(
                        f"unknown injection point {ev.point!r} "
                        f"(known: {', '.join(KNOWN_POINTS)})"
                    )
        # Stable order: (step, original index) so same-step events fire
        # in authoring order on every run.
        self._events: List[FaultEvent] = [
            ev
            for _, ev in sorted(
                enumerate(events), key=lambda t: (t[1].step, t[0])
            )
        ]
        self._lock = threading.Lock()
        self._now = -1
        self._draws: Dict[str, int] = {}
        self._fired: List[FaultEvent] = []

    # -- clock ---------------------------------------------------------------
    def advance(self, step: int) -> None:
        """Move the chaos clock to global training step ``step``
        (monotonic; the driver calls this at each step boundary)."""
        with self._lock:
            if step > self._now:
                self._now = step

    @property
    def now(self) -> int:
        with self._lock:
            return self._now

    # -- event delivery ------------------------------------------------------
    def due(self, point: str, step: Optional[int] = None) -> List[FaultEvent]:
        """Pop (one-shot) every not-yet-fired event for ``point`` whose
        step is <= the chaos clock (or explicit ``step``).  Every
        delivered fault is journaled to the flight recorder and counted
        (``edl_chaos_injections_total{point=}``) so a soak failure is
        reconstructible from telemetry alone — before this, injections
        vanished into logs."""
        with self._lock:
            now = self._now if step is None else step
            hits = [
                ev
                for ev in self._events
                if ev.point == point and ev.step <= now
            ]
            for ev in hits:
                self._events.remove(ev)
            self._fired.extend(hits)
        if hits:
            from edl_tpu import telemetry

            rec = telemetry.get_recorder()
            counter = telemetry.get_registry().counter(
                "edl_chaos_injections_total"
            )
            for ev in hits:
                counter.inc(point=ev.point)
                rec.record(
                    "chaos",
                    {
                        "point": ev.point,
                        "scheduled_step": ev.step,
                        "arg": ev.arg,
                    },
                    step=now,
                )
        return hits

    def pending(self) -> List[FaultEvent]:
        """Events not yet delivered (a finished soak asserts this is
        empty — every scheduled fault actually fired)."""
        with self._lock:
            return list(self._events)

    def fired(self) -> List[FaultEvent]:
        with self._lock:
            return list(self._fired)

    def maybe_raise(
        self, point: str, exc: Type[BaseException] = RuntimeError
    ) -> None:
        """Raise ``exc`` if an event for ``point`` is due — the hook
        shape production code embeds (one branch, zero cost when no
        chaos is installed)."""
        if self.due(point):
            raise exc(f"chaos[{point}] injected at step {self.now}")

    # -- derived determinism -------------------------------------------------
    def roll(self, point: str, p: float) -> bool:
        """Deterministic Bernoulli(p) draw for ``point``: the n-th draw
        of a point is a pure function of (seed, point, n)."""
        with self._lock:
            n = self._draws.get(point, 0)
            self._draws[point] = n + 1
        h = zlib.crc32(f"{self.seed}:{point}:{n}".encode()) / 2**32
        return h < p

    def rng(self, point: str) -> random.Random:
        """A fresh per-point ``random.Random`` stream derived from the
        seed (for fault payloads like delay durations)."""
        return random.Random(
            zlib.crc32(f"{self.seed}:{point}".encode())
        )
