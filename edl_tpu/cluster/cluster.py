"""L1 cluster abstraction: inventory + trainer-workload actuation.

Mirrors the reference's ``Cluster`` surface (``pkg/cluster.go``):

- ``inquiry_resource``      (ref ``InquiryResource``, ``:176-242``)
- ``get_trainer_workload``  (ref ``GetTrainerJob(ByName)``, ``:91-108``)
- ``update_parallelism``    (ref ``UpdateTrainerJob``, ``:110-113``)
- ``job_pods``              (ref ``JobPods``, ``:117-136``)
- create/delete             (ref ``:245-291``)

All Kubernetes I/O goes through the injected ``KubeAPI`` so everything
here is testable against ``FakeKube`` (the reference left this layer
entirely untested, SURVEY.md §4).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from edl_tpu.cluster.kube import KubeAPI, WorkloadInfo
from edl_tpu.cluster.resources import ClusterResource, Nodes
from edl_tpu.resource.training_job import TrainingJob
from edl_tpu.utils.retry import GiveUpError, RetryPolicy


class ParallelismUpdateError(GiveUpError):
    """``update_parallelism`` gave up: the optimistic-concurrency
    conflict storm outlasted the retry policy.  Typed so the autoscaler
    tick can log-and-skip the one job (the next 5s tick retries) while
    anything else failing still surfaces."""


#: Conflict-retry default: 5 attempts (the reference's ``scaleAllJobs``
#: count, ``pkg/autoscaler.go:346-370``) with a short jittered backoff
#: and a total deadline well inside the 5s control tick.
CONFLICT_RETRY = RetryPolicy(
    max_attempts=5, base_delay=0.05, max_delay=0.5, deadline=2.0
)


class Cluster:
    def __init__(self, kube: KubeAPI, conflict_retry: RetryPolicy = None):
        self.kube = kube
        self.conflict_retry = conflict_retry or CONFLICT_RETRY

    # -- inventory (ref InquiryResource) ------------------------------------
    def inquiry_resource(self) -> ClusterResource:
        """Total/used/idle snapshot.  Sums node allocatables; charges
        every non-terminal pod's requests (and chip limits) against the
        totals and its node's idle maps (ref ``pkg/cluster.go:176-242``
        with GPU -> TPU chips)."""
        nodes = self.kube.list_nodes()
        pods = self.kube.list_pods()

        r = ClusterResource(
            node_count=len(nodes),
            nodes=Nodes(
                cpu_idle_milli={n.name: n.cpu_milli for n in nodes},
                memory_free_mega={n.name: n.memory_mega for n in nodes},
                tpu_free={n.name: n.tpu_chips for n in nodes},
                pool_topology={
                    n.name: n.tpu_topology for n in nodes if n.tpu_topology
                },
                node_pool={n.name: n.pool for n in nodes if n.pool},
            ),
        )
        for n in nodes:
            r.cpu_total_milli += n.cpu_milli
            r.memory_total_mega += n.memory_mega
            r.tpu_total += n.tpu_chips

        for p in pods:
            if p.phase in ("Succeeded", "Failed"):
                continue  # ref filters these server-side (``:202-210``)
            if not p.node:
                # Unscheduled pod: physical usage is zero.  The reference
                # charged these anyway (``:202-210``), inflating load with
                # unmet demand; we surface them via the autoscaler's
                # explicit pending-demand path instead (fix, don't
                # replicate — see autoscaler.scaler docstring).
                continue
            r.cpu_request_milli += p.cpu_request_milli
            r.memory_request_mega += p.memory_request_mega
            r.tpu_request += p.tpu_limit
            r.tpu_limit += p.tpu_limit
            if p.node in r.nodes.cpu_idle_milli:
                r.nodes.cpu_idle_milli[p.node] -= p.cpu_request_milli
                r.nodes.memory_free_mega[p.node] -= p.memory_request_mega
                r.nodes.tpu_free[p.node] -= p.tpu_limit
        return r

    # -- trainer workload (ref GetTrainerJob / UpdateTrainerJob) ------------
    def _slice_jobs(self, job: TrainingJob) -> List[WorkloadInfo]:
        """The per-replica Indexed Jobs of a multi-host-topology job,
        sorted by replica index (workload name ``<job>-trainer-<r>``)."""
        prefix = job.trainer_job_name() + "-"
        out = []
        for w in self.kube.list_workloads():
            if w.kind != "Job" or not w.name.startswith(prefix):
                continue
            suffix = w.name[len(prefix):]
            if suffix.isdigit():
                out.append((int(suffix), w))
        return [w for _, w in sorted(out, key=lambda t: t[0])]

    @staticmethod
    def _aggregate_slices(
        job_name: str, trainer_name: str, slices: List[WorkloadInfo]
    ) -> WorkloadInfo:
        """Virtual aggregate over a multi-host job's per-replica Jobs:
        ``parallelism`` counts REPLICAS (slice groups), the unit every
        control-plane decision is made in."""
        return WorkloadInfo(
            name=trainer_name,
            job_name=job_name,
            parallelism=len(slices),
            cpu_request_milli=slices[0].cpu_request_milli,
            memory_request_mega=slices[0].memory_request_mega,
            tpu_limit=slices[0].tpu_limit,
            kind="Job",
            owner=slices[0].owner,
        )

    def get_trainer_workload(self, job: TrainingJob) -> Optional[WorkloadInfo]:
        """The job's trainer workload view.  Single-host: the batch Job
        itself.  Multi-host: the virtual replica-count aggregate."""
        if job.hosts_per_replica() == 1:
            return self.kube.get_workload(job.trainer_job_name())
        slices = self._slice_jobs(job)
        if not slices:
            return None
        return self._aggregate_slices(job.name, job.trainer_job_name(), slices)

    def trainer_workloads_map(self) -> Dict[str, WorkloadInfo]:
        """job name -> trainer workload view for EVERY framework job,
        from ONE ``list_workloads`` call — the control loop uses this so
        a tick costs O(1) kubectl subprocesses, not one ``get`` per job
        (the reference's ``GetTrainerJob``-per-job pattern blows the 5s
        tick at cluster scope).  Multi-host jobs aggregate to their
        replica count, same as ``get_trainer_workload``."""
        singles: Dict[str, WorkloadInfo] = {}
        groups: Dict[str, List[tuple]] = {}
        for w in self.kube.list_workloads():
            if w.kind != "Job" or not w.job_name:
                continue
            trainer_name = f"{w.job_name}-trainer"
            if w.name == trainer_name:
                singles[w.job_name] = w
                continue
            prefix = trainer_name + "-"
            if w.name.startswith(prefix) and w.name[len(prefix):].isdigit():
                groups.setdefault(w.job_name, []).append(
                    (int(w.name[len(prefix):]), w)
                )
        for job_name, pairs in groups.items():
            slices = [w for _, w in sorted(pairs, key=lambda t: t[0])]
            singles[job_name] = self._aggregate_slices(
                job_name, f"{job_name}-trainer", slices
            )
        return singles

    def update_parallelism(self, job: TrainingJob, parallelism: int) -> bool:
        """Set the trainer replica count.

        Single-host: rewrite the batch Job's parallelism under the
        ``conflict_retry`` policy — bounded attempts, jittered backoff,
        a deadline inside the control tick (the reference's bare
        5-retry loop, ``pkg/autoscaler.go:346-370``, with the retry
        behavior made uniform via ``utils.retry``).  Exhaustion raises
        the typed ``ParallelismUpdateError`` so the autoscaler tick can
        log-and-skip.  Returns False when the workload does not exist.
        Multi-host: a replica is a whole Indexed Job, so scaling
        creates the missing ``<job>-trainer-<r>`` Jobs (r ascending) or
        deletes the highest-indexed extras — the same
        highest-index-first order the coordinator's replica grouping
        drops, so control plane and world agree on victims."""
        from edl_tpu.cluster.kube import ConflictError

        if job.hosts_per_replica() > 1:
            from edl_tpu.controller.jobparser import parse_to_trainer_slice

            have = {  # replica index -> workload
                int(w.name.rsplit("-", 1)[1]): w
                for w in self._slice_jobs(job)
            }
            ok = True
            # Keep the LOWEST-indexed EXISTING replicas (the coordinator's
            # replica grouping keeps lowest complete replicas on
            # scale-down — deleting "every r >= parallelism" would kill
            # live survivors whenever indexes are non-contiguous, e.g.
            # after an external deletion of replica 0).
            existing = sorted(have)
            keep = existing[:parallelism]
            for r in existing[parallelism:]:
                if not self.kube.delete_workload(have[r].name):
                    ok = False
            # Fill the remainder with fresh Jobs on the smallest unused
            # indexes.
            missing = parallelism - len(keep)
            idx = 0
            while missing > 0:
                if idx not in have:
                    try:
                        self.kube.apply_manifests(
                            [parse_to_trainer_slice(job, idx)]
                        )
                    except Exception:
                        ok = False
                    missing -= 1
                idx += 1
            return ok

        missing = object()  # sentinel threaded out of the retry closure

        def put():
            w = self.kube.get_workload(job.trainer_job_name())
            if w is None:
                return missing
            w.parallelism = parallelism
            self.kube.update_workload(w)
            return True

        import zlib

        try:
            result = self.conflict_retry.run(
                put,
                retryable=lambda e: isinstance(e, ConflictError),
                # Per-job jitter stream: concurrent controllers fighting
                # over different Jobs decorrelate their retries.
                seed=zlib.crc32(job.name.encode()),
                describe=f"parallelism PUT for {job.name}",
            )
        except GiveUpError as e:
            raise ParallelismUpdateError(
                f"parallelism PUT for {job.name} -> {parallelism} gave up "
                f"after {e.attempts} conflict(s)",
                last_error=e.last_error,
                attempts=e.attempts,
            ) from e.last_error
        return result is not missing

    def update_serving_replicas(self, job: TrainingJob, replicas: int) -> bool:
        """Set the serving replica Deployment's replica count (the
        ``ServingLane`` retarget's kube half: the coordinator target
        moves the serving WORLD, this moves the pods that fill it).
        Same optimistic-concurrency discipline as
        ``update_parallelism`` — bounded ``conflict_retry`` attempts,
        typed ``ParallelismUpdateError`` on exhaustion so the lane's
        tick can log-and-skip.  Returns False when the job renders no
        serving fleet (``spec.serving`` unset) or the Deployment does
        not exist."""
        from edl_tpu.cluster.kube import ConflictError

        if job.spec.serving is None:
            return False
        name = job.serving_name()
        missing = object()

        def put():
            w = self.kube.get_workload(name, kind="Deployment")
            if w is None:
                return missing
            w.parallelism = replicas
            self.kube.update_workload(w)
            return True

        import zlib

        try:
            result = self.conflict_retry.run(
                put,
                retryable=lambda e: isinstance(e, ConflictError),
                seed=zlib.crc32(name.encode()),
                describe=f"serving replicas PUT for {job.name}",
            )
        except GiveUpError as e:
            raise ParallelismUpdateError(
                f"serving replicas PUT for {job.name} -> {replicas} gave "
                f"up after {e.attempts} conflict(s)",
                last_error=e.last_error,
                attempts=e.attempts,
            ) from e.last_error
        return result is not missing

    # -- pod counting (ref JobPods) -----------------------------------------
    def job_pods(self, job: TrainingJob) -> Tuple[int, int, int, int]:
        """(total, running, pending, succeeded) over the job's
        non-deleting pods (ref ``pkg/cluster.go:117-136``:
        label-selected, honoring DeletionTimestamp)."""
        return self.job_pods_map().get(job.name, (0, 0, 0, 0))

    def job_pods_map(self, pods=None) -> Dict[str, Tuple[int, int, int, int]]:
        """(total, running, pending, succeeded) for every job in ONE
        pod list — the autoscaler loop uses this so a tick costs one
        list call, not one per job.  ``pods``: optional shared
        snapshot."""
        out: Dict[str, List[int]] = {}
        for p in pods if pods is not None else self.kube.list_pods():
            if not p.job_name or p.deleting:
                continue
            c = out.setdefault(p.job_name, [0, 0, 0, 0])
            c[0] += 1
            if p.phase == "Running":
                c[1] += 1
            elif p.phase == "Pending":
                c[2] += 1
            elif p.phase == "Succeeded":
                c[3] += 1
        return {k: tuple(v) for k, v in out.items()}

    def job_pod_nodes_map(self, pods=None) -> Dict[str, List[str]]:
        """job name -> its scheduled, non-terminal, non-deleting pods'
        node names, newest pod first (descending ``creationTimestamp``,
        name as tiebreak — APPROXIMATING the coordinator's drop-newest
        victim order: k8s timestamps have 1s resolution and pod names
        carry random suffixes, so within one creation second the order
        can diverge from the true join order.  Harmless by design —
        this feeds only the autoscaler's dry-run capacity simulation,
        which self-corrects on the next tick from live pod state; the
        authoritative victim choice is the coordinator's, ADVICE r4).
        ``pods``: optional shared pod snapshot so a control tick costs
        ONE pod list for all its maps.  The autoscaler threads the
        result into ``JobView.pod_nodes`` so a dry-run shed returns
        capacity to the right node maps."""
        out: Dict[str, List[Tuple[str, str, str]]] = {}
        for p in pods if pods is not None else self.kube.list_pods():
            if not p.job_name or p.deleting or not p.node:
                continue
            if p.phase in ("Succeeded", "Failed"):
                continue
            out.setdefault(p.job_name, []).append((p.created, p.name, p.node))
        return {
            job: [node for _, _, node in sorted(triples, reverse=True)]
            for job, triples in out.items()
        }

    # -- CRUD (ref :245-291) -------------------------------------------------
    def create_trainer_workload(self, job: TrainingJob) -> Optional[WorkloadInfo]:
        """Create the trainer workload(s) by applying the jobparser's
        real manifests — one creation path for FakeKube and a live
        cluster (the reference's TODO at ``pkg/controller.go:115-133``,
        wired)."""
        from edl_tpu.controller.jobparser import parse_to_trainer_manifests

        self.kube.apply_manifests(parse_to_trainer_manifests(job))
        return self.get_trainer_workload(job)

    def delete_trainer_workload(self, job: TrainingJob) -> bool:
        if job.hosts_per_replica() > 1:
            deleted = False
            for w in self._slice_jobs(job):
                deleted = self.kube.delete_workload(w.name) or deleted
            # the headless per-pod-DNS Service shares the trainer name
            self.kube.delete_workload(job.trainer_job_name())
            return deleted
        return self.kube.delete_workload(job.trainer_job_name())

    def delete_pod(self, name: str) -> bool:
        """Graceful named-pod delete (scale-down victim coordination:
        the autoscaler deletes exactly the pods the coordinator dropped
        from the plan, so the kube Job controller never picks its own
        victim)."""
        return self.kube.delete_pod(name)
