"""L1 cluster abstraction: inventory + trainer-workload actuation.

Mirrors the reference's ``Cluster`` surface (``pkg/cluster.go``):

- ``inquiry_resource``      (ref ``InquiryResource``, ``:176-242``)
- ``get_trainer_workload``  (ref ``GetTrainerJob(ByName)``, ``:91-108``)
- ``update_parallelism``    (ref ``UpdateTrainerJob``, ``:110-113``)
- ``job_pods``              (ref ``JobPods``, ``:117-136``)
- create/delete             (ref ``:245-291``)

All Kubernetes I/O goes through the injected ``KubeAPI`` so everything
here is testable against ``FakeKube`` (the reference left this layer
entirely untested, SURVEY.md §4).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from edl_tpu.cluster.kube import KubeAPI, WorkloadInfo
from edl_tpu.cluster.resources import ClusterResource, Nodes
from edl_tpu.resource.training_job import TrainingJob


class Cluster:
    def __init__(self, kube: KubeAPI):
        self.kube = kube

    # -- inventory (ref InquiryResource) ------------------------------------
    def inquiry_resource(self) -> ClusterResource:
        """Total/used/idle snapshot.  Sums node allocatables; charges
        every non-terminal pod's requests (and chip limits) against the
        totals and its node's idle maps (ref ``pkg/cluster.go:176-242``
        with GPU -> TPU chips)."""
        nodes = self.kube.list_nodes()
        pods = self.kube.list_pods()

        r = ClusterResource(
            node_count=len(nodes),
            nodes=Nodes(
                cpu_idle_milli={n.name: n.cpu_milli for n in nodes},
                memory_free_mega={n.name: n.memory_mega for n in nodes},
                tpu_free={n.name: n.tpu_chips for n in nodes},
                pool_topology={
                    n.name: n.tpu_topology for n in nodes if n.tpu_topology
                },
            ),
        )
        for n in nodes:
            r.cpu_total_milli += n.cpu_milli
            r.memory_total_mega += n.memory_mega
            r.tpu_total += n.tpu_chips

        for p in pods:
            if p.phase in ("Succeeded", "Failed"):
                continue  # ref filters these server-side (``:202-210``)
            if not p.node:
                # Unscheduled pod: physical usage is zero.  The reference
                # charged these anyway (``:202-210``), inflating load with
                # unmet demand; we surface them via the autoscaler's
                # explicit pending-demand path instead (fix, don't
                # replicate — see autoscaler.scaler docstring).
                continue
            r.cpu_request_milli += p.cpu_request_milli
            r.memory_request_mega += p.memory_request_mega
            r.tpu_request += p.tpu_limit
            r.tpu_limit += p.tpu_limit
            if p.node in r.nodes.cpu_idle_milli:
                r.nodes.cpu_idle_milli[p.node] -= p.cpu_request_milli
                r.nodes.memory_free_mega[p.node] -= p.memory_request_mega
                r.nodes.tpu_free[p.node] -= p.tpu_limit
        return r

    # -- trainer workload (ref GetTrainerJob / UpdateTrainerJob) ------------
    def get_trainer_workload(self, job: TrainingJob) -> Optional[WorkloadInfo]:
        return self.kube.get_workload(job.trainer_job_name())

    def update_parallelism(self, job: TrainingJob, parallelism: int, retries: int = 5) -> bool:
        """Set the trainer workload's parallelism with optimistic-
        concurrency retries (ref ``scaleAllJobs``'s 5-retry loop,
        ``pkg/autoscaler.go:346-370``, moved down here so the decision
        plane stays pure)."""
        from edl_tpu.cluster.kube import ConflictError

        for _ in range(retries):
            w = self.kube.get_workload(job.trainer_job_name())
            if w is None:
                return False
            w.parallelism = parallelism
            try:
                self.kube.update_workload(w)
                return True
            except ConflictError:
                continue
        return False

    # -- pod counting (ref JobPods) -----------------------------------------
    def job_pods(self, job: TrainingJob) -> Tuple[int, int, int, int]:
        """(total, running, pending, succeeded) over the job's
        non-deleting pods (ref ``pkg/cluster.go:117-136``:
        label-selected, honoring DeletionTimestamp)."""
        return self.job_pods_map().get(job.name, (0, 0, 0, 0))

    def job_pods_map(self) -> Dict[str, Tuple[int, int, int, int]]:
        """(total, running, pending, succeeded) for every job in ONE
        pod list — the autoscaler loop uses this so a tick costs one
        list call, not one per job."""
        out: Dict[str, List[int]] = {}
        for p in self.kube.list_pods():
            if not p.job_name or p.deleting:
                continue
            c = out.setdefault(p.job_name, [0, 0, 0, 0])
            c[0] += 1
            if p.phase == "Running":
                c[1] += 1
            elif p.phase == "Pending":
                c[2] += 1
            elif p.phase == "Succeeded":
                c[3] += 1
        return {k: tuple(v) for k, v in out.items()}

    # -- CRUD (ref :245-291) -------------------------------------------------
    def create_trainer_workload(self, job: TrainingJob) -> Optional[WorkloadInfo]:
        """Create the trainer workload by applying the jobparser's real
        manifest — one creation path for FakeKube and a live cluster
        (the reference's TODO at ``pkg/controller.go:115-133``, wired)."""
        from edl_tpu.controller.jobparser import parse_to_trainer

        self.kube.apply_manifests([parse_to_trainer(job)])
        return self.kube.get_workload(job.trainer_job_name())

    def delete_trainer_workload(self, job: TrainingJob) -> bool:
        return self.kube.delete_workload(job.trainer_job_name())

    def delete_pod(self, name: str) -> bool:
        """Graceful named-pod delete (scale-down victim coordination:
        the autoscaler deletes exactly the pods the coordinator dropped
        from the plan, so the kube Job controller never picks its own
        victim)."""
        return self.kube.delete_pod(name)
