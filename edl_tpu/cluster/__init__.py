from edl_tpu.cluster.resources import ClusterResource, Nodes
from edl_tpu.cluster.tpu_topology import (
    topology_chips,
    legal_topologies,
    SliceTopology,
)

__all__ = [
    "ClusterResource",
    "Nodes",
    "topology_chips",
    "legal_topologies",
    "SliceTopology",
]
