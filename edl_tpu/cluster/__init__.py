from edl_tpu.cluster.resources import ClusterResource, Nodes
from edl_tpu.cluster.tpu_topology import (
    topology_chips,
    legal_topologies,
    SliceTopology,
)
from edl_tpu.cluster.kube import (
    KubeAPI,
    FakeKube,
    KubectlAPI,
    NodeInfo,
    PodInfo,
    WorkloadInfo,
    ConflictError,
)
from edl_tpu.cluster.cluster import Cluster

__all__ = [
    "ClusterResource",
    "Nodes",
    "topology_chips",
    "legal_topologies",
    "SliceTopology",
    "KubeAPI",
    "FakeKube",
    "KubectlAPI",
    "NodeInfo",
    "PodInfo",
    "WorkloadInfo",
    "ConflictError",
    "Cluster",
]
