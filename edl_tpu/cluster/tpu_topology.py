"""TPU slice topology model.

The reference counts GPUs as per-node scalar quantities
(``pkg/cluster.go:224-234`` sums ``alpha.kubernetes.io/nvidia-gpu``).
TPUs are not interchangeable scalars: a trainer replica owns a whole
*slice* (chips wired by ICI in a fixed shape), and a data-parallel world
grows and shrinks in units of slices.  This module is the vocabulary the
inventory (L1) and the autoscaler's slice-quantized deltas (L3) share.

Chips-per-slice for the supported v5e topologies mirror the real
offerings (1, 4, 8, 16, 32, 64 chips; 2D ICI meshes up to 8x8).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Tuple


@dataclass(frozen=True)
class SliceTopology:
    name: str
    chips: int
    ici_mesh: Tuple[int, int]  # 2D ICI mesh shape (v5e is a 2D torus)
    hosts: int  # host machines per slice (v5e: 8 chips/host)


def _mk(name: str, mesh: Tuple[int, int]) -> SliceTopology:
    chips = mesh[0] * mesh[1]
    return SliceTopology(name=name, chips=chips, ici_mesh=mesh, hosts=max(1, chips // 8))


#: Legal v5e slice topologies (by name as it appears in TrainerSpec).
_TOPOLOGIES: Dict[str, SliceTopology] = {
    t.name: t
    for t in [
        _mk("v5e-1", (1, 1)),
        _mk("v5e-4", (2, 2)),
        _mk("v5e-8", (2, 4)),
        _mk("v5e-16", (4, 4)),
        _mk("v5e-32", (4, 8)),
        _mk("v5e-64", (8, 8)),
        # CPU-host "topology" for tests / non-TPU jobs.
        SliceTopology(name="cpu", chips=0, ici_mesh=(0, 0), hosts=1),
    ]
}


def get_topology(name: str) -> SliceTopology:
    try:
        return _TOPOLOGIES[name]
    except KeyError:
        raise ValueError(
            f"unknown TPU slice topology {name!r}; legal: {sorted(_TOPOLOGIES)}"
        ) from None


def topology_chips(name: str) -> int:
    return get_topology(name).chips


def legal_topologies() -> List[str]:
    return sorted(_TOPOLOGIES, key=lambda n: _TOPOLOGIES[n].chips)


def normalize_topology(name: str):
    """Resolve either a framework topology name ("v5e-8") or a GKE
    ``cloud.google.com/gke-tpu-topology`` label value ("2x4") to a
    SliceTopology; None if unrecognized."""
    if name in _TOPOLOGIES:
        return _TOPOLOGIES[name]
    if "x" in name:
        try:
            mesh = tuple(int(p) for p in name.split("x"))
        except ValueError:
            return None
        for t in _TOPOLOGIES.values():
            if t.ici_mesh == mesh:
                return t
    return None


def largest_topology_fitting(chips: int) -> SliceTopology:
    """Largest legal slice with at most ``chips`` chips."""
    best = _TOPOLOGIES["cpu"]
    for t in _TOPOLOGIES.values():
        if 0 < t.chips <= chips and t.chips > best.chips:
            best = t
    return best
