"""The Kubernetes I/O boundary: a thin interface + an in-memory fake.

The reference's ``Cluster`` talks straight to client-go and is therefore
untestable (SURVEY.md §4: "What is *not* tested: Cluster (all k8s
I/O)").  We keep the same *surface* but put it behind ``KubeAPI`` so the
decision and control planes are testable against ``FakeKube`` — which
also emulates the external actors the reference system leaned on:

- the **kube Job controller** turning ``parallelism`` changes into pod
  creation/deletion (ref relies on it after the PUT,
  ``pkg/autoscaler.go:339-376``),
- the **scheduler** binding pods to nodes with capacity, leaving the
  rest ``Pending``.

``KubectlAPI`` adapts the same interface onto a real cluster through
the ``kubectl`` binary (no python k8s client dependency).
"""

from __future__ import annotations

import json
import subprocess
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple


@dataclass
class NodeInfo:
    """Allocatable capacity of one node/pool (the inventory unit of
    ref ``InquiryResource``, ``pkg/cluster.go:176-242``)."""

    name: str
    cpu_milli: int = 0
    memory_mega: int = 0
    tpu_chips: int = 0
    tpu_topology: str = ""  # e.g. "v5e-4": this pool schedules whole slices
    #: nodepool identity (GKE ``cloud.google.com/gke-nodepool``): the
    #: host nodes of ONE multi-host slice share it — a hosts>1 replica
    #: must place all its pods inside a single pool
    pool: str = ""


@dataclass
class PodInfo:
    name: str
    job_name: str  # label paddle-job analog: edl-job=<name>
    phase: str = "Pending"  # Pending | Running | Succeeded | Failed
    node: str = ""
    cpu_request_milli: int = 0
    memory_request_mega: int = 0
    tpu_limit: int = 0
    deleting: bool = False  # DeletionTimestamp set (ref pkg/cluster.go:127-131)
    #: owning workload name — distinct from job_name when a job renders
    #: several per-replica slice Jobs sharing one edl-job label
    workload: str = ""
    #: creationTimestamp (RFC3339, sorts lexicographically) — victim
    #: ordering for scale-down: newest pod first.  "" = unknown.
    created: str = ""


@dataclass
class WorkloadInfo:
    """The trainer workload: name + parallelism (the one mutable knob,
    ref ``Job.Spec.Parallelism``) + per-replica resources."""

    name: str
    job_name: str
    parallelism: int
    cpu_request_milli: int = 0
    memory_request_mega: int = 0
    tpu_limit: int = 0
    resource_version: int = 0
    #: k8s kind ("Job" for trainers, "Deployment" for coordinators)
    kind: str = "Job"
    #: owning TrainingJob name (from the edl-job / edl-owner label);
    #: empty for workloads the framework does not own.  Drives the
    #: controller's level-triggered orphan GC.
    owner: str = ""


class ConflictError(RuntimeError):
    """Optimistic-concurrency conflict (stale resourceVersion) — the
    reason the reference retried updates 5 times (``pkg/autoscaler.go:
    346-370``)."""


def _workload_from_manifest(m: dict) -> WorkloadInfo:
    """Project a rendered Job/Deployment manifest onto the WorkloadInfo
    view (name, parallelism knob, per-replica resources)."""
    from edl_tpu.utils.quantity import (
        parse_count,
        parse_cpu_milli,
        parse_memory_mega,
    )

    kind = m["kind"]
    meta = m["metadata"]
    spec = m["spec"]
    parallelism = (
        spec.get("parallelism", 1) if kind == "Job" else spec.get("replicas", 1)
    )
    cpu = mem = tpu = 0
    for c in spec.get("template", {}).get("spec", {}).get("containers", []):
        req = c.get("resources", {}).get("requests", {})
        lim = c.get("resources", {}).get("limits", {})
        cpu += parse_cpu_milli(req.get("cpu", 0))
        mem += parse_memory_mega(req.get("memory", 0))
        tpu += parse_count(lim.get("google.com/tpu", 0))
    labels = meta.get("labels", {})
    # Trainer Jobs carry the pod-counting label; coordinator Deployments
    # must NOT be counted as trainer pods (see jobparser OWNER_LABEL).
    job_name = labels.get("edl-job", meta["name"]) if kind == "Job" else meta["name"]
    return WorkloadInfo(
        name=meta["name"],
        job_name=job_name,
        parallelism=parallelism,
        cpu_request_milli=cpu,
        memory_request_mega=mem,
        tpu_limit=tpu,
        kind=kind,
        owner=labels.get("edl-job", labels.get("edl-owner", "")),
    )


class KubeAPI:
    """Everything the framework asks of Kubernetes.  One process
    boundary, kept narrow on purpose."""

    # inventory
    def list_nodes(self) -> List[NodeInfo]:
        raise NotImplementedError

    def list_pods(self) -> List[PodInfo]:
        raise NotImplementedError

    # trainer workload CRUD (ref pkg/cluster.go:91-113, 245-291)
    def get_workload(
        self, name: str, kind: str = "Job"
    ) -> Optional[WorkloadInfo]:
        """Fetch one workload by name.  ``kind`` routes the lookup on
        backends whose API is kind-scoped (kubectl); name-keyed
        backends (FakeKube) may ignore it."""
        raise NotImplementedError

    def list_workloads(self) -> List[WorkloadInfo]:
        """All framework-owned workloads (trainer Jobs + coordinator
        Deployments), for level-triggered reconciliation: the controller
        compares them against the live CR set and GCs orphans."""
        raise NotImplementedError

    def apply_manifests(self, manifests: List[dict]) -> None:
        """Create-or-update rendered k8s manifests (the jobparser's
        output).  This is the creation path — the reference's TODO
        (``pkg/controller.go:115-133``) wired for real."""
        raise NotImplementedError

    def update_workload(self, w: WorkloadInfo) -> WorkloadInfo:
        raise NotImplementedError

    def delete_workload(self, name: str) -> bool:
        raise NotImplementedError

    def delete_pod(self, name: str) -> bool:
        """Gracefully delete one named pod (scale-down victim
        coordination): the pod gets SIGTERM and enters Terminating; the
        kube Job controller then converges a lowered parallelism
        without choosing its own victim.  Returns False when the pod
        does not exist."""
        raise NotImplementedError

    def update_training_job_status(
        self, name: str, status: dict, namespace: Optional[str] = None
    ) -> bool:
        """Write the controller's status view to the CR's status
        subresource so ``kubectl get trainingjobs`` tells the truth —
        the reference declared ``TrainingJobStatus`` and never wrote it
        (SURVEY.md §5.5).  Default no-op: backends without CR storage
        (in-memory FakeKube) simply skip it."""
        return False


class FakeKube(KubeAPI):
    """In-memory cluster with a synchronous Job-controller + scheduler
    emulation: every mutation immediately reconciles pods to the
    declared parallelism and binds what fits onto nodes.

    Tests fabricate multi-node state as literals, exactly the
    reference's test philosophy (SURVEY.md §4) — but with the actuation
    half actually closed-loop.
    """

    def __init__(
        self,
        nodes: Optional[List[NodeInfo]] = None,
        scale_down_victim: str = "newest",
    ):
        self._lock = threading.RLock()
        self.nodes: Dict[str, NodeInfo] = {n.name: n for n in (nodes or [])}
        self.workloads: Dict[str, WorkloadInfo] = {}
        self.pods: Dict[str, PodInfo] = {}
        self.services: Dict[str, dict] = {}
        self._pod_seq = 0
        #: names of workloads whose pods must stay Pending (test knob to
        #: simulate unschedulable jobs beyond capacity math)
        self.hold_pending: set = set()
        #: which pod the emulated Job controller kills when parallelism
        #: drops below the live count.  "newest" (highest index) happens
        #: to match the coordinator's drop-newest rank order; "oldest"
        #: is the adversarial mode — the real controller makes no such
        #: promise, so tests use it to prove the autoscaler's named
        #: victim deletion matters (VERDICT r3 weak-6).
        if scale_down_victim not in ("newest", "oldest"):
            raise ValueError(f"unknown scale_down_victim {scale_down_victim!r}")
        self.scale_down_victim = scale_down_victim

    # -- inventory ----------------------------------------------------------
    def list_nodes(self) -> List[NodeInfo]:
        with self._lock:
            return [NodeInfo(**vars(n)) for n in self.nodes.values()]

    def list_pods(self) -> List[PodInfo]:
        with self._lock:
            return [PodInfo(**vars(p)) for p in self.pods.values()]

    # -- workload CRUD ------------------------------------------------------
    def get_workload(
        self, name: str, kind: str = "Job"
    ) -> Optional[WorkloadInfo]:
        with self._lock:
            w = self.workloads.get(name)  # name-keyed; kind advisory
            return WorkloadInfo(**vars(w)) if w else None

    def list_workloads(self) -> List[WorkloadInfo]:
        with self._lock:
            return [WorkloadInfo(**vars(w)) for w in self.workloads.values()]

    def create_workload(self, w: WorkloadInfo) -> WorkloadInfo:
        with self._lock:
            if w.name in self.workloads:
                raise ConflictError(f"workload {w.name} already exists")
            stored = WorkloadInfo(**vars(w))
            stored.resource_version = 1
            self.workloads[w.name] = stored
            self._reconcile(stored)
            return WorkloadInfo(**vars(stored))

    def update_workload(self, w: WorkloadInfo) -> WorkloadInfo:
        with self._lock:
            cur = self.workloads.get(w.name)
            if cur is None:
                raise KeyError(f"no workload {w.name}")
            if w.resource_version != cur.resource_version:
                raise ConflictError(
                    f"stale resourceVersion {w.resource_version} != {cur.resource_version}"
                )
            cur.parallelism = w.parallelism
            cur.resource_version += 1
            self._reconcile(cur)
            return WorkloadInfo(**vars(cur))

    def delete_workload(self, name: str) -> bool:
        with self._lock:
            svc = self.services.pop(name, None)
            w = self.workloads.pop(name, None)
            if w is None:
                return svc is not None
            for pname in [
                p
                for p, pod in self.pods.items()
                if (
                    pod.workload == name
                    if pod.workload
                    else pod.job_name == w.job_name
                )
            ]:
                del self.pods[pname]
            return True

    def delete_pod(self, name: str) -> bool:
        with self._lock:
            p = self.pods.get(name)
            if p is None or p.deleting:
                return False
            # Graceful delete: Terminating until the controller's next
            # reconcile purges it (emulates the SIGTERM grace window —
            # the launcher's graceful-leave handshake runs inside it).
            p.deleting = True
            return True

    # -- manifest application -------------------------------------------------
    def apply_manifests(self, manifests: List[dict]) -> None:
        """Interpret the jobparser's real manifests — so FakeKube tests
        exercise the identical creation path a live cluster gets."""
        for m in manifests:
            kind = m.get("kind", "")
            if kind == "Service":
                with self._lock:
                    self.services[m["metadata"]["name"]] = m
                continue
            if kind not in ("Job", "Deployment"):
                raise ValueError(f"FakeKube cannot apply kind {kind!r}")
            w = _workload_from_manifest(m)
            with self._lock:
                cur = self.workloads.get(w.name)
                if cur is None:
                    self.create_workload(w)
                else:
                    cur.parallelism = w.parallelism
                    cur.resource_version += 1
                    self._reconcile(cur)

    # -- controller + scheduler emulation ------------------------------------
    def _workload_pods(self, w: WorkloadInfo) -> List[PodInfo]:
        """Live (non-Terminating) pods owned by one workload.  Matching
        by workload name, not job label: a multi-host job's per-replica
        slice Jobs share the edl-job label but reconcile separately."""
        return [
            p
            for p in self.pods.values()
            if not p.deleting
            and (p.workload == w.name if p.workload else p.job_name == w.job_name)
        ]

    def _free_on(self, node: NodeInfo) -> Tuple[int, int, int]:
        used_cpu = used_mem = used_tpu = 0
        for p in self.pods.values():
            if p.node == node.name and p.phase in ("Pending", "Running"):
                used_cpu += p.cpu_request_milli
                used_mem += p.memory_request_mega
                used_tpu += p.tpu_limit
        return (
            node.cpu_milli - used_cpu,
            node.memory_mega - used_mem,
            node.tpu_chips - used_tpu,
        )

    def _reconcile(self, w: WorkloadInfo):
        """Kube Job controller: match pod count to parallelism.
        Terminating (gracefully deleted) pods are purged first — by the
        time the controller acts on a new parallelism, named victims
        deleted just before the PUT are already on their way out and
        don't count toward the live set.  Any remaining excess is
        killed per ``scale_down_victim``."""
        for pname in [
            p
            for p, pod in self.pods.items()
            if pod.job_name == w.job_name and pod.deleting
        ]:
            del self.pods[pname]
        pods = sorted(self._workload_pods(w), key=lambda p: p.name)
        while len(pods) > w.parallelism:
            victim = (
                pods.pop() if self.scale_down_victim == "newest" else pods.pop(0)
            )
            del self.pods[victim.name]
        while len(pods) < w.parallelism:
            self._pod_seq += 1
            p = PodInfo(
                # zero-padded so lexicographic name order == creation order
                name=f"{w.job_name}-pod-{self._pod_seq:06d}",
                created=f"{self._pod_seq:06d}",  # monotonic, sortable
                job_name=w.job_name,
                cpu_request_milli=w.cpu_request_milli,
                memory_request_mega=w.memory_request_mega,
                tpu_limit=w.tpu_limit,
                workload=w.name,
            )
            self.pods[p.name] = p
            pods.append(p)
        self._schedule()

    def _schedule(self):
        """Bind Pending pods to nodes with room; leave the rest Pending."""
        for p in sorted(self.pods.values(), key=lambda p: p.name):
            if p.phase != "Pending" or p.node or p.job_name in self.hold_pending:
                continue
            for node in sorted(self.nodes.values(), key=lambda n: n.name):
                free_cpu, free_mem, free_tpu = self._free_on(node)
                if (
                    p.cpu_request_milli <= free_cpu
                    and p.memory_request_mega <= free_mem
                    and p.tpu_limit <= free_tpu
                ):
                    p.node = node.name
                    p.phase = "Running"
                    break

    # -- test helpers --------------------------------------------------------
    def complete_pods(self, job_name: str):
        """Test knob: all of a job's pods run to completion (the kube
        Job controller leaves Succeeded pods in place)."""
        with self._lock:
            for p in self.pods.values():
                if p.job_name == job_name:
                    p.phase = "Succeeded"

    def kill_pod(self, name: str):
        """Simulate a pod death (node failure, preemption)."""
        with self._lock:
            self.pods.pop(name, None)
            # The Job controller would re-create it:
            for w in self.workloads.values():
                self._reconcile(w)

    def retry_scheduling(self):
        with self._lock:
            self._schedule()


class KubectlAPI(KubeAPI):  # pragma: no cover - needs a real cluster
    """Real-cluster adapter via the ``kubectl`` binary (the baked-in
    image has no python k8s client; shelling out keeps the dependency
    surface zero).  Only the subset the framework uses."""

    def __init__(self, namespace: str = "default", kubectl: str = "kubectl"):
        self.namespace = namespace
        self.kubectl = kubectl

    def _run(self, *args: str) -> dict:
        out = subprocess.run(
            [self.kubectl, "-n", self.namespace, *args, "-o", "json"],
            capture_output=True,
            text=True,
            check=True,
        )
        return json.loads(out.stdout)

    def list_nodes(self) -> List[NodeInfo]:
        items = self._run("get", "nodes")["items"]
        nodes = []
        for it in items:
            alloc = it["status"].get("allocatable", {})
            from edl_tpu.utils.quantity import (
                parse_count,
                parse_cpu_milli,
                parse_memory_mega,
            )

            labels = it["metadata"].get("labels", {})
            nodes.append(
                NodeInfo(
                    name=it["metadata"]["name"],
                    cpu_milli=parse_cpu_milli(alloc.get("cpu", 0)),
                    memory_mega=parse_memory_mega(alloc.get("memory", 0)),
                    tpu_chips=parse_count(alloc.get("google.com/tpu", 0)),
                    tpu_topology=labels.get(
                        "cloud.google.com/gke-tpu-topology", ""
                    ),
                    pool=labels.get("cloud.google.com/gke-nodepool", ""),
                )
            )
        return nodes

    def list_pods(self) -> List[PodInfo]:
        from edl_tpu.utils.quantity import (
            parse_count,
            parse_cpu_milli,
            parse_memory_mega,
        )

        items = self._run("get", "pods")["items"]
        pods = []
        for it in items:
            cpu = mem = tpu = 0
            for c in it["spec"].get("containers", []):
                req = c.get("resources", {}).get("requests", {})
                lim = c.get("resources", {}).get("limits", {})
                cpu += parse_cpu_milli(req.get("cpu", 0))
                mem += parse_memory_mega(req.get("memory", 0))
                tpu += parse_count(lim.get("google.com/tpu", 0))
            pods.append(
                PodInfo(
                    name=it["metadata"]["name"],
                    job_name=it["metadata"].get("labels", {}).get("edl-job", ""),
                    phase=it["status"].get("phase", "Pending"),
                    node=it["spec"].get("nodeName", ""),
                    cpu_request_milli=cpu,
                    memory_request_mega=mem,
                    tpu_limit=tpu,
                    deleting="deletionTimestamp" in it["metadata"],
                    created=it["metadata"].get("creationTimestamp", ""),
                )
            )
        return pods

    def get_workload(
        self, name: str, kind: str = "Job"
    ) -> Optional[WorkloadInfo]:
        try:
            it = self._run("get", kind.lower(), name)
        except subprocess.CalledProcessError:
            return None
        spec = it["spec"]
        tmpl = spec["template"]["spec"]["containers"][0]
        from edl_tpu.utils.quantity import (
            parse_count,
            parse_cpu_milli,
            parse_memory_mega,
        )

        req = tmpl.get("resources", {}).get("requests", {})
        lim = tmpl.get("resources", {}).get("limits", {})
        labels = it["metadata"].get("labels", {})
        return WorkloadInfo(
            name=name,
            job_name=labels.get("edl-job", name),
            parallelism=spec.get(
                "parallelism", spec.get("replicas", 0)
            ),
            cpu_request_milli=parse_cpu_milli(req.get("cpu", 0)),
            memory_request_mega=parse_memory_mega(req.get("memory", 0)),
            tpu_limit=parse_count(lim.get("google.com/tpu", 0)),
            resource_version=int(it["metadata"]["resourceVersion"]),
            kind=it.get("kind", kind),
            owner=labels.get("edl-job", labels.get("edl-owner", "")),
        )

    def list_workloads(self) -> List[WorkloadInfo]:
        """Framework-owned workloads via label selectors: trainer Jobs
        carry ``edl-job``, coordinator Deployments ``edl-owner``."""
        out: List[WorkloadInfo] = []
        for kind_plural, kind, selector in (
            ("jobs", "Job", "edl-job"),
            ("deployments", "Deployment", "edl-owner"),
        ):
            try:
                items = self._run("get", kind_plural, "-l", selector)["items"]
            except subprocess.CalledProcessError:
                continue
            for it in items:
                labels = it["metadata"].get("labels", {})
                out.append(
                    WorkloadInfo(
                        name=it["metadata"]["name"],
                        job_name=labels.get("edl-job", it["metadata"]["name"]),
                        parallelism=it["spec"].get(
                            "parallelism", it["spec"].get("replicas", 1)
                        ),
                        resource_version=int(
                            it["metadata"].get("resourceVersion", 0)
                        ),
                        kind=kind,
                        owner=labels.get(
                            "edl-job", labels.get("edl-owner", "")
                        ),
                    )
                )
        return out

    def update_workload(self, w: WorkloadInfo) -> WorkloadInfo:
        # Include resourceVersion in the merge patch so the API server
        # enforces the optimistic-concurrency precondition; a 409 maps to
        # ConflictError so Cluster.update_parallelism's retry loop works
        # identically against FakeKube and a real cluster.  The knob
        # follows the kind: batch Jobs scale through spec.parallelism,
        # Deployments (the serving replica fleet) through spec.replicas.
        knob = "replicas" if w.kind == "Deployment" else "parallelism"
        patch = {
            "metadata": {"resourceVersion": str(w.resource_version)},
            "spec": {knob: w.parallelism},
        }
        r = subprocess.run(
            [
                self.kubectl,
                "-n",
                self.namespace,
                "patch",
                w.kind.lower(),
                w.name,
                "--type=merge",
                "-p",
                json.dumps(patch),
            ],
            capture_output=True,
            text=True,
        )
        if r.returncode != 0:
            msg = r.stderr or r.stdout
            if "Conflict" in msg or "the object has been modified" in msg:
                raise ConflictError(msg.strip())
            raise RuntimeError(f"kubectl patch failed: {msg.strip()}")
        return self.get_workload(w.name, kind=w.kind)

    def update_training_job_status(
        self, name: str, status: dict, namespace: Optional[str] = None
    ) -> bool:
        r = subprocess.run(
            [
                self.kubectl,
                "-n",
                namespace or self.namespace,
                "patch",
                "trainingjob",
                name,
                "--subresource=status",
                "--type=merge",
                "-p",
                json.dumps({"status": status}),
            ],
            capture_output=True,
            text=True,
        )
        return r.returncode == 0

    def list_training_jobs(self) -> List[dict]:
        """All TrainingJob CRs across namespaces (the watch source,
        ref informer ListWatch ``pkg/controller.go:80-85``)."""
        r = subprocess.run(
            [self.kubectl, "get", "trainingjobs", "-A", "-o", "json"],
            capture_output=True,
            text=True,
        )
        if r.returncode != 0:
            raise RuntimeError(
                f"kubectl get trainingjobs failed: {(r.stderr or r.stdout).strip()}"
            )
        return json.loads(r.stdout).get("items", [])

    def apply_manifests(self, manifests: List[dict]) -> None:
        payload = json.dumps({"apiVersion": "v1", "kind": "List", "items": manifests})
        r = subprocess.run(
            [self.kubectl, "-n", self.namespace, "apply", "-f", "-"],
            input=payload,
            capture_output=True,
            text=True,
        )
        if r.returncode != 0:
            raise RuntimeError(
                f"kubectl apply failed: {(r.stderr or r.stdout).strip()}"
            )

    def delete_workload(self, name: str) -> bool:
        """Delete by name across the kinds a job owns: trainer batch
        Job, or coordinator Deployment + Service (same name)."""
        deleted = False
        for kind in ("job", "deployment", "service"):
            r = subprocess.run(
                [
                    self.kubectl,
                    "-n",
                    self.namespace,
                    "delete",
                    kind,
                    name,
                    "--ignore-not-found",
                ],
                capture_output=True,
                text=True,
            )
            if r.returncode == 0 and r.stdout.strip():
                deleted = True
        return deleted

    def delete_pod(self, name: str) -> bool:
        """Graceful named-pod delete (``--wait=false``: the pod keeps
        its SIGTERM grace window; the control loop must not block on
        it)."""
        r = subprocess.run(
            [
                self.kubectl,
                "-n",
                self.namespace,
                "delete",
                "pod",
                name,
                "--wait=false",
                "--ignore-not-found",
            ],
            capture_output=True,
            text=True,
        )
        return r.returncode == 0 and bool(r.stdout.strip())
