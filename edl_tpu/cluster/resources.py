"""Cluster resource value types.

Mirrors the reference's ``ClusterResource``/``Nodes`` structs
(``pkg/cluster.go:32-61``) with the GPU axis replaced by TPU chips.
These are plain mutable value types on purpose: the autoscaler's dry-run
simulates scaling decisions by mutating a *copy* of the inventory
(ref ``pkg/autoscaler.go:201-291``), and tests fabricate cluster state
as literals exactly like the reference's test suite does
(``pkg/autoscaler_internal_test.go:104-123``).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Dict


@dataclass
class Nodes:
    """Per-node idle resources (ref Nodes, pkg/cluster.go:58-61).

    ``tpu_free`` is new: free TPU chips per node pool, so slice
    assignability can be checked per pool (a v5e slice must come from
    one pool's contiguous capacity; we model pools at chip granularity)."""

    cpu_idle_milli: Dict[str, int] = field(default_factory=dict)
    memory_free_mega: Dict[str, int] = field(default_factory=dict)
    tpu_free: Dict[str, int] = field(default_factory=dict)
    #: Slice topology each pool schedules (e.g. "v5e-8", from the GKE
    #: node label) — empty/absent = untyped chip pool (tests, CPU).  A
    #: replica's slice must match the pool's topology: 16 free chips
    #: spread over two v5e-8 pools cannot host one v5e-16 replica.
    pool_topology: Dict[str, str] = field(default_factory=dict)
    #: Nodepool identity per node (GKE ``cloud.google.com/gke-nodepool``).
    #: A multi-host slice's host NODES share one nodepool == one
    #: physical slice; a hosts>1 replica must take all its nodes from
    #: ONE pool — free hosts on two different slices are not a slice.
    node_pool: Dict[str, str] = field(default_factory=dict)


@dataclass
class ClusterResource:
    """Cluster-wide totals/requests/limits (ref ClusterResource,
    pkg/cluster.go:32-54), with ``gpu_*`` -> ``tpu_*`` in chips."""

    node_count: int = 0

    tpu_total: int = 0
    tpu_request: int = 0
    tpu_limit: int = 0

    cpu_total_milli: int = 0
    cpu_request_milli: int = 0
    cpu_limit_milli: int = 0

    memory_total_mega: int = 0
    memory_request_mega: int = 0
    memory_limit_mega: int = 0

    nodes: Nodes = field(default_factory=Nodes)

    def deepcopy(self) -> "ClusterResource":
        return copy.deepcopy(self)

    # -- derived load fractions (used by the dry run's maxLoadDesired
    #    checks, ref pkg/autoscaler.go:259-278) -----------------------------
    def cpu_load(self) -> float:
        if self.cpu_total_milli <= 0:
            return 1.0
        return self.cpu_request_milli / self.cpu_total_milli

    def memory_load(self) -> float:
        if self.memory_total_mega <= 0:
            return 1.0
        return self.memory_request_mega / self.memory_total_mega

    def tpu_load(self) -> float:
        if self.tpu_total <= 0:
            return 1.0
        return self.tpu_limit / self.tpu_total

    def free_chips(self) -> int:
        """Unclaimed TPU chips (total minus the scheduled pods'
        limits) — the single number the fleet arbiter's chip market
        opens each tick with (``edl_tpu.fleet.inventory``)."""
        return max(0, self.tpu_total - self.tpu_limit)
