"""A ``kubectl`` stand-in backed by FakeKube, for end-to-end control
plane tests without a cluster.

Each invocation loads cluster state from the JSON file named by
``EDL_FAKE_KUBE_STATE``, performs one kubectl-shaped operation through
the *real* ``FakeKube`` implementation (so the Job-controller +
scheduler emulation applies), and writes the state back.  Point
``KubectlAPI(kubectl=<shim>)`` — where the shim execs
``python -m edl_tpu.cluster.fake_kubectl "$@"`` — at it and the entire
KubectlAPI surface (get/apply/patch/delete, TrainingJob CRs) runs
against deterministic in-memory semantics.

Supported verb shapes (exactly what ``KubectlAPI`` and the CLI emit):

- ``get nodes|pods|trainingjobs [-A] -o json``
- ``get job <name> -o json``
- ``apply -f -``                     (JSON List on stdin)
- ``patch job <name> --type=merge -p <json>``
- ``delete job|deployment|service|trainingjob|pod <name> [--ignore-not-found]``
"""

from __future__ import annotations

import json
import os
import sys
from typing import List

from edl_tpu.cluster.kube import (
    ConflictError,
    FakeKube,
    NodeInfo,
    PodInfo,
    WorkloadInfo,
)


def _load() -> tuple[FakeKube, dict]:
    path = os.environ["EDL_FAKE_KUBE_STATE"]
    with open(path) as f:
        raw = json.load(f)
    kube = FakeKube([NodeInfo(**n) for n in raw.get("nodes", [])])
    kube.workloads = {
        w["name"]: WorkloadInfo(**w) for w in raw.get("workloads", [])
    }
    kube.pods = {p["name"]: PodInfo(**p) for p in raw.get("pods", [])}
    kube.services = {s["metadata"]["name"]: s for s in raw.get("services", [])}
    kube._pod_seq = raw.get("pod_seq", 0)
    return kube, raw


def _save(kube: FakeKube, raw: dict) -> None:
    raw["nodes"] = [vars(n) for n in kube.nodes.values()]
    raw["workloads"] = [vars(w) for w in kube.workloads.values()]
    raw["pods"] = [vars(p) for p in kube.pods.values()]
    raw["services"] = list(kube.services.values())
    raw["pod_seq"] = kube._pod_seq
    path = os.environ["EDL_FAKE_KUBE_STATE"]
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(raw, f)
    os.replace(tmp, path)


def _node_manifest(n: NodeInfo) -> dict:
    labels = {}
    if n.tpu_topology:
        labels["cloud.google.com/gke-tpu-topology"] = n.tpu_topology
    if n.pool:
        labels["cloud.google.com/gke-nodepool"] = n.pool
    return {
        "metadata": {"name": n.name, "labels": labels},
        "status": {
            "allocatable": {
                "cpu": f"{n.cpu_milli}m",
                "memory": f"{n.memory_mega}Mi",
                "google.com/tpu": str(n.tpu_chips),
            }
        },
    }


def _pod_manifest(p: PodInfo) -> dict:
    meta = {"name": p.name, "labels": {"edl-job": p.job_name} if p.job_name else {}}
    if p.deleting:
        meta["deletionTimestamp"] = "1970-01-01T00:00:00Z"
    return {
        "metadata": meta,
        "status": {"phase": p.phase},
        "spec": {
            "nodeName": p.node,
            "containers": [
                {
                    "resources": {
                        "requests": {
                            "cpu": f"{p.cpu_request_milli}m",
                            "memory": f"{p.memory_request_mega}Mi",
                        },
                        "limits": {"google.com/tpu": str(p.tpu_limit)},
                    }
                }
            ],
        },
    }


def _job_manifest(w: WorkloadInfo) -> dict:
    if w.kind == "Deployment":
        labels = {"edl-owner": w.owner} if w.owner else {}
        knob = {"replicas": w.parallelism}
    else:
        labels = {"edl-job": w.owner or w.job_name}
        knob = {"parallelism": w.parallelism}
    return {
        "kind": w.kind,
        "metadata": {
            "name": w.name,
            "labels": labels,
            "resourceVersion": str(w.resource_version),
        },
        "spec": {
            **knob,
            "template": {
                "spec": {
                    "containers": [
                        {
                            "resources": {
                                "requests": {
                                    "cpu": f"{w.cpu_request_milli}m",
                                    "memory": f"{w.memory_request_mega}Mi",
                                },
                                "limits": {"google.com/tpu": str(w.tpu_limit)},
                            }
                        }
                    ]
                }
            },
        },
    }


def main(argv: List[str]) -> int:
    # Strip flags KubectlAPI interleaves; record the ones that matter.
    args: List[str] = []
    out_json = False
    selector = None
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "-n":
            i += 2
            continue
        if a == "-o":
            out_json = argv[i + 1] == "json"
            i += 2
            continue
        if a == "-l":
            selector = argv[i + 1]
            i += 2
            continue
        if a in ("-A", "--ignore-not-found", "--wait=false"):
            i += 1
            continue
        args.append(a)
        i += 1

    kube, raw = _load()
    verb = args[0]

    if verb == "get":
        kind = args[1]
        if kind == "nodes":
            print(json.dumps({"items": [_node_manifest(n) for n in kube.list_nodes()]}))
        elif kind == "pods":
            print(json.dumps({"items": [_pod_manifest(p) for p in kube.list_pods()]}))
        elif kind == "trainingjobs":
            print(json.dumps({"items": raw.get("trainingjobs", [])}))
        elif kind in ("jobs", "deployments"):
            want = "Deployment" if kind == "deployments" else "Job"
            items = []
            for w in kube.list_workloads():
                if w.kind != want:
                    continue
                m = _job_manifest(w)
                if selector and selector not in m["metadata"]["labels"]:
                    continue
                items.append(m)
            print(json.dumps({"items": items}))
        elif kind in ("job", "deployment"):
            w = kube.get_workload(args[2])
            want = "Deployment" if kind == "deployment" else "Job"
            if w is None or w.kind != want:
                print(
                    f'Error from server (NotFound): {kind}s "{args[2]}" '
                    "not found",
                    file=sys.stderr,
                )
                return 1
            print(json.dumps(_job_manifest(w)))
        else:
            print(f"fake-kubectl: unsupported get {kind}", file=sys.stderr)
            return 1
        return 0

    if verb == "apply":
        payload = json.loads(sys.stdin.read())
        items = payload.get("items", [payload])
        crs = {m["metadata"]["name"]: m for m in raw.get("trainingjobs", [])}
        rest = []
        for m in items:
            if m.get("kind") == "TrainingJob":
                # A real API server assigns the object UID on creation;
                # ownerReferences on rendered workloads depend on it.
                prior = crs.get(m["metadata"]["name"])
                m["metadata"].setdefault(
                    "uid",
                    (prior or {}).get("metadata", {}).get("uid")
                    or f"uid-{m['metadata']['name']}",
                )
                crs[m["metadata"]["name"]] = m
            else:
                rest.append(m)
        raw["trainingjobs"] = list(crs.values())
        if rest:
            kube.apply_manifests(rest)
        _save(kube, raw)
        for m in items:
            print(f"{m.get('kind', 'object').lower()}/{m['metadata']['name']} configured")
        return 0

    if verb == "patch":
        # patch job|trainingjob <name> [--subresource=status] --type=merge -p <json>
        name = args[2]
        patch = json.loads(args[args.index("-p") + 1])
        if args[1] == "trainingjob":
            for m in raw.get("trainingjobs", []):
                if m["metadata"]["name"] == name:
                    m.setdefault("status", {}).update(patch.get("status", {}))
                    _save(kube, raw)
                    print(f"trainingjob/{name} patched")
                    return 0
            print(
                f'Error from server (NotFound): trainingjobs "{name}" not found',
                file=sys.stderr,
            )
            return 1
        w = kube.get_workload(name)
        if w is None:
            print(
                f'Error from server (NotFound): {args[1]}s "{name}" not '
                "found",
                file=sys.stderr,
            )
            return 1
        rv = patch.get("metadata", {}).get("resourceVersion")
        if rv is not None:
            w.resource_version = int(rv)
        spec = patch.get("spec", {})
        # Jobs scale through spec.parallelism, Deployments (the serving
        # replica fleet) through spec.replicas — one knob either way.
        w.parallelism = spec.get(
            "replicas", spec.get("parallelism", w.parallelism)
        )
        try:
            kube.update_workload(w)
        except ConflictError as e:
            print(f"Error from server (Conflict): {e}", file=sys.stderr)
            return 1
        _save(kube, raw)
        print(f"{args[1]}/{name} patched")
        return 0

    if verb == "delete":
        kind, name = args[1], args[2]
        if kind == "pod":
            existed = kube.delete_pod(name)
            _save(kube, raw)
            if existed:
                print(f"pod/{name} deleted")
            return 0
        if kind == "trainingjob":
            before = raw.get("trainingjobs", [])
            raw["trainingjobs"] = [m for m in before if m["metadata"]["name"] != name]
            _save(kube, raw)
            if len(raw["trainingjobs"]) < len(before):
                print(f"trainingjob/{name} deleted")
            return 0
        existed = (
            kube.delete_workload(name)
            if kind in ("job", "deployment")
            else kube.services.pop(name, None) is not None
        )
        _save(kube, raw)
        if existed:
            print(f"{kind}/{name} deleted")
        return 0

    print(f"fake-kubectl: unsupported verb {verb}", file=sys.stderr)
    return 1


if __name__ == "__main__":  # pragma: no cover - subprocess entrypoint
    sys.exit(main(sys.argv[1:]))
