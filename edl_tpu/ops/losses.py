"""Memory-fused losses for large-vocabulary models.

The naive tied-softmax cross entropy materializes [B, T, V] logits in
HBM (f32: gigabytes at training batch sizes) and then reads them twice
more (logsumexp + gather) — at transformer-base scale that HBM traffic,
not FLOPs, dominates the step.  ``tied_vocab_xent`` computes the same
loss in row chunks under ``jax.checkpoint``: the vocab projection, the
logsumexp and the label gather happen per chunk and the logits of a
chunk die in registers/VMEM before the next chunk starts.  Backward
rematerializes each chunk's logits (one extra vocab matmul — FLOPs are
cheap here, bytes are not) and accumulates dE across chunks via the
scan's closed-over embedding.

The reference has no loss code at all (training was external to the
controller repo, SURVEY.md §0); this is trainer-half infrastructure the
TPU rebuild owns.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def tied_vocab_xent(
    features: jax.Array,
    embedding: jax.Array,
    labels: jax.Array,
    valid: jax.Array,
    chunk_rows: int = 8192,
    compute_dtype=jnp.bfloat16,
    with_accuracy: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Softmax cross entropy against a weight-tied vocab projection.

    features:  [B, T, D] pre-projection activations (any float dtype).
    embedding: [V, D] tied embedding table.
    labels:    [B, T] int32 target ids.
    valid:     [B, T] bool/float — 1 where the token counts.

    Returns (mean_nll, mean_accuracy) over valid tokens.  The vocab
    matmul runs with ``compute_dtype`` operands and f32 MXU
    accumulation (an f32 [*, V] matmul runs far below bf16 peak).
    """
    b, t, d = features.shape
    n = b * t
    y = features.reshape(n, d)
    lab = labels.reshape(n).astype(jnp.int32)
    val = valid.reshape(n).astype(jnp.float32)

    c = min(chunk_rows, n)
    pad = (-n) % c
    if pad:
        y = jnp.pad(y, ((0, pad), (0, 0)))
        lab = jnp.pad(lab, (0, pad))
        val = jnp.pad(val, (0, pad))  # pads are invalid -> contribute 0
    chunks = (n + pad) // c
    y = y.reshape(chunks, c, d)
    lab = lab.reshape(chunks, c)
    val = val.reshape(chunks, c)

    emb = embedding.astype(compute_dtype)

    def one_chunk(carry, xs):
        loss_sum, correct_sum = carry
        yc, lc, vc = xs  # [c, D], [c], [c]
        logits = jnp.einsum(
            "cd,vd->cv",
            yc.astype(compute_dtype),
            emb,
            preferred_element_type=jnp.float32,
        )  # [c, V] — lives only inside this chunk
        lse = jax.nn.logsumexp(logits, axis=-1)
        label_logit = jnp.take_along_axis(logits, lc[:, None], axis=-1)[:, 0]
        loss_sum = loss_sum + ((lse - label_logit) * vc).sum()
        if with_accuracy:
            correct = (jnp.argmax(logits, axis=-1) == lc).astype(jnp.float32)
            correct_sum = correct_sum + (correct * vc).sum()
        return (loss_sum, correct_sum), None

    (loss_sum, correct_sum), _ = jax.lax.scan(
        jax.checkpoint(one_chunk), (jnp.float32(0), jnp.float32(0)),
        (y, lab, val),
    )
    denom = jnp.maximum(val.sum(), 1.0)
    return loss_sum / denom, correct_sum / denom


def best_vocab_xent(
    features: jax.Array,
    embedding: jax.Array,
    labels: jax.Array,
    valid: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Best tied-vocab cross entropy for the current backend: the fused
    Pallas kernels on TPU (logits never leave VMEM — ~2x faster at 32k
    vocab), this module's chunked jnp path elsewhere (it doubles as the
    correctness oracle in tests)."""
    if jax.default_backend() == "tpu":
        from edl_tpu.ops.fused_xent import fused_vocab_xent

        return fused_vocab_xent(features, embedding, labels, valid)
    return tied_vocab_xent(features, embedding, labels, valid)
