"""Flash attention as Pallas TPU kernels — forward AND backward.

The single-device attention hot path: blockwise online-softmax so the
[T, T] score matrix never materializes in HBM — scores live in VMEM one
(block_q x block_k) tile at a time, matmuls hit the MXU in f32
accumulation, and causal runs skip fully-masked K blocks entirely.

Layering: ``ring_attention`` (sequence parallel, ``ops/ring_attention``)
distributes the sequence *across chips*; this kernel optimizes the
*within-chip* block loop.  They compose concretely: the ring's
per-step local attention IS this kernel via
``flash_attention_with_lse``, whose differentiable lse output feeds
the ring's normalized-partial merge.

Backward — TWO implementations behind one dispatch (``_bwd_common``):

- **merged** (estimated VMEM residency — which scales with T*d —
  within the 100MB cap, up to T=16384; ``_merged_bwd_fits``): a
  single blockwise kernel with saved
  residuals — the forward emits per-row logsumexp (O(T) stats,
  broadcast over STAT_LANES trailing values so tiles stay legal
  (sublane, lane) shapes), and ONE backward pass recomputes each
  probability tile once to produce dQ, dK and dV together (dK/dV
  accumulate in f32 VMEM scratch while Q tiles stream; the split
  dq/dkv formulation pays the score dot and the exp twice — merging
  them measured +15% tokens/s on the T=2048 LM).  Its VMEM footprint
  grows with T; past T=2048 it needs the scoped-VMEM limit raised
  above the 16MB default (``_vmem_limit`` — v5e has the physical
  headroom), which measures 0.428 MFU at T=4096, 0.408 at 8192 and
  0.388 at 16384 single-chip.
- **streaming-K** (everything larger — long T, or wide heads like
  d=128 near T=16384 whose capped grant the merged residency would
  overflow): K blocks become the outer grid dim, so
  only one (block_k, d) K/V block + scratch is resident — VMEM use
  depends on block_k, not T (block_k grows with T for fewer Q
  re-streams, capped at 16384 to stay inside the VMEM grant; the dQ
  partials buffer makes HBM the eventual bound at extreme T).  dQ
  comes out as per-K-block f32 partials summed by XLA, and the softmax
  correction delta arrives precomputed (per row, not per K block).

In the merged kernel the softmax correction delta = rowsum(dO * O) is
computed in-kernel from the O/dO tiles, so nothing O(T^2) — and no
extra stats array — ever hits HBM in either direction.

Masking: ``causal`` masks by absolute position inside the kernel (and
skips fully-masked K tiles); ``kv_mask`` ([B, Tk] bool, True = valid)
handles padded batches so the kernel can serve the padded-seq2seq
models (``models/transformer.py``) and not just LM stacks.

On non-TPU backends the kernels run in Pallas interpret mode, so tests
validate the identical code path on the CPU mesh.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

# Hard dependency: the backward kernel needs pltpu.VMEM scratch (a
# clear import error beats an AttributeError deep inside a custom_vjp).
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

#: Trailing width for the per-row logsumexp residual between forward
#: and backward.  Stats live lane-broadcast *inside* kernels (the
#: standard TPU trick for per-row scalars), but storing all 128 lanes
#: to HBM pays 128x the bytes the stat needs (ADVICE r3); 8 trailing
#: values keep every tile a legal (sublane, lane) shape while cutting
#: the residual 16x (at T=8k training shapes: 16MB instead of 268MB).
STAT_LANES = 8

#: Default tile sizes (overridable per call).  Re-swept in-model on
#: v5e at T=2048 with the merged single-pass backward (bq/bk in
#: {128, 256, 512, 1024}): full-step time is 222ms at 128x128, 131ms
#: at 256x256, **114ms at 512x512**, and 1024x1024 overflows the 16MB
#: VMEM scoped allocation in the backward.  (The r3 sweep that picked
#: 128x128 predates the merged backward.)  Larger tiles win because
#: each (i, j) tile pair pays fixed VPU work — mask iota, online-
#: softmax carries — per tile, and 1/16th the tiles means 1/16th that
#: overhead while the MXU dots stay the same total size.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512


def _pick_block(t: int, want: int) -> int:
    """Largest divisor of ``t`` that is <= want (prefers want itself)."""
    b = min(want, t)
    while t % b != 0:
        b -= 1
    return b


#: Context length above which the backward switches from the merged
#: single-pass kernel to the streaming-K kernel.  The merged kernel's
#: residency (K/V + full-T dK/dV f32 scratch per bh) grows with T, but
#: v5e's physical VMEM is far above the 16MB default scoped limit:
#: raising ``vmem_limit_bytes`` (see ``_vmem_limit``) runs it clean to
#: T=16384 — measured 0.428 MFU at T=4096 (vs 0.389 streaming-K),
#: 0.408 at 8192, 0.388 at 16384.  Streaming-K (VMEM-independent of T)
#: remains the fallback beyond.
_MERGED_BWD_MAX_T = 16384

#: Scoped-VMEM ceiling any kernel may be granted (v5e physical VMEM
#: minus headroom); the DISPATCH predicate, not just the grant, must
#: respect it (see ``_merged_bwd_fits``).
_VMEM_CAP_BYTES = 100 * 1024 * 1024

#: Test hook: force a backward implementation ("merged" | "streamk");
#: None = pick by _merged_bwd_fits.
_BWD_IMPL_OVERRIDE = None


def _merged_bwd_residency(tk: int, d: int) -> int:
    """Estimated scoped-VMEM residency of the merged backward: the
    16MB baseline plus ~12 bytes/key-position/lane (K, V bf16 + dK/dV
    f32 scratch) granted at 4x for double-buffering slack.  Scales
    with T*d — the HEAD DIM matters as much as the context length."""
    return 16 * 1024 * 1024 + 4 * tk * d * 12


def _merged_bwd_fits(tk: int, d: int) -> bool:
    """Whether the merged single-pass backward fits its VMEM grant.

    Dispatching on T alone (the r5 rule: merged iff T <= 16384) hid a
    d-shaped hole: residency scales with T*d, and ``_vmem_limit`` CAPS
    the grant at 100MB — so at d=128 near T=16384 the capped grant is
    smaller than the estimated residency and the merged kernel risks a
    scoped-VMEM overflow (ADVICE r5).  Folding d into the predicate
    switches exactly those shapes to the streaming-K fallback, whose
    residency depends on block_k, not T*d."""
    return tk <= _MERGED_BWD_MAX_T and _merged_bwd_residency(tk, d) <= _VMEM_CAP_BYTES


def _vmem_limit(tk: int, d: int):
    """Scoped-VMEM limit for long-context kernels: None keeps the 16MB
    default where the merged backward measurably fits it (T*d up to
    the 2048 x 64 reference shape — keyed on T*d, not T alone, so a
    wide-head short-context shape like T=2048/d=256 gets a raised
    grant instead of silently overflowing the default); beyond, grant
    the merged backward's estimated residency
    (``_merged_bwd_residency``), capped at the physical ceiling (64MB
    measured sufficient at T=16384, d=64 on v5e).  Shapes whose
    estimate EXCEEDS the cap never run the merged kernel
    (``_merged_bwd_fits``), so the grant covers the estimate whenever
    merged is dispatched; past the cap this limit sizes the
    streaming-K kernel, whose residency is block_k-bound."""
    if tk * d <= 2048 * 64:
        return None
    return min(_merged_bwd_residency(tk, d), _VMEM_CAP_BYTES)


def _compiler_params(tk: int, d: int):
    limit = _vmem_limit(tk, d)
    if limit is None:
        return {}
    return {
        "compiler_params": pltpu.CompilerParams(vmem_limit_bytes=limit)
    }


#: Streaming-K backward tile defaults (tk > _MERGED_BWD_MAX_T), from
#: the v5e full-step sweep at T=4096 batch 4: 256x2048 0.1891 s,
#: 512x1024 0.1903, 512x512 0.2144, 128x2048 0.2108; 512x2048
#: overflows scoped VMEM by 84KB.  Tall K blocks win: fewer Q
#: re-streams and fewer dQ partials, while the (block_k, d) scratch
#: stays far under the VMEM roof.
_STREAMK_BWD_BLOCK_Q = 256
_STREAMK_BWD_BLOCK_K = 2048


def _safe(m):
    """Replace NEG_INF row-maxima with 0 so fully-masked rows produce
    p == exp(NEG_INF - 0) == 0 instead of exp(0) == 1."""
    return jnp.where(m <= NEG_INF / 2, 0.0, m)


def _adapt_optional(kernel, n_base, present):
    """Adapt a kernel written with trailing optional input slots (in
    signature order) to a pallas_call that passes only the live ones —
    absent slots reach the kernel as None."""
    n_in = n_base + sum(present)

    def wrapped(*refs):
        ins, outs = refs[:n_in], refs[n_in:]
        rest = iter(ins[n_base:])
        opts = [next(rest) if p else None for p in present]
        return kernel(*ins[:n_base], *opts, *outs)

    return wrapped


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, mask_ref, o_ref, lse_ref,
    *, scale, causal, block_k, kv_len, has_mask,
):
    # Dots take the refs' native dtype (bf16 in production) with f32 MXU
    # accumulation — f32 operands would fall off the fast MXU path and
    # run several times slower.  Scale applies to the f32 product.
    qb = q_ref[0]  # [block_q, D]
    block_q = qb.shape[0]
    i = pl.program_id(1)
    num_k = kv_len // block_k

    q_pos = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0
    )  # [bq, 1]

    def make_body(diag):
        """``diag=False``: tile pairs strictly below the causal
        diagonal — no position mask needed, so the iota/where VPU work
        is skipped entirely (it is per-tile overhead that tiling can't
        amortize).  ``diag=True``: diagonal tiles, position-masked."""

        def body(j, carry):
            acc, m, l = carry
            kb = k_ref[0, pl.ds(j * block_k, block_k), :]
            vb = v_ref[0, pl.ds(j * block_k, block_k), :]
            s = scale * jax.lax.dot_general(
                qb,
                kb,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [bq, bk]
            if causal and diag:
                k_pos = j * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (1, block_k), 1
                )
                s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            if has_mask:
                valid = mask_ref[0, :, pl.ds(j * block_k, block_k)] != 0
                s = jnp.where(valid, s, NEG_INF)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            m_use = _safe(m_new)
            p = jnp.exp(s - m_use)
            alpha = jnp.exp(_safe(m) - m_use)  # [bq,1]
            l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                p.astype(vb.dtype),
                vb,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            acc = acc * alpha + pv
            return acc, m_new, l

        return body

    d = q_ref.shape[-1]
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    if causal:
        # K blocks entirely at-or-before this Q block's first position
        # are never masked; blocks past its last position are fully
        # masked and skipped (the flash speedup for causal); the strip
        # between runs the masked body.
        full = (i * block_q + 1) // block_k
        upper = jnp.minimum(num_k, pl.cdiv((i + 1) * block_q, block_k))
        carry = jax.lax.fori_loop(0, full, make_body(False), (acc0, m0, l0))
        acc, m, l = jax.lax.fori_loop(full, upper, make_body(True), carry)
    else:
        acc, m, l = jax.lax.fori_loop(
            0, num_k, make_body(False), (acc0, m0, l0)
        )
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    lse = _safe(m) + jnp.log(l_safe)  # [bq, 1]
    lse_ref[0] = jax.lax.broadcast_in_dim(
        lse.reshape(block_q), (block_q, STAT_LANES), (0,)
    )


def _flash_fwd_3d(q, k, v, mask, causal, scale, block_q, block_k, interpret):
    """q: [BH, Tq, D]; k, v: [BH, Tk, D]; mask: [B, Tk] int32 or None.

    Returns (o [BH, Tq, D], lse [BH, Tq, STAT_LANES] f32, broadcast)."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    has_mask = mask is not None
    kernel = functools.partial(
        _fwd_kernel,
        scale=scale,
        causal=causal,
        block_k=block_k,
        kv_len=tk,
        has_mask=has_mask,
    )
    grid = (bh, tq // block_q)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0)),
        pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0)),
    ]
    args = [q, k, v]
    if has_mask:
        heads = bh // mask.shape[0]
        in_specs.append(
            pl.BlockSpec((1, 1, tk), lambda b, i, h=heads: (b // h, 0, 0))
        )
        args.append(mask)
    return pl.pallas_call(
        _adapt_optional(kernel, 3, (has_mask,)),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, STAT_LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((bh, tq, STAT_LANES), jnp.float32),
        ],
        interpret=interpret,
        **_compiler_params(tk, d),
    )(*args)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _row_stat(ref2d):
    """Collapse a broadcast [rows, STAT_LANES] stat tile to [rows, 1]
    (all lanes hold the same value; a lane reduction is the portable
    way to read one back)."""
    return jnp.max(ref2d, axis=-1, keepdims=True)


def _bwd_kernel(
    q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, glse_ref, mask_ref,
    dq_ref, dk_ref, dv_ref, dk_scr, dv_scr,
    *, scale, causal, block_k, kv_len, num_i, has_mask, has_glse,
):
    """Single-pass backward: dQ, dK and dV in one sweep.

    Grid (BH, Tq/block_q); K/V stay resident per bh while Q/dO/O tiles
    stream; dK/dV accumulate in f32 VMEM scratch and flush on the last
    Q tile.  One score dot and ONE exp per (i, j) tile pair — the
    split dq/dkv formulation pays both twice."""
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    qb = q_ref[0]  # [bq, D] — native dtype into the dots (see _fwd_kernel)
    ob = o_ref[0].astype(jnp.float32)
    dob = do_ref[0]
    dob_f32 = dob.astype(jnp.float32)
    block_q = qb.shape[0]
    num_k = kv_len // block_k
    lse = _row_stat(lse_ref[0])  # [bq, 1]
    delta = jnp.sum(dob_f32 * ob, axis=-1, keepdims=True)  # [bq, 1]
    if has_glse:
        # The lse output's cotangent enters ds exactly like -delta:
        # d lse / d s_ij = p_ij, so ds = p * (dp - delta + glse).
        delta = delta - _row_stat(glse_ref[0])

    q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)

    def make_body(diag):
        """Same sub-diagonal/diagonal split as the forward: tiles
        strictly below the causal diagonal skip the mask iota/where."""

        def body(j, dq_acc):
            kb = k_ref[0, pl.ds(j * block_k, block_k), :]
            vb = v_ref[0, pl.ds(j * block_k, block_k), :]
            s = scale * jax.lax.dot_general(
                qb, kb,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [bq, bk]
            if causal and diag:
                k_pos = j * block_k + jax.lax.broadcasted_iota(
                    jnp.int32, (1, block_k), 1
                )
                s = jnp.where(q_pos >= k_pos, s, NEG_INF)
            if has_mask:
                valid = mask_ref[0, :, pl.ds(j * block_k, block_k)] != 0
                s = jnp.where(valid, s, NEG_INF)
            p = jnp.exp(s - lse)  # [bq, bk]; masked -> exp(NEG_INF-lse) == 0
            dp = jax.lax.dot_general(
                dob, vb,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [bq, bk]
            ds = (p * (dp - delta)).astype(kb.dtype)
            dv_scr[pl.ds(j * block_k, block_k), :] += jax.lax.dot_general(
                p.astype(dob.dtype), dob,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [bk, D]
            dk_scr[pl.ds(j * block_k, block_k), :] += jax.lax.dot_general(
                ds, qb,
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [bk, D]
            return dq_acc + jax.lax.dot_general(
                ds, kb,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        return body

    d = q_ref.shape[-1]
    dq0 = jnp.zeros((block_q, d), jnp.float32)
    if causal:
        full = (i * block_q + 1) // block_k
        upper = jnp.minimum(num_k, pl.cdiv((i + 1) * block_q, block_k))
        acc = jax.lax.fori_loop(0, full, make_body(False), dq0)
        acc = jax.lax.fori_loop(full, upper, make_body(True), acc)
    else:
        acc = jax.lax.fori_loop(0, num_k, make_body(False), dq0)
    dq_ref[0] = (acc * scale).astype(dq_ref.dtype)

    @pl.when(i == num_i - 1)
    def _emit():
        dk_ref[0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_3d(
    q, k, v, o, lse, do, glse, mask, causal, scale, block_q, block_k,
    interpret,
):
    bh, tq, d = q.shape
    tk = k.shape[1]
    has_mask = mask is not None
    has_glse = glse is not None
    heads = bh // mask.shape[0] if has_mask else 1
    num_i = tq // block_q

    kernel = functools.partial(
        _bwd_kernel,
        scale=scale, causal=causal, block_k=block_k, kv_len=tk,
        num_i=num_i, has_mask=has_mask, has_glse=has_glse,
    )
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),      # q
        pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0)),           # k
        pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0)),           # v
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),      # o
        pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),      # do
        pl.BlockSpec((1, block_q, STAT_LANES), lambda b, i: (b, i, 0)),  # lse
    ]
    args = [q, k, v, o, do, lse]
    if has_glse:
        in_specs.append(
            pl.BlockSpec((1, block_q, STAT_LANES), lambda b, i: (b, i, 0))
        )
        args.append(glse)
    if has_mask:
        in_specs.append(
            pl.BlockSpec((1, 1, tk), lambda b, i, h=heads: (b // h, 0, 0))
        )
        args.append(mask)
    dq, dk, dv = pl.pallas_call(
        _adapt_optional(kernel, 6, (has_glse, has_mask)),
        grid=(bh, num_i),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tk, d), lambda b, i: (b, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((tk, d), jnp.float32),
            pltpu.VMEM((tk, d), jnp.float32),
        ],
        interpret=interpret,
        **_compiler_params(tk, d),
    )(*args)
    return dq, dk, dv


def _bwd_streamk_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, mask_ref,
    dqp_ref, dk_ref, dv_ref, dk_scr, dv_scr,
    *, scale, causal, num_i, has_mask,
):
    """Streaming-K backward: grid (BH, Tk/block_k, Tq/block_q).

    The merged kernel (``_bwd_kernel``) keeps K/V + full-T dK/dV f32
    scratch resident per bh, which overflows VMEM past T=2048 at 512
    tiles and fits NOTHING at T=8192.  Here K blocks are the OUTER grid
    dim: only one (block_k, d) K/V block and its (block_k, d) dK/dV
    scratch are resident — VMEM use depends on block_k, not T (see
    ``_prep`` for the growth/cap policy); HBM for the dQ partials is
    the eventual bound.  The price: Q/dO/stat tiles re-stream per K
    block, and dQ comes out as per-K-block PARTIALS (f32,
    [BH, num_j, Tq, D]) summed by XLA afterwards — in-kernel dQ
    accumulation across the grid would need non-consecutive output
    revisits, which Pallas TPU does not keep (same dead end as the
    fused-xent merge attempt).

    Unlike the merged kernel, the softmax correction delta =
    rowsum(dO * O) [- glse] arrives PRECOMPUTED (one cheap XLA
    elementwise reduce per backward): computing it in-kernel would
    re-read the O tile and redo the rowsum once per K block instead of
    once per row."""
    j = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    qb = q_ref[0]  # [bq, D]
    kb = k_ref[0]  # [bk, D]
    vb = v_ref[0]
    block_q = qb.shape[0]
    block_k = kb.shape[0]

    # Causal tile classification from the block indices alone.
    if causal:
        # max q_pos < min k_pos -> every score masked; skip everything.
        fully_masked = (i + 1) * block_q - 1 < j * block_k
        # min q_pos >= max k_pos -> nothing masked; skip the iota/where.
        needs_mask_pred = i * block_q < (j + 1) * block_k - 1

    def compute():
        dob = do_ref[0]
        lse = _row_stat(lse_ref[0])  # [bq, 1]
        delta = _row_stat(delta_ref[0])  # [bq, 1]
        s = scale * jax.lax.dot_general(
            qb, kb,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0
            )
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1
            )
            s = jnp.where(
                jnp.logical_or(
                    jnp.logical_not(needs_mask_pred), q_pos >= k_pos
                ),
                s,
                NEG_INF,
            )
        if has_mask:
            valid = mask_ref[0] != 0  # [1, bk]
            s = jnp.where(valid, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            dob, vb,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta)).astype(kb.dtype)
        dv_scr[...] += jax.lax.dot_general(
            p.astype(dob.dtype), dob,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dk_scr[...] += jax.lax.dot_general(
            ds, qb,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return jax.lax.dot_general(
            ds, kb,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    if causal:
        # pl.when (not a value-returning cond): the compute branch also
        # writes the dK/dV scratch, and divergent ref writes belong in
        # when-blocks, not lax.cond branches.
        @pl.when(fully_masked)
        def _masked():
            dqp_ref[0, 0] = jnp.zeros((block_q, qb.shape[-1]), jnp.float32)

        @pl.when(jnp.logical_not(fully_masked))
        def _live():
            dqp_ref[0, 0] = compute()
    else:
        dqp_ref[0, 0] = compute()

    @pl.when(i == num_i - 1)
    def _emit():
        dk_ref[0] = (dk_scr[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_streamk_3d(
    q, k, v, o, lse, do, glse, mask, causal, scale, block_q, block_k,
    interpret,
):
    bh, tq, d = q.shape
    tk = k.shape[1]
    has_mask = mask is not None
    has_glse = glse is not None
    heads = bh // mask.shape[0] if has_mask else 1
    num_i = tq // block_q
    num_j = tk // block_k

    # Precompute the softmax correction once per ROW (the merged kernel
    # derives it per Q tile from the O/dO tiles; here every K block
    # would redo it): delta = rowsum(dO * O) [- glse], STAT_LANES-
    # broadcast like the lse residual.
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )  # [bh, tq]
    if has_glse:
        delta = delta - glse[:, :, 0]
    delta = jnp.broadcast_to(delta[:, :, None], (bh, tq, STAT_LANES))

    kernel = functools.partial(
        _bwd_streamk_kernel,
        scale=scale, causal=causal, num_i=num_i, has_mask=has_mask,
    )
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),       # q
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),       # k
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),       # v
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),       # do
        pl.BlockSpec(
            (1, block_q, STAT_LANES), lambda b, j, i: (b, i, 0)
        ),                                                              # lse
        pl.BlockSpec(
            (1, block_q, STAT_LANES), lambda b, j, i: (b, i, 0)
        ),                                                              # delta
    ]
    args = [q, k, v, do, lse, delta]
    if has_mask:
        in_specs.append(
            pl.BlockSpec(
                (1, 1, block_k), lambda b, j, i, h=heads: (b // h, 0, j)
            )
        )
        args.append(mask)
    dqp, dk, dv = pl.pallas_call(
        _adapt_optional(kernel, 6, (has_mask,)),
        grid=(bh, num_j, num_i),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b, j, i: (b, j, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, num_j, tq, d), jnp.float32),
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
        **_compiler_params(block_k, d),
    )(*args)
    dq = jnp.sum(dqp, axis=1).astype(q.dtype)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom_vjp plumbing
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _flash(
    q, k, v, mask, causal, scale, block_q, block_k, bwd_block_q,
    bwd_block_k, interpret,
):
    out, _ = _run(q, k, v, mask, causal, scale, block_q, block_k, interpret)
    return out


def _to3(x):
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _from3(x, b, h):
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _run(q, k, v, mask, causal, scale, block_q, block_k, interpret):
    b, t, h, d = q.shape
    out3, lse = _flash_fwd_3d(
        _to3(q), _to3(k), _to3(v), mask, causal, scale, block_q, block_k,
        interpret,
    )
    return _from3(out3, b, h), (out3, lse)


def _flash_fwd_rule(
    q, k, v, mask, causal, scale, block_q, block_k, bwd_block_q,
    bwd_block_k, interpret,
):
    out, (out3, lse) = _run(
        q, k, v, mask, causal, scale, block_q, block_k, interpret
    )
    return out, (q, k, v, out3, lse, mask)


def _bwd_common(res, g_o, glse3, causal, scale, bwd_block_q, bwd_block_k,
                interpret):
    """The one backward path both vjp rules share; ``glse3`` is the lse
    cotangent in residual layout ([BH, T, STAT_LANES]) or None."""
    q, k, v, out3, lse, mask = res
    b, t, h, d = q.shape
    tk = k.shape[1]
    impl = _BWD_IMPL_OVERRIDE or (
        "merged" if _merged_bwd_fits(tk, d) else "streamk"
    )
    bwd_3d = _flash_bwd_3d if impl == "merged" else _flash_bwd_streamk_3d
    dq3, dk3, dv3 = bwd_3d(
        _to3(q), _to3(k), _to3(v), out3, lse, _to3(g_o.astype(q.dtype)),
        glse3, mask, causal, scale, bwd_block_q, bwd_block_k, interpret,
    )
    dmask = (
        None
        if mask is None
        else np.zeros(mask.shape, dtype=jax.dtypes.float0)
    )
    return _from3(dq3, b, h), _from3(dk3, b, h), _from3(dv3, b, h), dmask


def _flash_bwd_rule(
    causal, scale, block_q, block_k, bwd_block_q, bwd_block_k, interpret,
    res, g,
):
    return _bwd_common(
        res, g, None, causal, scale, bwd_block_q, bwd_block_k, interpret
    )


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# -- (o, lse) variant: lse is a first-class differentiable output ----------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _flash_stats(
    q, k, v, mask, causal, scale, block_q, block_k, bwd_block_q,
    bwd_block_k, interpret,
):
    out, (out3, lse) = _run(
        q, k, v, mask, causal, scale, block_q, block_k, interpret
    )
    b, t, h, _ = q.shape
    return out, lse[:, :, 0].reshape(b, h, t)


def _flash_stats_fwd_rule(
    q, k, v, mask, causal, scale, block_q, block_k, bwd_block_q,
    bwd_block_k, interpret,
):
    out, (out3, lse) = _run(
        q, k, v, mask, causal, scale, block_q, block_k, interpret
    )
    b, t, h, _ = q.shape
    return (out, lse[:, :, 0].reshape(b, h, t)), (q, k, v, out3, lse, mask)


def _flash_stats_bwd_rule(
    causal, scale, block_q, block_k, bwd_block_q, bwd_block_k, interpret,
    res, g,
):
    g_o, g_lse = g
    b, t = res[0].shape[0], res[0].shape[1]
    h = res[0].shape[2]
    glse3 = jnp.broadcast_to(
        g_lse.astype(jnp.float32).reshape(b * h, t)[:, :, None],
        (b * h, t, STAT_LANES),
    )
    return _bwd_common(
        res, g_o, glse3, causal, scale, bwd_block_q, bwd_block_k, interpret
    )


_flash_stats.defvjp(_flash_stats_fwd_rule, _flash_stats_bwd_rule)


def _prep(q, k, causal, scale, kv_mask, block_q, block_k, bwd_block_q,
          bwd_block_k, interpret):
    """Shared public-wrapper normalization: defaults, validation, tile
    picking, mask encoding — one place so the two entry points cannot
    diverge."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    tq, tk = q.shape[1], k.shape[1]
    if causal and tq != tk:
        raise ValueError(f"causal requires square attention, got {tq=} {tk=}")
    block_q = _pick_block(tq, block_q or DEFAULT_BLOCK_Q)
    block_k = _pick_block(tk, block_k or DEFAULT_BLOCK_K)
    if _merged_bwd_fits(tk, q.shape[-1]):
        # Merged backward: forward-size tiles (fastest measured).
        dq_want, dk_want = block_q, block_k
    else:
        # Streaming-K backward: its swept optimum, with block_k scaled
        # up at extreme T so the dQ partial buffer ([bh, tk/block_k,
        # tq, d] f32) stays near 8 K blocks' worth — the fallback must
        # not trade a VMEM wall for an HBM one — but capped so the
        # (block_k, d) f32 scratch pair itself stays well inside the
        # raised VMEM grant.  Contexts this long are really the sp
        # ring axis's job (O(T/ring) per chip); this just keeps
        # single-chip correctness available as far as HBM allows.
        dq_want = _STREAMK_BWD_BLOCK_Q
        dk_want = min(max(_STREAMK_BWD_BLOCK_K, tk // 8), 16384)
    bwd_block_q = _pick_block(tq, bwd_block_q or dq_want)
    bwd_block_k = _pick_block(tk, bwd_block_k or dk_want)
    mask = None if kv_mask is None else kv_mask.astype(jnp.int32)[:, None, :]
    return (mask, causal, scale, block_q, block_k, bwd_block_q,
            bwd_block_k, interpret)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    kv_mask: Optional[jax.Array] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    bwd_block_q: Optional[int] = None,
    bwd_block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention over [B, T, H, D] tensors.

    ``kv_mask``: optional [B, Tk] bool (True = attend) for padded
    batches.  ``bwd_block_q``/``bwd_block_k`` tile the backward
    independently (it carries dK/dV scratch, so its VMEM ceiling —
    and sweet spot — differ from the forward's): up to T=16384 the
    merged backward runs at the forward tiles under a per-shape
    raised VMEM limit (``_vmem_limit``); beyond, the streaming-K
    backward runs at its swept optimum (256 x 2048, block_k scaled so
    its dQ-partials buffer stays bounded).  ``interpret=None``
    auto-selects: real kernel on TPU, Pallas interpreter elsewhere
    (tests on the CPU mesh take this path)."""
    return _flash(
        q, k, v,
        *_prep(q, k, causal, scale, kv_mask, block_q, block_k,
               bwd_block_q, bwd_block_k, interpret),
    )


def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    kv_mask: Optional[jax.Array] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    bwd_block_q: Optional[int] = None,
    bwd_block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """``flash_attention`` that also returns the per-row logsumexp.

    Returns (o [B, Tq, H, D], lse [B, H, Tq] f32).  The lse output is
    DIFFERENTIABLE — its cotangent folds into the backward kernel's
    delta term (d lse / d s = p) at no extra passes — which is what a
    blockwise combiner needs: ``ring_attention`` merges per-ring-step
    normalized partials as o = sum_i w_i o_i with w_i = exp(lse_i -
    logsumexp_i lse_i), and gradients flow through both o_i and lse_i."""
    return _flash_stats(
        q, k, v,
        *_prep(q, k, causal, scale, kv_mask, block_q, block_k,
               bwd_block_q, bwd_block_k, interpret),
    )
