"""Flash attention as a Pallas TPU kernel.

The single-device attention hot path: blockwise online-softmax so the
[T, T] score matrix never materializes in HBM — scores live in VMEM one
(block_q x block_k) tile at a time, matmuls hit the MXU in f32
accumulation, and causal runs skip fully-masked K blocks entirely.

Layering: ``ring_attention`` (sequence parallel, ``ops/ring_attention``)
distributes the sequence *across chips*; this kernel optimizes the
*within-chip* block loop.  They compose: the ring's per-step local
attention is exactly this computation.

Backward: ``jax.custom_vjp`` with a recompute backward (standard
flash-attention practice — residuals are O(T) stats, not O(T^2)
scores); the backward math is expressed in plain jnp and fuses under
XLA.  On non-TPU backends the kernel runs in Pallas interpret mode, so
tests validate the identical code path on the CPU mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _pick_block(t: int, want: int) -> int:
    """Largest divisor of ``t`` that is <= want (prefers want itself)."""
    b = min(want, t)
    while t % b != 0:
        b -= 1
    return b


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_k, seq_len):
    qb = q_ref[0].astype(jnp.float32) * scale  # [block_q, D]
    block_q = qb.shape[0]
    i = pl.program_id(1)
    num_k = seq_len // block_k

    q_pos = i * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0
    )  # [bq, 1]

    def body(j, carry):
        acc, m, l = carry
        kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            qb,
            kb,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bq, bk]
        if causal:
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1
            )
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))  # [bq,1]
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)  # [bq,1]
        l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p,
            vb,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc = acc * alpha + pv
        return acc, m_new, l

    if causal:
        # K blocks whose start exceeds this Q block's last position are
        # fully masked: skip them (the flash speedup for causal).
        upper = jnp.minimum(num_k, pl.cdiv((i + 1) * block_q, block_k))
    else:
        upper = num_k

    d = q_ref.shape[-1]
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, upper, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-20)).astype(o_ref.dtype)


def _flash_fwd_3d(q, k, v, causal, scale, block_q, block_k, interpret):
    """q, k, v: [BH, T, D] -> [BH, T, D]."""
    bh, t, d = q.shape
    kernel = functools.partial(
        _fwd_kernel,
        scale=scale,
        causal=causal,
        block_k=block_k,
        seq_len=t,
    )
    grid = (bh, t // block_q)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=interpret,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _run(q, k, v, causal, scale, block_q, block_k, interpret)


def _run(q, k, v, causal, scale, block_q, block_k, interpret):
    b, t, h, d = q.shape
    to3 = lambda x: x.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    out = _flash_fwd_3d(
        to3(q), to3(k), to3(v), causal, scale, block_q, block_k, interpret
    )
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _flash_ref(q, k, v, causal, scale):
    """Recompute oracle for the backward pass (plain jnp; XLA fuses)."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w.astype(v.dtype), v)


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _run(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_bwd_rule(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _flash_ref(q, k, v, causal, scale), q, k, v)
    return vjp(g.astype(jnp.float32) if g.dtype != q.dtype else g)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention over [B, T, H, D] tensors.

    ``interpret=None`` auto-selects: real kernel on TPU, Pallas
    interpreter elsewhere (tests on the CPU mesh take this path)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    t = q.shape[1]
    block_q = _pick_block(t, block_q)
    block_k = _pick_block(t, block_k)
    return _flash(q, k, v, causal, scale, block_q, block_k, interpret)
