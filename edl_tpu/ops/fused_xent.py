"""Fused tied-vocab softmax cross entropy as Pallas TPU kernels.

The chunked jnp path (``losses.tied_vocab_xent``) still materializes
each chunk's [rows, V] f32 logits in HBM and reads them back for the
logsumexp / gather — at 32k vocab that traffic (~8GB/step at batch 64)
is the loss's real cost, not its FLOPs.  These kernels stream vocab
tiles through VMEM flash-attention-style: the forward computes online
max/sum-exp plus the label logit per row tile-by-tile (logits never
leave VMEM), and the backward recomputes each tile once to produce dY
and dE.  HBM traffic drops to the embedding table re-reads (~0.8GB).

Numerics match the jnp path: logits are bf16xbf16->f32 MXU dots, the
online softmax stats are f32, gradients accumulate f32.

Layout notes (TPU tiling): per-row scalars (lse, label logit, row max,
row scale) travel as [N, STAT_LANES] broadcast arrays — broadcast over
a few trailing lanes keeps every tile a legal (sublane, lane) shape
without paying the full 128-lane residual in HBM (ADVICE r3); labels
ride as [N, 1] int32.  The vocab axis is padded to a multiple of the v-tile
and masked with NEG_INF inside the kernel.

Used by the models' loss functions on TPU; the jnp chunked path stays
as the oracle and the non-TPU fallback (the interpreter would add
nothing on CPU).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

# Hard dependency: every kernel here uses pltpu.VMEM scratch (a clear
# import error beats an AttributeError deep inside a custom_vjp).
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
STAT_LANES = 8


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# forward: per-row (lse, label_logit, row_max)
# ---------------------------------------------------------------------------


def _fwd_kernel(
    y_ref, e_ref, lab_ref, o_lse, o_label, o_max,
    m_scr, l_scr, lab_scr,
    *, block_v, vocab, num_v,
):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        lab_scr[...] = jnp.zeros(lab_scr.shape, jnp.float32)

    yb = y_ref[...]  # [bn, D] bf16
    eb = e_ref[...]  # [bv, D] bf16
    logits = jax.lax.dot_general(
        yb, eb,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bn, bv]
    v_pos = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, 1
    )
    logits = jnp.where(v_pos < vocab, logits, NEG_INF)

    lab = lab_ref[...]  # [bn, 1] int32
    onehot = v_pos == lab  # [bn, bv]
    lab_scr[...] += jnp.sum(
        jnp.where(onehot, logits, 0.0), axis=1, keepdims=True
    )

    m_prev = m_scr[...]  # [bn, 1]
    m_new = jnp.maximum(m_prev, jnp.max(logits, axis=1, keepdims=True))
    # all-NEG_INF guard (can't happen with vocab >= 1, kept for safety)
    m_use = jnp.where(m_new <= NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(logits - m_use)
    alpha = jnp.exp(jnp.where(m_prev <= NEG_INF / 2, 0.0, m_prev) - m_use)
    l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
    m_scr[...] = m_new

    @pl.when(j == num_v - 1)
    def _emit():
        bn = m_scr.shape[0]
        m = jnp.where(m_scr[...] <= NEG_INF / 2, 0.0, m_scr[...])
        lse = m + jnp.log(jnp.maximum(l_scr[...], 1e-30))
        o_lse[...] = jax.lax.broadcast_in_dim(
            lse.reshape(bn), (bn, STAT_LANES), (0,)
        )
        o_label[...] = jax.lax.broadcast_in_dim(
            lab_scr[...].reshape(bn), (bn, STAT_LANES), (0,)
        )
        o_max[...] = jax.lax.broadcast_in_dim(
            m.reshape(bn), (bn, STAT_LANES), (0,)
        )


def _fwd(y, e_pad, labels, vocab, block_n, block_v):
    n, d = y.shape
    vp = e_pad.shape[0]
    num_v = vp // block_v
    kernel = functools.partial(
        _fwd_kernel, block_v=block_v, vocab=vocab, num_v=num_v
    )
    grid = (n // block_n, num_v)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_n, STAT_LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, STAT_LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, STAT_LANES), lambda i, j: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, STAT_LANES), jnp.float32),
            jax.ShapeDtypeStruct((n, STAT_LANES), jnp.float32),
            jax.ShapeDtypeStruct((n, STAT_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
            pltpu.VMEM((block_n, 1), jnp.float32),
        ],
        interpret=jax.default_backend() != "tpu",
    )(y, e_pad, labels)
    return out  # lse3, label3, max3 (each [N, STAT_LANES])


# ---------------------------------------------------------------------------
# backward: dY and dE
# ---------------------------------------------------------------------------


def _dy_kernel(
    y_ref, e_ref, lab_ref, lse_ref, scale_ref, dy_ref, acc_scr,
    *, block_v, vocab, num_v,
):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    yb = y_ref[...]
    eb = e_ref[...]
    logits = jax.lax.dot_general(
        yb, eb,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    v_pos = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, 1
    )
    logits = jnp.where(v_pos < vocab, logits, NEG_INF)
    lse = jnp.max(lse_ref[...], axis=1, keepdims=True)  # [bn, 1]
    scale = jnp.max(scale_ref[...], axis=1, keepdims=True)
    p = jnp.exp(logits - lse)
    onehot = (v_pos == lab_ref[...]).astype(jnp.float32)
    dl = ((p - onehot) * scale).astype(eb.dtype)  # [bn, bv]
    acc_scr[...] += jax.lax.dot_general(
        dl, eb,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == num_v - 1)
    def _emit():
        dy_ref[...] = acc_scr[...].astype(dy_ref.dtype)


def _de_kernel(
    y_ref, e_ref, lab_ref, lse_ref, scale_ref, de_ref, acc_scr,
    *, block_n, vocab, block_v, num_n,
):
    j = pl.program_id(0)  # vocab tile (major: e block stays resident)
    i = pl.program_id(1)  # row tile

    @pl.when(i == 0)
    def _init():
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    yb = y_ref[...]  # [bn, D]
    eb = e_ref[...]  # [bv, D]
    logits = jax.lax.dot_general(
        yb, eb,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bn, bv]
    v_pos = j * block_v + jax.lax.broadcasted_iota(
        jnp.int32, logits.shape, 1
    )
    logits = jnp.where(v_pos < vocab, logits, NEG_INF)
    lse = jnp.max(lse_ref[...], axis=1, keepdims=True)
    scale = jnp.max(scale_ref[...], axis=1, keepdims=True)
    p = jnp.exp(logits - lse)
    onehot = (v_pos == lab_ref[...]).astype(jnp.float32)
    dl = ((p - onehot) * scale).astype(yb.dtype)  # [bn, bv]
    acc_scr[...] += jax.lax.dot_general(
        dl, yb,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bv, D]

    @pl.when(i == num_n - 1)
    def _emit():
        de_ref[...] = acc_scr[...].astype(de_ref.dtype)


def _bwd(y, e_pad, labels, lse3, row_scale3, vocab, block_n, block_v):
    n, d = y.shape
    vp = e_pad.shape[0]
    num_v = vp // block_v
    num_n = n // block_n
    interpret = jax.default_backend() != "tpu"

    dy = pl.pallas_call(
        functools.partial(
            _dy_kernel, block_v=block_v, vocab=vocab, num_v=num_v
        ),
        grid=(num_n, num_v),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
            pl.BlockSpec((block_v, d), lambda i, j: (j, 0)),
            pl.BlockSpec((block_n, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, STAT_LANES), lambda i, j: (i, 0)),
            pl.BlockSpec((block_n, STAT_LANES), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_n, d), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), y.dtype),
        scratch_shapes=[pltpu.VMEM((block_n, d), jnp.float32)],
        interpret=interpret,
    )(y, e_pad, labels, lse3, row_scale3)

    de = pl.pallas_call(
        functools.partial(
            _de_kernel,
            block_n=block_n, vocab=vocab, block_v=block_v, num_n=num_n,
        ),
        grid=(num_v, num_n),
        in_specs=[
            pl.BlockSpec((block_n, d), lambda j, i: (i, 0)),
            pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
            pl.BlockSpec((block_n, 1), lambda j, i: (i, 0)),
            pl.BlockSpec((block_n, STAT_LANES), lambda j, i: (i, 0)),
            pl.BlockSpec((block_n, STAT_LANES), lambda j, i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_v, d), lambda j, i: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((vp, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((block_v, d), jnp.float32)],
        interpret=interpret,
    )(y, e_pad, labels, lse3, row_scale3)
    return dy, de[:vocab]


# ---------------------------------------------------------------------------
# custom_vjp wrapper
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _xent(y, emb, labels2, valid, denom, vocab, block_n, block_v):
    out, _ = _xent_fwd_impl(
        y, emb, labels2, valid, denom, vocab, block_n, block_v
    )
    return out


def _xent_fwd_impl(y, emb, labels2, valid, denom, vocab, block_n, block_v):
    # Pad + cast INSIDE the vjp boundary: emb stays f32 at the custom_vjp
    # interface so dE comes back f32 (cotangent dtype must match primal).
    vp = _ceil_to(vocab, block_v)
    e_pad = emb.astype(jnp.bfloat16)
    if vp != vocab:
        e_pad = jnp.pad(e_pad, ((0, vp - vocab), (0, 0)))
    lse3, label3, max3 = _fwd(y, e_pad, labels2, vocab, block_n, block_v)
    lse = lse3[:, 0]
    label_logit = label3[:, 0]
    nll = (lse - label_logit) * valid
    loss = nll.sum() / denom
    correct = (
        (label_logit >= max3[:, 0]) & (valid > 0)
    ).astype(jnp.float32)
    acc = correct.sum() / denom
    return (loss, acc), (y, emb, labels2, valid, denom, lse3)


def _xent_fwd_rule(y, emb, labels2, valid, denom, vocab, block_n, block_v):
    return _xent_fwd_impl(
        y, emb, labels2, valid, denom, vocab, block_n, block_v
    )


def _xent_bwd_rule(vocab, block_n, block_v, res, g):
    y, emb, labels2, valid, denom, lse3 = res
    g_loss, _g_acc = g  # accuracy is a metric: no gradient flows
    vp = _ceil_to(vocab, block_v)
    e_pad = emb.astype(jnp.bfloat16)
    if vp != vocab:
        e_pad = jnp.pad(e_pad, ((0, vp - vocab), (0, 0)))
    row_scale = (g_loss * valid / denom).astype(jnp.float32)  # [N]
    row_scale3 = jax.lax.broadcast_in_dim(
        row_scale, (row_scale.shape[0], STAT_LANES), (0,)
    )
    dy, de = _bwd(
        y, e_pad, labels2, lse3, row_scale3, vocab, block_n, block_v
    )
    return (
        dy.astype(y.dtype),
        de.astype(emb.dtype),
        np.zeros(labels2.shape, dtype=jax.dtypes.float0),
        jnp.zeros_like(valid),
        jnp.zeros_like(denom),
    )


_xent.defvjp(_xent_fwd_rule, _xent_bwd_rule)


def fused_vocab_xent(
    features: jax.Array,
    embedding: jax.Array,
    labels: jax.Array,
    valid: jax.Array,
    block_rows: int = 1024,
    block_vocab: int = 1024,
) -> Tuple[jax.Array, jax.Array]:
    """Drop-in fused equivalent of ``losses.tied_vocab_xent``.

    features [B, T, D], embedding [V, D], labels [B, T] int32,
    valid [B, T] -> (mean_nll, mean_accuracy) over valid tokens.
    """
    b, t, d = features.shape
    vocab = embedding.shape[0]
    n = b * t
    if d > 512:
        # Keep each kernel's tiles + f32 accumulator + pipeline double
        # buffers inside the ~16MB scoped-VMEM budget at wide d_model.
        block_rows = min(block_rows, 512)
        block_vocab = min(block_vocab, 512)
    # bf16 operands into the MXU dots (f32 accumulation in-kernel) —
    # same compute contract as the jnp path's einsum.
    y = features.reshape(n, d).astype(jnp.bfloat16)
    lab = labels.reshape(n).astype(jnp.int32)
    val = valid.reshape(n).astype(jnp.float32)
    block_rows = min(block_rows, _ceil_to(n, 8))
    pad_n = _ceil_to(n, block_rows) - n
    if pad_n:
        y = jnp.pad(y, ((0, pad_n), (0, 0)))
        # padded rows point at label 0 with valid 0: contribute nothing
        lab = jnp.pad(lab, (0, pad_n))
        val = jnp.pad(val, (0, pad_n))
    denom = jnp.maximum(val.sum(), 1.0)
    loss, acc = _xent(
        y,
        embedding,
        lab[:, None],
        val,
        denom,
        vocab,
        block_rows,
        block_vocab,
    )
    return loss, acc
