"""Ring attention: exact attention over sequences sharded across devices.

Long-context path: a sequence too long for one device's HBM is sharded
over the mesh's ``sp`` axis.  Each device keeps its Q shard resident
and the K/V shards *rotate* around the ring via ``lax.ppermute`` (one
ICI hop per step — neighbor exchanges, the cheapest collective there
is), while a blockwise online-softmax accumulates exact results
(numerically identical to full attention up to float reassociation).

This is the standard public recipe (Ring Attention / blockwise
parallel attention; see PAPERS.md) implemented jax-natively with
``shard_map`` — communication overlaps compute because each step's
matmuls and the next block's ppermute are independent in XLA's
schedule.

The reference system has nothing like this (SURVEY.md §5.7: 2018-era,
pre-dates sequence parallelism entirely); it is required for the
long-context capability bar of the TPU rebuild.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def _block_attn(q, k, v, scale, mask):
    """Scores + masked softmax stats for one (Q block, K/V block) pair.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D]; mask: [Tq, Tk] bool or None.
    Returns (o_unnorm [B,Tq,H,D], m [B,H,Tq], l [B,H,Tq]) — f32 stats.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)  # [B,H,Tq]
    # guard fully-masked rows: exp(NEG_INF - NEG_INF) would be 1
    m_safe = jnp.where(m <= NEG_INF / 2, 0.0, m)
    p = jnp.exp(s - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)  # [B,H,Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return o.astype(jnp.float32), m_safe, l


def _merge(o1, m1, l1, o2, m2, l2):
    """Merge two online-softmax partials (flash-attention combine)."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    l = l1 * a1 + l2 * a2
    o = o1 * a1.transpose(0, 2, 1)[..., None] + o2 * a2.transpose(0, 2, 1)[..., None]
    return o, m, l


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention with sequence sharded over ``mesh`` axis ``axis``.

    q, k, v: [B, T, H, D] with T sharded over ``axis`` (global arrays).
    Returns [B, T, H, D], same sharding.  ``causal`` applies a global
    causal mask (each device resolves its shard's absolute positions
    from its ring rank).
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if axis not in mesh.axis_names:
        return reference_attention(q, k, v, causal=causal, scale=scale)
    n = dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1)
    t_local = q.shape[1] // n

    # Batch stays sharded over the data axes present; sequence over the
    # ring axis.  Heads/head_dim replicated (tp composes by sharding H
    # outside this op).  Axes that don't divide the (static) batch size
    # are dropped — e.g. module.init traces with batch 1.
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    data_axes: list = []
    prod = 1
    for a in ("dp", "fsdp"):
        if a in sizes and q.shape[0] % (prod * sizes[a]) == 0:
            data_axes.append(a)
            prod *= sizes[a]
    bspec = (
        tuple(data_axes)
        if len(data_axes) > 1
        else (data_axes[0] if data_axes else None)
    )
    spec = P(bspec, axis, None, None)

    def local_fn(q_blk, k_blk, v_blk):
        rank = lax.axis_index(axis)
        q_pos = rank * t_local + jnp.arange(t_local)  # absolute Q positions

        def mask_for(src_rank):
            if not causal:
                return None
            k_pos = src_rank * t_local + jnp.arange(t_local)
            return q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]

        # step 0: attend to the locally-resident K/V block
        o, m, l = _block_attn(q_blk, k_blk, v_blk, scale, mask_for(rank))

        if n > 1:
            perm = [(i, (i + 1) % n) for i in range(n)]

            def body(t, carry):
                o, m, l, k_cur, v_cur = carry
                k_cur = lax.ppermute(k_cur, axis, perm)
                v_cur = lax.ppermute(v_cur, axis, perm)
                # after t+1 hops, this device holds the block that
                # originated at ring rank (rank - t - 1) mod n
                src = (rank - t - 1) % n
                if causal:
                    k_pos = src * t_local + jnp.arange(t_local)
                    blk_mask = q_pos[:, None] >= k_pos[None, :]
                else:
                    blk_mask = None
                o2, m2, l2 = _block_attn(q_blk, k_cur, v_cur, scale, blk_mask)
                o, m, l = _merge(o, m, l, o2, m2, l2)
                return (o, m, l, k_cur, v_cur)

            o, m, l, _, _ = lax.fori_loop(
                0, n - 1, body, (o, m, l, k_blk, v_blk)
            )

        denom = jnp.maximum(l, 1e-20).transpose(0, 2, 1)[..., None]
        return (o / denom).astype(q_blk.dtype)

    kwargs = dict(mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    try:  # jax >= 0.8 renamed check_rep -> check_vma
        fn = shard_map(local_fn, check_vma=False, **kwargs)
    except TypeError:  # pragma: no cover - older jax
        fn = shard_map(local_fn, check_rep=False, **kwargs)
    return fn(q, k, v)


def reference_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False,
    scale: Optional[float] = None, kv_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Single-device exact attention (the correctness oracle).

    ``kv_mask``: optional [B, Tk] bool (True = attend) for padded
    batches — same contract as ``flash_attention``."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    # f32 MXU accumulation straight out of the dot: without it the
    # scores materialize in the input dtype and get re-written as f32
    # by the softmax cast — one extra full [B,H,T,T] HBM pass.
    s = (
        jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        )
        * scale
    )
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w.astype(v.dtype), v)
