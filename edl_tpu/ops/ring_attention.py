"""Ring attention: exact attention over sequences sharded across devices.

Long-context path: a sequence too long for one device's HBM is sharded
over the mesh's ``sp`` axis.  Each device keeps its Q shard resident
and the K/V shards *rotate* around the ring via ``lax.ppermute`` (one
ICI hop per step — neighbor exchanges, the cheapest collective there
is), while a blockwise online-softmax accumulates exact results
(numerically identical to full attention up to float reassociation).

This is the standard public recipe (Ring Attention / blockwise
parallel attention; see PAPERS.md) implemented jax-natively with
``shard_map`` — communication overlaps compute because each step's
matmuls and the next block's ppermute are independent in XLA's
schedule.  Each ring step's LOCAL attention is the Pallas flash kernel
(``ops/flash_attention``), composed through its differentiable lse
output: scores never materialize in HBM on either level, and causal
runs skip entirely-future blocks at ring granularity (each device
computes rank+1 of n block pairs; a zigzag/striped layout that
rebalances the skip savings across ranks is a known extension).

The reference system has nothing like this (SURVEY.md §5.7: 2018-era,
pre-dates sequence parallelism entirely); it is required for the
long-context capability bar of the TPU rebuild.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def _local_attn(q, k, v, scale, causal):
    """One (Q block, K/V block) local attention on the Pallas flash
    kernel (``ops/flash_attention``): the ring distributes the sequence
    across chips, the kernel optimizes the within-chip block loop, and
    the two compose through the kernel's differentiable lse output.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D].
    Returns (o [B,Tq,H,D] f32 normalized, lse [B,H,Tq] f32)."""
    from edl_tpu.ops.flash_attention import flash_attention_with_lse

    o, lse = flash_attention_with_lse(q, k, v, causal=causal, scale=scale)
    return o.astype(jnp.float32), lse


def _merge_norm(o1, lse1, o2, lse2):
    """Merge two NORMALIZED softmax partials: o = w1*o1 + w2*o2 with
    w_i = exp(lse_i - logaddexp(lse1, lse2)).  Safe against a partial
    whose block was fully masked (lse == NEG_INF -> weight 0)."""
    m = jnp.maximum(lse1, lse2)
    m = jnp.where(m <= NEG_INF / 2, 0.0, m)  # both-empty guard
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    denom = jnp.maximum(w1 + w2, 1e-30)
    wt1 = (w1 / denom).transpose(0, 2, 1)[..., None]  # [B,Tq,H,1]
    wt2 = (w2 / denom).transpose(0, 2, 1)[..., None]
    o = o1 * wt1 + o2 * wt2
    return o, m + jnp.log(denom)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    scale: Optional[float] = None,
) -> jax.Array:
    """Exact attention with sequence sharded over ``mesh`` axis ``axis``.

    q, k, v: [B, T, H, D] with T sharded over ``axis`` (global arrays).
    Returns [B, T, H, D], same sharding.  ``causal`` applies a global
    causal mask (each device resolves its shard's absolute positions
    from its ring rank).
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if axis not in mesh.axis_names:
        return reference_attention(q, k, v, causal=causal, scale=scale)
    n = dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1)

    # Batch stays sharded over the data axes present; sequence over the
    # ring axis.  Heads/head_dim replicated (tp composes by sharding H
    # outside this op).  Axes that don't divide the (static) batch size
    # are dropped — e.g. module.init traces with batch 1.
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    data_axes: list = []
    prod = 1
    for a in ("dp", "fsdp"):
        if a in sizes and q.shape[0] % (prod * sizes[a]) == 0:
            data_axes.append(a)
            prod *= sizes[a]
    bspec = (
        tuple(data_axes)
        if len(data_axes) > 1
        else (data_axes[0] if data_axes else None)
    )
    spec = P(bspec, axis, None, None)

    def local_fn(q_blk, k_blk, v_blk):
        rank = lax.axis_index(axis)

        # step 0: the locally-resident K/V block — same-origin, so the
        # causal mask is the kernel's ordinary within-block causal.
        o, lse = _local_attn(q_blk, k_blk, v_blk, scale, causal=causal)

        if n > 1:
            perm = [(i, (i + 1) % n) for i in range(n)]

            def body(t, carry):
                o, lse, k_cur, v_cur = carry
                k_cur = lax.ppermute(k_cur, axis, perm)
                v_cur = lax.ppermute(v_cur, axis, perm)
                # after t+1 hops, this device holds the block that
                # originated at ring rank (rank - t - 1) mod n
                src = (rank - t - 1) % n
                if causal:
                    # src != rank in the rotation, so a visiting block
                    # is either entirely in the past (src < rank:
                    # attend unmasked) or entirely in the future
                    # (skip the matmuls altogether — the causal flash
                    # speedup, lifted to ring granularity).  Weight 0
                    # in the merge keeps the skip exact.
                    o2, lse2 = lax.cond(
                        src < rank,
                        lambda ops: _local_attn(
                            q_blk, ops[0], ops[1], scale, causal=False
                        ),
                        lambda ops: (
                            jnp.zeros_like(o),
                            jnp.full(lse.shape, NEG_INF, jnp.float32),
                        ),
                        (k_cur, v_cur),
                    )
                else:
                    o2, lse2 = _local_attn(
                        q_blk, k_cur, v_cur, scale, causal=False
                    )
                o, lse = _merge_norm(o, lse, o2, lse2)
                return (o, lse, k_cur, v_cur)

            o, lse, _, _ = lax.fori_loop(
                0, n - 1, body, (o, lse, k_blk, v_blk)
            )

        return o.astype(q_blk.dtype)

    kwargs = dict(mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    try:  # jax >= 0.8 renamed check_rep -> check_vma
        fn = shard_map(local_fn, check_vma=False, **kwargs)
    except TypeError:  # pragma: no cover - older jax
        fn = shard_map(local_fn, check_rep=False, **kwargs)
    return fn(q, k, v)


def reference_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False,
    scale: Optional[float] = None, kv_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Single-device exact attention (the correctness oracle).

    ``kv_mask``: optional [B, Tk] bool (True = attend) for padded
    batches — same contract as ``flash_attention``."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    # f32 MXU accumulation straight out of the dot: without it the
    # scores materialize in the input dtype and get re-written as f32
    # by the softmax cast — one extra full [B,H,T,T] HBM pass.
    s = (
        jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        )
        * scale
    )
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w.astype(v.dtype), v)
