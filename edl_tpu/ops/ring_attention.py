"""Ring attention: exact attention over sequences sharded across devices.

Long-context path: a sequence too long for one device's HBM is sharded
over the mesh's ``sp`` axis.  Each device keeps its Q shard resident
and the K/V shards *rotate* around the ring via ``lax.ppermute`` (one
ICI hop per step — neighbor exchanges, the cheapest collective there
is), while a blockwise online-softmax accumulates exact results
(numerically identical to full attention up to float reassociation).

This is the standard public recipe (Ring Attention / blockwise
parallel attention; see PAPERS.md) implemented jax-natively with
``shard_map`` — communication overlaps compute because each step's
matmuls and the next block's ppermute are independent in XLA's
schedule.  Each ring step's LOCAL attention is the Pallas flash kernel
(``ops/flash_attention``), composed through its differentiable lse
output: scores never materialize in HBM on either level, and causal
runs skip entirely-future blocks at ring granularity.  Causal rings
default to the ZIGZAG layout (device r holds sequence stripes r and
2n-1-r) so the skip savings balance exactly across ranks — on the
plain contiguous layout rank n-1 does n times rank 0's work and gates
every ppermute (see ``_zigzag_ring``).

The reference system has nothing like this (SURVEY.md §5.7: 2018-era,
pre-dates sequence parallelism entirely); it is required for the
long-context capability bar of the TPU rebuild.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

NEG_INF = -1e30


def _local_attn(q, k, v, scale, causal):
    """One (Q block, K/V block) local attention on the Pallas flash
    kernel (``ops/flash_attention``): the ring distributes the sequence
    across chips, the kernel optimizes the within-chip block loop, and
    the two compose through the kernel's differentiable lse output.

    q: [B, Tq, H, D]; k, v: [B, Tk, H, D].
    Returns (o [B,Tq,H,D] f32 normalized, lse [B,H,Tq] f32)."""
    from edl_tpu.ops.flash_attention import flash_attention_with_lse

    o, lse = flash_attention_with_lse(q, k, v, causal=causal, scale=scale)
    return o.astype(jnp.float32), lse


def _merge_norm(o1, lse1, o2, lse2):
    """Merge two NORMALIZED softmax partials: o = w1*o1 + w2*o2 with
    w_i = exp(lse_i - logaddexp(lse1, lse2)).  Safe against a partial
    whose block was fully masked (lse == NEG_INF -> weight 0)."""
    both_empty = jnp.maximum(lse1, lse2) <= NEG_INF / 2
    m = jnp.where(both_empty, 0.0, jnp.maximum(lse1, lse2))
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    denom = jnp.maximum(w1 + w2, 1e-30)
    wt1 = (w1 / denom).transpose(0, 2, 1)[..., None]  # [B,Tq,H,1]
    wt2 = (w2 / denom).transpose(0, 2, 1)[..., None]
    o = o1 * wt1 + o2 * wt2
    # A both-empty merge must KEEP weight-zero semantics (lse = NEG_INF,
    # not log(1e-30) ~= -69) so a later merge still assigns it zero
    # weight (ADVICE r4; unreachable in current causal rings — every
    # row sees itself at step 0 — but load-bearing if the combiner is
    # reused with kv masking).
    lse = jnp.where(both_empty, NEG_INF, m + jnp.log(denom))
    return o, lse


def _batch_spec(mesh: Mesh, batch_size: int):
    """Batch-dim sharding over whichever data axes divide it.  Axes
    that don't divide the (static) batch size are dropped — e.g.
    ``module.init`` traces with batch 1.  Heads/head_dim stay
    replicated (tp composes by sharding H outside this op)."""
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    data_axes: list = []
    prod = 1
    for a in ("dp", "fsdp"):
        if a in sizes and batch_size % (prod * sizes[a]) == 0:
            data_axes.append(a)
            prod *= sizes[a]
    return (
        tuple(data_axes)
        if len(data_axes) > 1
        else (data_axes[0] if data_axes else None)
    )


def _shard_mapped(local_fn, mesh, spec, n_in=3):
    kwargs = dict(
        mesh=mesh, in_specs=(spec,) * n_in, out_specs=spec
    )
    try:  # jax >= 0.8 renamed check_rep -> check_vma
        return shard_map(local_fn, check_vma=False, **kwargs)
    except TypeError:  # pragma: no cover - older jax
        return shard_map(local_fn, check_rep=False, **kwargs)


def _zigzag_ring(q, k, v, mesh, axis, n, scale):
    """Causal ring attention on the ZIGZAG layout: device r holds
    sequence stripes ``r`` and ``2n-1-r`` (width T/2n each), so every
    rank's causal work is identical — per ring step each rank attends
    exactly 2 of the 4 (q-stripe, k-stripe) pairs:

    - (qa, ka'): a' = src — full when src < r, skipped when src > r
    - (qa, kb'): b' = 2n-1-src >= n > a = r — always future, skipped
    - (qb, ka'): a' <= n-1 < b = 2n-1-r — always past, attended
    - (qb, kb'): b' < b iff src > r — full when src > r, else skipped

    (step 0, src == r, runs the two stripe diagonals causally plus the
    always-past cross pair).  The permutation into/out of the zigzag
    order is a global take on the sequence dim; XLA lowers it to the
    shard exchange once per call — O(T) traffic vs the ring's O(n*T)."""
    T = q.shape[1]
    s = T // (2 * n)  # stripe width; local shard = 2 stripes
    idx = []
    for r in range(n):
        idx.extend(range(r * s, (r + 1) * s))
        br = 2 * n - 1 - r
        idx.extend(range(br * s, (br + 1) * s))
    zig = jnp.asarray(idx, jnp.int32)  # new position -> old index
    inv = jnp.argsort(zig)  # old position -> new index

    qz = jnp.take(q, zig, axis=1)
    kz = jnp.take(k, zig, axis=1)
    vz = jnp.take(v, zig, axis=1)
    spec = P(_batch_spec(mesh, q.shape[0]), axis, None, None)

    def local_fn(q_blk, k_blk, v_blk):
        rank = lax.axis_index(axis)
        qa, qb = q_blk[:, :s], q_blk[:, s:]

        def halves(x):
            return x[:, :s], x[:, s:]

        ka, kb = halves(k_blk)
        va, vb = halves(v_blk)

        # step 0 (src == rank): both stripe diagonals causal, plus the
        # always-past (qb, ka) cross pair.
        oa, lsea = _local_attn(qa, ka, va, scale, causal=True)
        ob, lseb = _merge_norm(
            *_local_attn(qb, ka, va, scale, causal=False),
            *_local_attn(qb, kb, vb, scale, causal=True),
        )

        if n > 1:
            perm = [(i, (i + 1) % n) for i in range(n)]

            def body(t, carry):
                oa, lsea, ob, lseb, kc, vc = carry
                kc = lax.ppermute(kc, axis, perm)
                vc = lax.ppermute(vc, axis, perm)
                src = (rank - t - 1) % n
                ka_, kb_ = halves(kc)
                va_, vb_ = halves(vc)
                # qb vs the visitor's a-stripe: always past.
                ob, lseb = _merge_norm(
                    ob, lseb,
                    *_local_attn(qb, ka_, va_, scale, causal=False),
                )
                # Exactly one of (qa, ka') / (qb, kb') is visible
                # (balanced work — the zigzag point); both branches
                # share shapes so one cond covers them.
                o_x, lse_x = lax.cond(
                    src < rank,
                    lambda ops: _local_attn(
                        qa, ops[0], ops[2], scale, causal=False
                    ),
                    lambda ops: _local_attn(
                        qb, ops[1], ops[3], scale, causal=False
                    ),
                    (ka_, kb_, va_, vb_),
                )
                na, nlsea = _merge_norm(oa, lsea, o_x, lse_x)
                nb, nlseb = _merge_norm(ob, lseb, o_x, lse_x)
                sel = src < rank
                oa = jnp.where(sel, na, oa)
                lsea = jnp.where(sel, nlsea, lsea)
                ob = jnp.where(sel, ob, nb)
                lseb = jnp.where(sel, lseb, nlseb)
                return (oa, lsea, ob, lseb, kc, vc)

            oa, lsea, ob, lseb, _, _ = lax.fori_loop(
                0, n - 1, body, (oa, lsea, ob, lseb, k_blk, v_blk)
            )

        return jnp.concatenate([oa, ob], axis=1).astype(q_blk.dtype)

    out = _shard_mapped(local_fn, mesh, spec)(qz, kz, vz)
    return jnp.take(out, inv, axis=1)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    scale: Optional[float] = None,
    zigzag: Optional[bool] = None,
) -> jax.Array:
    """Exact attention with sequence sharded over ``mesh`` axis ``axis``.

    q, k, v: [B, T, H, D] with T sharded over ``axis`` (global arrays).
    Returns [B, T, H, D], same sharding.  ``causal`` applies a global
    causal mask (each device resolves its shard's absolute positions
    from its ring rank).

    ``zigzag`` (causal only; default auto): on a plain contiguous
    layout the causal skip is rank-IMBALANCED — rank r computes r+1 of
    n block pairs, so the slowest rank gates every ppermute and the
    skip saves no wall-clock.  The zigzag layout gives device r
    stripes ``r`` and ``2n-1-r`` of the sequence, making every rank's
    visible work identical (each ring step attends exactly 2 of 4
    stripe pairs).  Auto-enabled for causal rings when the local shard
    splits into two stripes; ``zigzag=False`` forces the plain layout.
    """
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if axis not in mesh.axis_names:
        return reference_attention(q, k, v, causal=causal, scale=scale)
    n = dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1)
    # The zigzag layout needs T to split into 2n equal stripes; gate on
    # exact divisibility (floor-division parity would admit T=20, n=8
    # and silently TRUNCATE the output to 16 positions).
    splits = q.shape[1] % (2 * n) == 0 if n > 0 else False
    if zigzag is None:
        zigzag = causal and n > 1 and splits
    if zigzag and causal and n > 1 and splits:
        return _zigzag_ring(q, k, v, mesh, axis, n, scale)

    spec = P(_batch_spec(mesh, q.shape[0]), axis, None, None)

    def local_fn(q_blk, k_blk, v_blk):
        rank = lax.axis_index(axis)

        # step 0: the locally-resident K/V block — same-origin, so the
        # causal mask is the kernel's ordinary within-block causal.
        o, lse = _local_attn(q_blk, k_blk, v_blk, scale, causal=causal)

        if n > 1:
            perm = [(i, (i + 1) % n) for i in range(n)]

            def body(t, carry):
                o, lse, k_cur, v_cur = carry
                k_cur = lax.ppermute(k_cur, axis, perm)
                v_cur = lax.ppermute(v_cur, axis, perm)
                # after t+1 hops, this device holds the block that
                # originated at ring rank (rank - t - 1) mod n
                src = (rank - t - 1) % n
                if causal:
                    # src != rank in the rotation, so a visiting block
                    # is either entirely in the past (src < rank:
                    # attend unmasked) or entirely in the future
                    # (skip the matmuls altogether — the causal flash
                    # speedup, lifted to ring granularity).  Weight 0
                    # in the merge keeps the skip exact.
                    o2, lse2 = lax.cond(
                        src < rank,
                        lambda ops: _local_attn(
                            q_blk, ops[0], ops[1], scale, causal=False
                        ),
                        lambda ops: (
                            jnp.zeros_like(o),
                            jnp.full(lse.shape, NEG_INF, jnp.float32),
                        ),
                        (k_cur, v_cur),
                    )
                else:
                    o2, lse2 = _local_attn(
                        q_blk, k_cur, v_cur, scale, causal=False
                    )
                o, lse = _merge_norm(o, lse, o2, lse2)
                return (o, lse, k_cur, v_cur)

            o, lse, _, _ = lax.fori_loop(
                0, n - 1, body, (o, lse, k_blk, v_blk)
            )

        return o.astype(q_blk.dtype)

    return _shard_mapped(local_fn, mesh, spec)(q, k, v)


def reference_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False,
    scale: Optional[float] = None, kv_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Single-device exact attention (the correctness oracle).

    ``kv_mask``: optional [B, Tk] bool (True = attend) for padded
    batches — same contract as ``flash_attention``."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    # f32 MXU accumulation straight out of the dot: without it the
    # scores materialize in the input dtype and get re-written as f32
    # by the softmax cast — one extra full [B,H,T,T] HBM pass.
    s = (
        jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        )
        * scale
    )
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, NEG_INF)
    if kv_mask is not None:
        s = jnp.where(kv_mask[:, None, None, :], s, NEG_INF)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w.astype(v.dtype), v)
