"""Hot-path ops: ring attention (sequence parallelism) and Pallas TPU
kernels."""

from edl_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_with_lse,
)
from edl_tpu.ops.ring_attention import reference_attention, ring_attention


#: At or above this sequence length attention dispatches to the Pallas
#: flash kernel on TPU.  Re-measured on v5e after the 512x512 tile
#: retune (in-model, fwd+bwd, fixed B*T): flash wins from T=512 up
#: (T=512/B=32: 33.8ms vs XLA 43.5; T=1024/B=16: 37.7 vs 60.9) and is
#: a wash at T=256 (34.2 vs 33.9, XLA marginally ahead).  From T=2048
#: it is also the only path that fits: XLA's [B, H, T, T] f32 scores
#: OOM 16G HBM at training batch sizes.
FLASH_MIN_SEQ_LEN = 512


def fused_attention(q, k, v, causal=False, scale=None, kv_mask=None):
    """Best single-device attention for the current backend/shape: the
    Pallas flash kernel on TPU from moderate context up, XLA's fused
    reference otherwise (the interpreter would be slow on CPU for no
    accuracy gain, and XLA's fusion edges out the kernel at short T).

    ``kv_mask``: optional [B, Tk] bool (True = attend), the padded-batch
    contract shared by both implementations."""
    import jax

    if jax.default_backend() == "tpu" and q.shape[1] >= FLASH_MIN_SEQ_LEN:
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               kv_mask=kv_mask)
    return reference_attention(q, k, v, causal=causal, scale=scale,
                               kv_mask=kv_mask)


__all__ = [
    "ring_attention",
    "flash_attention_with_lse",
    "reference_attention",
    "flash_attention",
    "fused_attention",
]
