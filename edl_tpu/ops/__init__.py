"""Hot-path ops: ring attention (sequence parallelism) and Pallas TPU
kernels."""

from edl_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_with_lse,
)
from edl_tpu.ops.ring_attention import reference_attention, ring_attention


#: At or above this sequence length attention dispatches to the Pallas
#: flash kernel on TPU.  Re-measured on v5e with the blockwise
#: backward: XLA's fused attention is slightly faster fwd+bwd up
#: through T=1024 (both are softmax/VPU-bound at head_dim 64), but its
#: [B, H, T, T] f32 score tensor OOMs 16G HBM from T=2048 at training
#: batch sizes — the crossover is *memory*, and flash is the only
#: path that scales long-context.
FLASH_MIN_SEQ_LEN = 2048


def fused_attention(q, k, v, causal=False, scale=None, kv_mask=None):
    """Best single-device attention for the current backend/shape: the
    Pallas flash kernel on TPU from moderate context up, XLA's fused
    reference otherwise (the interpreter would be slow on CPU for no
    accuracy gain, and XLA's fusion edges out the kernel at short T).

    ``kv_mask``: optional [B, Tk] bool (True = attend), the padded-batch
    contract shared by both implementations."""
    import jax

    if jax.default_backend() == "tpu" and q.shape[1] >= FLASH_MIN_SEQ_LEN:
        return flash_attention(q, k, v, causal=causal, scale=scale,
                               kv_mask=kv_mask)
    return reference_attention(q, k, v, causal=causal, scale=scale,
                               kv_mask=kv_mask)


__all__ = [
    "ring_attention",
    "flash_attention_with_lse",
    "reference_attention",
    "flash_attention",
    "fused_attention",
]
