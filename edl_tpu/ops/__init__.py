"""Hot-path ops: ring attention (sequence parallelism) and Pallas TPU
kernels."""

from edl_tpu.ops.flash_attention import flash_attention
from edl_tpu.ops.ring_attention import reference_attention, ring_attention


#: Below this sequence length XLA's own attention fusion wins on TPU
#: (measured on v5e: reference faster at T<=1024, flash 2.2x faster at
#: 4096 and 45x at 8192 where the [T,T] scores thrash HBM).
FLASH_MIN_SEQ_LEN = 2048


def fused_attention(q, k, v, causal=False, scale=None):
    """Best single-device attention for the current backend/shape: the
    Pallas flash kernel on TPU at long context, XLA's fused reference
    otherwise (the interpreter would be slow on CPU for no accuracy
    gain, and XLA's fusion beats the kernel at short T)."""
    import jax

    if jax.default_backend() == "tpu" and q.shape[1] >= FLASH_MIN_SEQ_LEN:
        return flash_attention(q, k, v, causal=causal, scale=scale)
    return reference_attention(q, k, v, causal=causal, scale=scale)


__all__ = [
    "ring_attention",
    "reference_attention",
    "flash_attention",
    "fused_attention",
]
