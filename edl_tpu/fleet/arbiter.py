"""The cluster arbiter: one chip market, iterated to a fixed point.

This is the reference's cluster-wide dry run (``scaleAllJobsDryRun``,
``pkg/autoscaler.go:296-337``) generalized the way ROADMAP item 2 asks:
N elastic ``TrainingJob`` bidders + M serving-fleet bidders against ONE
TPU inventory, with three deltas the reference could never have:

- **Serving SLOs are hard constraints.**  A serving bid's
  ``required_units`` (the ``ServingLane`` band decision) is a floor the
  market covers BEFORE any training growth — and when the free pool is
  short, by preempting the lowest-priority elastic trainer one legal
  step at a time (the Varuna/Bamboo/Oobleck posture: training churn is
  steady state, and the PR 6 consensus bus made the scale-down safe).
- **Goodput-per-chip is the objective.**  Training growth within a
  priority tier goes to the bid with the best observed
  goodput-per-chip (PR 7's ledger, read back through the coordinator's
  merged telemetry) — measured throughput, not declared ranges, breaks
  ties for the marginal chip.
- **Chips come back.**  When the spike clears (the serving band's
  hysteresis drops its requirement), the serving fleet sheds to its
  requirement and the freed chips flow back to the starved trainers in
  the same fixed point.

``arbitrate`` is a pure function over ``Bid``s (trivially golden-
testable, like ``algorithm.scale_all_jobs_dry_run``); ``FleetArbiter``
is the tick driver that collects bids, arbitrates, actuates each
transition under its own minted trace id (prewarm→retarget; training
scale-downs wait for the consensus victim-drain ack before their chips
move), and journals per-job decision entries + ``fleet.*`` flight
events.

Convergence argument (the oscillation-freedom test pins it): within a
tick the serving requirements are fixed inputs, pass 1 only moves
serving allocations TOWARD their requirement (preempting trainers
downward), and pass 2 only grows training into genuinely free chips
AFTER every requirement is satisfied — so no pass can undo another's
work, every pass strictly reduces a bounded potential (unmet serving
chips, then free chips), and the loop reaches a fixed point in
O(total steps) iterations.  The reference's unbounded loop could
livelock at full utilization; ``max_iters`` caps ours anyway.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Union

from edl_tpu.fleet.bidders import Bid, ServingBidder, TrainingBidder
from edl_tpu.fleet.inventory import ChipInventory


@dataclass
class Arbitration:
    """Outcome of one pure market pass."""

    #: name -> decided unit count (EVERY bid gets an entry)
    targets: Dict[str, int]
    #: chips left unallocated after the fixed point
    free_chips: int
    #: preemption records: lowest-priority trainers stepped down to
    #: cover serving requirements, in decision order
    preemptions: List[dict] = field(default_factory=list)
    #: serving bids whose requirement could NOT be covered even after
    #: exhausting every preemptible trainer: name -> unmet chips
    unmet: Dict[str, int] = field(default_factory=dict)
    iterations: int = 0


def _fulfillment_at(b: Bid, units: int) -> float:
    """Fulfillment against the EVOLVING dry-run allocation (a key
    computed from the stale ``current_units`` would let one job absorb
    the whole free pool / one victim shed to its floor before a peer
    is touched)."""
    if b.min_units >= b.max_units:
        return 1.0
    return (units - b.min_units) / (b.max_units - b.min_units)


def _utility_at(b: Bid, units: int) -> Optional[float]:
    """Goodput-per-chip re-scaled to the evolving allocation: the
    observed ledger fraction is what it is, but the chips dividing it
    grow as the dry run grants steps — the diminishing-returns shape
    that spreads chips instead of feeding one job forever."""
    if b.utility is None:
        return None
    return b.utility * b.current_units / max(1, units)


def _growth_key(b: Bid, units: int):
    """Training growth order: priority tier first, then measured
    goodput-per-chip at the evolving allocation (unmeasured bids sort
    behind measured ones in their tier), then least-fulfilled, then
    name (determinism)."""
    u = _utility_at(b, units)
    return (
        -b.priority,
        0 if u is not None else 1,
        -(u or 0.0),
        _fulfillment_at(b, units),
        b.name,
    )


def _victim_key(b: Bid, units: int):
    """Preemption order: LOWEST priority first, most-fulfilled (at the
    evolving allocation) first — shed from the job farthest above its
    floor, rotating to its peer once they even out — then name."""
    return (b.priority, -_fulfillment_at(b, units), b.name)


def _serving_want(s: Bid) -> int:
    """The units a serving bid's SLO band demands, bounded to its
    [min, max] — THE requirement all three consumers (satisfaction
    pass, growth reservation, unmet report) must agree on."""
    return min(max(s.required_units or s.min_units, s.min_units), s.max_units)


def arbitrate(
    bids: Sequence[Bid],
    total_chips: int,
    max_iters: int = 256,
) -> Arbitration:
    """Iterate allocate/evict to a fixed point over ``total_chips``.

    ``bids``' ``current_units`` seed the allocation (clamped to each
    bid's legal sizes); the returned targets are absolute unit counts.
    Serving requirements are satisfied in priority order before any
    training growth; preemption stops at every trainer's min."""
    bids = list(bids)
    by_name: Dict[str, Bid] = {}
    for b in bids:
        if b.name in by_name:
            raise ValueError(f"duplicate bid name {b.name!r}")
        by_name[b.name] = b
    alloc: Dict[str, int] = {
        b.name: max(b.min_units, b.clamp(b.current_units)) for b in bids
    }
    serving = sorted(
        (b for b in bids if b.kind == "serving"),
        key=lambda b: (-b.priority, b.name),
    )
    training = [b for b in bids if b.kind == "training"]
    free = total_chips - sum(
        alloc[b.name] * b.chips_per_unit for b in bids
    )
    preemptions: List[dict] = []

    def preempt_for(need_chips: int, beneficiary: str) -> int:
        """Shed lowest-priority elastic trainers (one legal step at a
        time) until ``need_chips`` are freed or nothing preemptible is
        left.  Returns chips freed.  Serving requirements are HARD:
        any elastic trainer above its min is a candidate — priority
        only orders who goes first (training growth, by contrast,
        never preempts anyone: it consumes free chips only)."""
        freed = 0
        while freed < need_chips:
            victims = sorted(
                (
                    t
                    for t in training
                    if t.elastic and alloc[t.name] > t.min_units
                ),
                key=lambda t: _victim_key(t, alloc[t.name]),
            )
            if not victims:
                break
            v = victims[0]
            down = v.next_down(alloc[v.name])
            if down is None or down < v.min_units:
                break
            step_chips = (alloc[v.name] - down) * v.chips_per_unit
            preemptions.append(
                {
                    "victim": v.name,
                    "priority": v.priority,
                    "beneficiary": beneficiary,
                    "units_from": alloc[v.name],
                    "units_to": down,
                    "chips_freed": step_chips,
                }
            )
            alloc[v.name] = down
            freed += step_chips
        return freed

    iters = 0
    for iters in range(1, max_iters + 1):
        changed = False

        # -- pass 0: oversubscription (inventory shrank under us) -----------
        while free < 0:
            got = preempt_for(-free, beneficiary="(inventory)")
            free += got
            if got:
                changed = True
            if free < 0 and got == 0:
                # Trainers exhausted: shed serving above min too.
                sheddable = sorted(
                    (s for s in serving if alloc[s.name] > s.min_units),
                    key=lambda s: (s.priority, s.name),
                )
                if not sheddable:
                    break
                s = sheddable[0]
                down = s.next_down(alloc[s.name])
                if down is None:
                    break
                free += (alloc[s.name] - down) * s.chips_per_unit
                alloc[s.name] = down
                changed = True

        # -- pass 1: serving hard constraints, priority order ---------------
        for s in serving:
            want = s.clamp(_serving_want(s))
            # Spike cleared: give chips back down to the requirement.
            while alloc[s.name] > want:
                down = s.next_down(alloc[s.name])
                if down is None or down < want:
                    break
                free += (alloc[s.name] - down) * s.chips_per_unit
                alloc[s.name] = down
                changed = True
            # Spike: grow to the requirement, preempting when short.
            while alloc[s.name] < want:
                up = s.next_up(alloc[s.name])
                if up is None or up > s.max_units:
                    break
                need = (up - alloc[s.name]) * s.chips_per_unit
                if free < need:
                    free += preempt_for(need - free, beneficiary=s.name)
                if free < need:
                    break  # nothing left to evict: requirement unmet
                alloc[s.name] = up
                free -= need
                changed = True

        # -- pass 2: training growth into genuinely free chips --------------
        reserved = sum(
            max(0, (_serving_want(s) - alloc[s.name]) * s.chips_per_unit)
            for s in serving
        )
        # ONE legal step per iteration, to the first bid (strict
        # priority tiers, then goodput-per-chip, then least-fulfilled)
        # whose whole step fits: higher tiers saturate before a lower
        # tier sees a chip ("starved low-priority job" is a designed
        # outcome, not a fairness bug), but a step the leading bid
        # CANNOT take (quantized step bigger than the remaining free)
        # falls through to the next — holding chips no tick can assign
        # is pure waste.  Never eats room an unmet serving requirement
        # is still waiting for.
        for t in sorted(
            training, key=lambda t: _growth_key(t, alloc[t.name])
        ):
            if not t.elastic:
                continue
            up = t.next_up(alloc[t.name])
            if up is None or up > t.max_units:
                continue
            need = (up - alloc[t.name]) * t.chips_per_unit
            if need <= free - reserved:
                alloc[t.name] = up
                free -= need
                changed = True
                break

        if not changed:
            break

    unmet = {}
    for s in serving:
        short = (_serving_want(s) - alloc[s.name]) * s.chips_per_unit
        if short > 0:
            unmet[s.name] = short
    return Arbitration(
        targets=dict(alloc),
        free_chips=free,
        preemptions=preemptions,
        unmet=unmet,
        iterations=iters,
    )


class FleetArbiter:
    """The per-tick market driver.

    ``inventory``: a ``ChipInventory``, an int (total market chips), or
    a zero-arg callable returning either — called every tick so a live
    cluster's inquiry feeds the market fresh
    (``ChipInventory.from_cluster_resource(cluster.inquiry_resource())``
    composed with the non-fleet holding subtraction).

    Ride it on the training autoscaler's 5s tick with
    ``attach_fleet(autoscaler, arbiter)`` (the Pathways shape: one
    control loop owns every workload), or drive ``run_once`` directly.
    """

    def __init__(
        self,
        inventory: Union[ChipInventory, int, Callable],
        trainers: Sequence[TrainingBidder] = (),
        fleets: Sequence[ServingBidder] = (),
        *,
        victim_drain_timeout: float = 20.0,
    ):
        self._inventory_src = inventory
        self.trainers: List[TrainingBidder] = list(trainers)
        self.fleets: List[ServingBidder] = list(fleets)
        self.victim_drain_timeout = victim_drain_timeout
        self.inventory = ChipInventory()
        self.decision_log: List[dict] = []
        self.decision_log_max = 256
        #: tick-indexed chips-over-time series (bounded): one
        #: ``inventory.snapshot()`` per tick — the bench storm's
        #: chips_over_time and the ``edl fleet`` trend read this
        self.history: List[dict] = []
        self.history_max = 512

        from edl_tpu import telemetry

        self._recorder = telemetry.get_recorder()
        reg = telemetry.get_registry()
        self._m_ticks = reg.counter("edl_autoscaler_ticks_total")
        self._m_decisions = reg.counter("edl_fleet_decisions_total")
        self._m_preemptions = reg.counter("edl_fleet_preemptions_total")
        self._g_total = reg.gauge("edl_fleet_chips_total")
        self._g_free = reg.gauge("edl_fleet_chips_free")
        self._g_alloc = reg.gauge("edl_fleet_chips_allocated")
        self._g_target = reg.gauge("edl_fleet_target_units")
        self._g_unmet = reg.gauge("edl_fleet_unmet_demand_chips")

    # -- wiring --------------------------------------------------------------
    def add_trainer(self, bidder: TrainingBidder) -> TrainingBidder:
        self.trainers.append(bidder)
        return bidder

    def add_fleet(self, bidder: ServingBidder) -> ServingBidder:
        self.fleets.append(bidder)
        return bidder

    def _bidders(self) -> list:
        return list(self.trainers) + list(self.fleets)

    def _market_chips(self) -> int:
        src = self._inventory_src
        if callable(src):
            src = src()
        if isinstance(src, ChipInventory):
            mine = {b.name for b in self._bidders()}
            outside = sum(
                h for n, h in src.holdings.items() if n not in mine
            )
            self.inventory.total_chips = src.total_chips
            # Park the non-fleet usage so the snapshot stays honest —
            # including CLEARING holdings the fresh inquiry no longer
            # reports (an outside workload that finished must not
            # haunt chips_over_time as phantom allocation).
            for n in list(self.inventory.holdings):
                if n not in mine and n not in src.holdings:
                    self.inventory.set_holding(n, 0)
            for n, h in src.holdings.items():
                if n not in mine:
                    self.inventory.set_holding(n, h)
            return max(0, src.total_chips - outside)
        self.inventory.total_chips = int(src)
        return int(src)

    # -- one decision cycle ---------------------------------------------------
    def run_once(self) -> Optional[dict]:
        """Collect -> arbitrate -> actuate -> journal.  Returns the
        tick record (None when no bidder was observable)."""
        market_chips = self._market_chips()
        bids: List[Bid] = []
        blind: List[str] = []
        for bidder in self._bidders():
            bid = bidder.collect()
            if bid is None:
                # Unreachable coordinator: its holding is frozen — the
                # market neither grows nor preempts what it can't see.
                # Reserve its LAST-KNOWN holding (the previous tick's
                # actuated allocation, still physically occupied by
                # its pods), floored at min units for a job never yet
                # observed.
                blind.append(bidder.name)
                market_chips -= max(
                    bidder.min_units * bidder.chips_per_unit,
                    self.inventory.holdings.get(bidder.name, 0),
                )
                continue
            bids.append(bid)
        if not bids:
            return None
        self._m_ticks.inc()
        result = arbitrate(bids, market_chips)
        outcome = self._actuate(bids, result)
        record = self._journal(bids, result, outcome, blind)
        return record

    # -- actuation ------------------------------------------------------------
    def _actuate(self, bids: List[Bid], result: Arbitration) -> Dict[str, dict]:
        """Apply the arbitration: every transition gets its OWN minted
        trace id; scale-downs actuate first (training ones wait for the
        consensus victim-drain ack) so the chips a scale-up consumes
        are genuinely free before its retarget lands."""
        from edl_tpu import telemetry

        by_name = {}
        for b in self._bidders():
            by_name[b.name] = b
        diffs = []
        for bid in bids:
            target = result.targets.get(bid.name, bid.current_units)
            if target != bid.current_units:
                diffs.append((bid, target))
        # downs first; training downs before serving downs (the freed
        # training chips are what the serving growth is waiting for)
        diffs.sort(
            key=lambda bt: (
                0 if bt[1] < bt[0].current_units else 1,
                0 if bt[0].kind == "training" else 1,
                bt[0].name,
            )
        )
        outcome: Dict[str, dict] = {}
        for bid, target in diffs:
            bidder = by_name[bid.name]
            trace_id = telemetry.new_trace_id()
            ok = bidder.actuate(target, trace_id)
            # drained is only meaningful for an ACTUATED scale-down; a
            # failed retarget never quiesced anything.
            drained = bool(ok)
            if ok and target < bid.current_units:
                drained = bidder.wait_drain(self.victim_drain_timeout)
            outcome[bid.name] = {
                "actuated": ok,
                "drained": drained,
                "trace_id": trace_id,
            }
        return outcome

    # -- journaling -----------------------------------------------------------
    def _journal(
        self,
        bids: List[Bid],
        result: Arbitration,
        outcome: Dict[str, dict],
        blind: List[str],
    ) -> dict:
        preempted_by = {
            p["victim"]: p["beneficiary"] for p in result.preemptions
        }
        decisions = []
        for bid in bids:
            target = result.targets.get(bid.name, bid.current_units)
            out = outcome.get(bid.name, {})
            diff = target - bid.current_units
            # The recorded holding must track what the pods PHYSICALLY
            # occupy: a transition whose retarget failed leaves the
            # old allocation standing (and the blind-coordinator
            # freeze reserves this holding next tick — recording the
            # unactuated target would fabricate free chips).
            held = (
                target
                if (diff == 0 or out.get("actuated"))
                else bid.current_units
            )
            if bid.name in preempted_by:
                reason = (
                    f"preempted by {preempted_by[bid.name]} "
                    "(serving SLO hard constraint)"
                )
            elif bid.kind == "serving" and bid.name in result.unmet:
                reason = (
                    f"SLO requirement unmet by {result.unmet[bid.name]} "
                    "chips (nothing left to evict)"
                )
            elif diff > 0:
                reason = f"market grants +{diff} units"
            elif diff < 0:
                reason = f"market sheds {-diff} units"
            else:
                reason = "at fixed point"
            entry = {
                "lane": "fleet",
                "job": bid.name,
                "kind": bid.kind,
                "priority": bid.priority,
                "dry_run": {
                    "current": bid.current_units,
                    "proposed": target,
                    "diff": diff,
                },
                "observed": dict(bid.observed),
                "required_units": bid.required_units,
                "utility": bid.utility,
                "preempted": bid.name in preempted_by,
                "preempted_by": preempted_by.get(bid.name),
                "actuated": bool(out.get("actuated")),
                "drained": out.get("drained", True),
                "reason": reason,
                "trace_id": out.get("trace_id", ""),
            }
            decisions.append(entry)
            self.decision_log.append(entry)
            self._m_decisions.inc()
            data = {k: v for k, v in entry.items() if k != "trace_id"}
            self._recorder.record(
                "fleet.decision", data, trace=entry["trace_id"]
            )
            self._g_alloc.set(
                held * bid.chips_per_unit, job=bid.name
            )
            self._g_target.set(target, job=bid.name)
            if bid.kind == "serving":
                self._g_unmet.set(
                    result.unmet.get(bid.name, 0), job=bid.name
                )
            self.inventory.set_holding(
                bid.name, held * bid.chips_per_unit
            )
        del self.decision_log[: -self.decision_log_max]
        for p in result.preemptions:
            self._m_preemptions.inc(job=p["victim"])
            self._recorder.record(
                "fleet.preempt",
                dict(
                    p,
                    victim_trace=outcome.get(p["victim"], {}).get(
                        "trace_id", ""
                    ),
                    beneficiary_trace=outcome.get(
                        p["beneficiary"], {}
                    ).get("trace_id", ""),
                ),
            )
        self._g_total.set(self.inventory.total_chips)
        self._g_free.set(result.free_chips)
        record = {
            "decisions": decisions,
            "preemptions": result.preemptions,
            "unmet": result.unmet,
            "free_chips": result.free_chips,
            "iterations": result.iterations,
            "blind": blind,
            "inventory": self.inventory.snapshot(),
        }
        self.history.append(record["inventory"])
        del self.history[: -self.history_max]
        return record

    # -- the loop -------------------------------------------------------------
    def run(self, stop_event, loop_seconds: float = 5.0) -> None:
        """Tick until ``stop_event`` is set (thread entry)."""
        while not stop_event.wait(loop_seconds):
            try:
                self.run_once()
            except Exception:
                import traceback

                traceback.print_exc()


def attach_fleet(autoscaler, arbiter: FleetArbiter) -> FleetArbiter:
    """Host the arbiter on a training ``Autoscaler``'s 5s tick (the
    same shape as ``attach_serving_lane``): every ``run_once`` of the
    scaler also runs the market, and the market's per-job decisions
    flow into the AUTOSCALER's decision log so ``edl trace`` and
    operators read one journal.  The arbiter supersedes the scaler's
    per-job planning for jobs it owns — don't also register those jobs
    with the single-cluster lane."""
    if getattr(autoscaler, "fleet_arbiter", None) is not None:
        raise ValueError("an arbiter is already attached")
    autoscaler.fleet_arbiter = arbiter
    orig = autoscaler.run_once

    def run_once(*args, **kwargs):
        plan = orig(*args, **kwargs)
        try:
            record = arbiter.run_once()
        except Exception:
            # Keep the scaler tick alive, but NEVER silently: a
            # persistently failing market must not just vanish from
            # the decision log while the autoscaler looks healthy.
            import traceback

            traceback.print_exc()
            record = None
        if record is not None:
            for entry in record["decisions"]:
                autoscaler.decision_log.append(entry)
            del autoscaler.decision_log[: -autoscaler.decision_log_max]
        return plan

    autoscaler.run_once = run_once
    return arbiter
